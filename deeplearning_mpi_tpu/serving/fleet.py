"""Fault-tolerant serving fleet: supervised replicas behind a router.

One :class:`~.engine.ServingEngine` is a single point of failure; ROADMAP
item 1's millions-of-users direction needs N of them behind admission
control. This module runs each replica as a supervised OS process (the
same worker/supervisor split as :mod:`~..resilience.pod`, reusing its
:class:`~..resilience.supervisor.Heartbeat` /
:class:`~..resilience.pod.LivenessTracker` machinery to separate dead,
hung, and merely slow replicas) and fronts them with the
:class:`~.router.Router`'s policy: lowest-load replica selection off the
``serve_*`` telemetry each heartbeat carries, deadline-budgeted hedged
retries against slow replicas, and an exclusion window for the recently
dead.

Robustness contract (drilled by ``tools/fleet_drill.py`` / ``make
fleet-smoke``):

- **Failover re-dispatch.** When a replica dies (exit observed) or wedges
  (heartbeat fresh, ``progress_seq`` frozen — the daemon thread beats
  through a hang), its in-flight requests are re-dispatched *from their
  prompts* to a survivor, carrying their ORIGINAL arrival/deadline
  (`ServingEngine.submit(arrival=...)`) so failover never mints fresh SLO
  budget. Restarting from the prompt is what keeps every completed stream
  bit-identical to offline greedy — the same parity bar as in-process
  ``recover()``.
- **Hedged retries.** A request outstanding past ``hedge_ms`` with budget
  left gets a duplicate on a second replica; first completion wins, the
  loser is cancelled, exactly one stream reaches the client
  (``serve_hedge_total{outcome}`` accounts every case).
- **Hot weight swap.** :meth:`FleetSupervisor.swap_weights` (driven by
  ``run(swap_at=...)``) rolls through the fleet: drain one replica's
  outstanding work (router exclusion — in-flight requests complete, new
  ones go elsewhere), swap its params in place (same shapes/dtypes ⇒ the
  warmed programs retrace nothing; ``serve_compile_total`` must stay
  flat), re-include, next replica. The fleet keeps serving throughout —
  zero downtime, zero dropped requests.

Chaos: ``replica_kill`` / ``replica_hang`` / ``replica_slow``
(:data:`~..resilience.faults.FLEET_KINDS`) detonate inside a worker via
:meth:`ChaosInjector.check_replica_fault`; the supervisor owns their
books (``fire_observed`` on detection, ``record_recovery`` when the
re-dispatched work completes), reconciled under the same
``fault_injected_total == recovery_total + rollback_total`` invariant as
training chaos.

Wire protocol: per-replica append-only JSONL files (``inbox.jsonl``
supervisor→worker, ``outbox.jsonl`` worker→supervisor), single writer
each, readers tail by byte offset and consume only newline-terminated
lines — a mid-write SIGKILL can truncate at most the final, unconsumed
line. Arrival/deadline stamps are absolute ``time.monotonic()`` values:
CLOCK_MONOTONIC is system-wide on Linux, so they survive the process
boundary intact.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from collections import Counter, deque
from pathlib import Path
from typing import Any, Mapping, Optional

from deeplearning_mpi_tpu.resilience.cluster import (
    JOURNAL_FILE,
    SUP_INCARNATION,
    SUP_READOPTED,
    SUP_REPLAY_S,
    SUP_RESPAWNED,
    ClusterSupervisor,
    kill_and_reap,
    pid_alive,
    replay_journal,
    scrub_rendezvous_env,
    tail_jsonl,
)

__all__ = ["FleetFailure", "FleetResult", "FleetSupervisor", "worker_main"]

FLEET_RESTARTS = "fleet_replica_restarts_total"
FLEET_FAILURES = "fleet_replica_failures_total"
FLEET_REDISPATCH = "fleet_redispatch_total"
# Control-plane crash safety (docs/RESILIENCE.md): the incarnation gauge
# and recovery books a restarted supervisor reports after replaying the
# write-ahead journal and probing the dead incarnation's orphans. The
# names live in resilience/cluster.py (shared with PodSupervisor).

# The JSONL-tail reader moved into the unified supervision core
# (resilience/cluster.py); the historical name stays importable here.
_tail_jsonl = tail_jsonl


class FleetFailure(RuntimeError):
    """The fleet cannot meet its contract (restart budget spent, run
    timeout, every replica gone)."""


# ---------------------------------------------------------------------------
# worker (one process per replica)
# ---------------------------------------------------------------------------

def worker_main(argv: list[str] | None = None) -> int:
    """Replica worker: a ServingEngine wrapped in the fleet wire protocol.

    Builds the model/params from the spec file (``model.init`` from the
    spec's seed — replicas of the same (seed, version) are bit-identical
    by construction, which is what makes cross-replica re-dispatch
    parity-safe), warms the engine, then loops: drain inbox ops, step the
    engine when busy, report completions, and publish liveness + the
    telemetry snapshot the router scores on through the heartbeat.
    """
    import argparse

    parser = argparse.ArgumentParser(prog="fleet-worker")
    parser.add_argument("--replica", type=int, required=True)
    parser.add_argument("--dir", required=True)
    parser.add_argument("--spec", required=True)
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
    from deeplearning_mpi_tpu.resilience import ChaosInjector, InjectedFault
    from deeplearning_mpi_tpu.resilience.pod import ENV_HEARTBEAT_INTERVAL
    from deeplearning_mpi_tpu.resilience.supervisor import Heartbeat
    from deeplearning_mpi_tpu.serving.engine import EngineConfig, ServingEngine
    from deeplearning_mpi_tpu.serving.scheduler import RequestState
    from deeplearning_mpi_tpu.telemetry import MetricsRegistry

    rdir = Path(args.dir)
    spec = json.loads(Path(args.spec).read_text())
    # Topology keys ride next to (not inside) the engine kwargs dict.
    disagg = bool(spec.get("disagg", False))
    tp = int(spec.get("tp", 1))
    if tp > 1 and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Hardware-free TP: fake CPU devices, forced BEFORE the first
        # backend use (model.init below initializes it).
        from deeplearning_mpi_tpu.runtime.bootstrap import (
            set_virtual_cpu_devices,
        )

        set_virtual_cpu_devices(tp)
    cfg = TransformerConfig(**spec["model"])
    model = TransformerLM(config=cfg, dtype=jnp.float32)

    param_sharding = None
    if tp > 1:
        from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

        mesh = create_mesh(MeshSpec(data=1, model=tp))

    def init_params(seed: int):
        # EXACTLY the serve_lm --selftest init: the drill's offline-greedy
        # oracle rebuilds params from (config, seed) alone, so any drift
        # here is a parity failure, not a tolerable difference.
        p = model.init(
            jax.random.key(seed), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        if tp > 1:
            # Megatron-style sharded replica: one replica = tp devices.
            # XLA's GSPMD partitioner splits the engine's jitted steps
            # along the param shardings; the serving code is unchanged.
            from deeplearning_mpi_tpu.parallel.tensor_parallel import (
                infer_tp_param_sharding,
            )

            nonlocal param_sharding
            if param_sharding is None:
                param_sharding = infer_tp_param_sharding(p, mesh)
            p = jax.device_put(p, param_sharding)
        return p

    version = int(spec.get("version", 0))
    # Which supervisor incarnation owns this worker. Rides every heartbeat
    # (LivenessTracker rejects records from dead incarnations) and is
    # updated in place by the `adopt` handshake when a restarted
    # supervisor claims this orphan.
    incarnation = int(spec.get("incarnation", 0))
    params = init_params(int(spec["seed"]))
    registry = MetricsRegistry()
    chaos = ChaosInjector.from_spec(None, registry=registry)  # $DMT_CHAOS
    # Per-process span recorder, configured through the spec (the
    # supervisor owns the trace dir; the worker owns its clock offset).
    # One file per (replica, pid): a respawned attempt is a NEW process
    # and must not share a writer with its dead predecessor's file.
    tracer = None
    if spec.get("trace_dir"):
        from deeplearning_mpi_tpu.telemetry import SpanRecorder

        trace_dir = Path(spec["trace_dir"])
        tracer = SpanRecorder(
            trace_dir / f"trace_replica{args.replica}-{os.getpid()}.jsonl",
            proc=f"replica{args.replica}",
            registry=registry,
            flight_dir=trace_dir / "flight",
        )
    engine_cls: Any = ServingEngine
    if disagg:
        from deeplearning_mpi_tpu.serving.disagg import DisaggregatedEngine

        engine_cls = DisaggregatedEngine
    engine = engine_cls(
        cfg, params, EngineConfig(**spec["engine"]),
        dtype=jnp.float32, eos_id=spec.get("eos_id"),
        registry=registry, chaos=chaos,
        tenants=spec.get("tenants") or None,
        tracer=tracer,
    )
    if disagg:
        eng_idle = engine.idle
        q_depth = lambda: engine.prefill.scheduler.queue_depth()  # noqa: E731
        slots_active = lambda: (  # noqa: E731
            engine.prefill.scheduler.slots_active()
            + engine.decode.scheduler.slots_active()
        )
        handoff_depth = lambda: engine.handoff_depth  # noqa: E731
    else:
        eng_idle = engine.scheduler.idle
        q_depth = engine.scheduler.queue_depth
        slots_active = engine.scheduler.slots_active
        handoff_depth = lambda: 0  # noqa: E731
    if spec.get("warmup", True):
        engine.warmup()
    compile_counter = registry.counter("serve_compile_total")
    ttft_hist = registry.histogram("serve_ttft_s")

    outbox = (rdir / "outbox.jsonl").open("a")

    def emit(obj: dict) -> None:
        outbox.write(json.dumps(obj) + "\n")
        outbox.flush()

    # The monotonic-vs-epoch offset is what lets the supervisor (and
    # trace_report) place this worker's spans on the fleet's shared
    # wall-clock timeline; it rides the ready ack and every heartbeat.
    mono_offset = (
        tracer.mono_offset if tracer is not None
        else time.time() - time.monotonic()
    )
    emit({
        "op": "ready", "replica": args.replica, "pid": os.getpid(),
        "version": version, "compile_total": compile_counter.value,
        "mono_offset": mono_offset, "incarnation": incarnation,
    })

    inbox = rdir / "inbox.jsonl"
    offset = 0
    live: dict[int, Any] = {}  # fleet rid -> engine Request
    cancelled: set[int] = set()
    slow_reported = False
    stop = False
    hb = Heartbeat(
        rdir / "heartbeat.json",
        interval_s=float(os.environ.get(ENV_HEARTBEAT_INTERVAL, "0.5")),
    )
    hb.start()
    try:
        while not stop:
            msgs, offset = _tail_jsonl(inbox, offset)
            for m in msgs:
                op = m["op"]
                if op == "req":
                    rid = int(m["rid"])
                    if rid in cancelled:
                        continue  # the cancel raced ahead of this copy
                    if rid in live:
                        # Duplicate copy of work already decoding here (a
                        # re-dispatch raced the adopt ack) — idempotent.
                        continue
                    req = engine.submit(
                        np.asarray(m["prompt"], np.int32), int(m["max_new"]),
                        deadline=m.get("deadline"), arrival=m.get("arrival"),
                        tenant=m.get("tenant", "default"),
                        trace=m.get("trace"),
                    )
                    if req.state is RequestState.SHED:
                        emit({"op": "shed", "rid": rid,
                              "reason": req.shed_reason})
                    else:
                        live[rid] = req
                elif op == "cancel":
                    rid = int(m["rid"])
                    cancelled.add(rid)
                    req = live.pop(rid, None)
                    if req is not None:
                        engine.cancel(req)
                elif op == "adopt":
                    # Orphan re-adoption handshake: a restarted supervisor
                    # (new incarnation) claims this still-running worker.
                    # NOTHING is reset — the warmed engine keeps its KV
                    # pools and compiled programs (the ack's compile
                    # counter proves zero retraces) and in-flight requests
                    # keep decoding; the ack lists their rids so the new
                    # incarnation rebuilds its router books instead of
                    # re-dispatching work this worker already holds.
                    incarnation = int(m["incarnation"])
                    emit({
                        "op": "adopted", "replica": args.replica,
                        "pid": os.getpid(), "incarnation": incarnation,
                        "version": version,
                        "compile_total": compile_counter.value,
                        "mono_offset": mono_offset,
                        "rids": sorted(live),
                    })
                elif op == "swap":
                    # Same-shape/dtype params are an argument to the warmed
                    # programs, not a capture — assignment swaps weights
                    # with zero retraces. The ack carries the compile
                    # counter so the supervisor can PROVE that.
                    engine.params = init_params(int(m["seed"]))
                    # Cached prefix KV was computed under the old weights;
                    # serving it after the swap would break greedy parity.
                    # (DisaggregatedEngine flushes in its params setter —
                    # flushing an already-empty cache is a no-op.)
                    cache = getattr(engine, "prefix_cache", None)
                    if cache is not None:
                        cache.flush()
                    version = int(m["version"])
                    emit({"op": "swapped", "version": version,
                          "compile_total": compile_counter.value})
                elif op == "brownout":
                    # Overload ladder from the autoscaler: door policy is
                    # replica-local (each scheduler sheds at its own door),
                    # so a stage broadcast reaches every admission point.
                    engine.set_brownout(int(m["stage"]))
                elif op == "stop":
                    stop = True

            if not stop and not eng_idle():
                if chaos is not None:
                    slow_s = chaos.check_replica_fault(step=engine.steps)
                    if slow_s > 0.0:
                        if not slow_reported:
                            # Alive-but-degraded is the one fleet fault the
                            # worker CAN report itself; the supervisor still
                            # owns the accounting (fire_observed on receipt).
                            emit({"op": "fault", "kind": "replica_slow",
                                  "step": engine.steps})
                            slow_reported = True
                        time.sleep(slow_s)
                try:
                    engine.step()
                except InjectedFault:
                    engine.recover()
                for rid, req in list(live.items()):
                    if req.state is RequestState.FINISHED:
                        emit({
                            "op": "done", "rid": rid,
                            "tokens": [int(t) for t in req.generated],
                            "version": version,
                            "ttft": req.ttft, "tpot": req.tpot,
                            # CLOCK_MONOTONIC is system-wide: the finish
                            # stamp lets the supervisor span the stream
                            # leg (worker finish → supervisor receipt).
                            "t_finished": req.t_finished,
                        })
                        del live[rid]
                    elif req.state is RequestState.SHED:
                        emit({"op": "shed", "rid": rid,
                              "reason": req.shed_reason})
                        del live[rid]
            elif not stop:
                time.sleep(0.002)

            # Every loop iteration bumps progress_seq — an idle replica is
            # a live replica. Only a genuine wedge (replica_hang blocks THIS
            # loop; the heartbeat daemon keeps the file fresh) freezes the
            # seq, which is exactly what LivenessTracker watches.
            hb.progress = {
                "step": engine.steps,
                "queue_depth": q_depth(),
                "slots_active": slots_active(),
                "handoff_depth": handoff_depth(),
                "ttft_p50": ttft_hist.percentile(0.5) or 0.0,
                "version": version,
                "mono_offset": mono_offset,
                # Stale-incarnation hygiene: which supervisor this beat
                # answers to. A restarted supervisor's LivenessTracker
                # rejects beats stamped by a dead incarnation, so a
                # pre-crash heartbeat file can never mask a dead worker.
                "incarnation": incarnation,
            }
    except BaseException:
        # Unclean exit: leave the black box. (A chaos replica_kill never
        # reaches here — os._exit — so faults._exit_rank dumps instead.)
        if tracer is not None:
            tracer.dump_flight("worker-unclean-exit")
        raise
    finally:
        hb.stop()
    emit({
        "op": "stopped", "version": version,
        "compile_total": compile_counter.value,
        "snapshot": registry.snapshot(),
    })
    outbox.close()
    if tracer is not None:
        tracer.close()
    return 0


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

class _AdoptedProc:
    """Popen-shaped handle for a re-adopted orphan.

    An adopted worker is NOT this supervisor's child — it was forked by a
    dead incarnation and reparented to init — so there is no waitable
    handle and no exit status to observe. Liveness is pid probing
    (:func:`~..resilience.cluster.pid_alive`), teardown is a best-effort
    group SIGKILL, and "reaping" is waiting for the pid to vanish (init
    does the actual reap). Implements exactly the ``poll``/``wait``/
    ``kill`` surface ``kill_and_reap`` and the supervision loop use.
    """

    def __init__(self, pid: int) -> None:
        self.pid = pid
        self._rc: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._rc is None and not pid_alive(self.pid):
            # The true status died with the old incarnation; report the
            # conventional SIGKILL code so failure handling reads sanely.
            self._rc = -9
        return self._rc

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("adopted-orphan", timeout)
            time.sleep(0.05)
        return self._rc  # type: ignore[return-value]

    def kill(self) -> None:
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


@dataclasses.dataclass
class _Replica:
    """Supervisor-side state for one replica slot."""

    idx: int
    seed: int
    version: int = 0
    chaos_spec: str = ""
    attempt: int = 0
    dir: Optional[Path] = None
    proc: Optional[subprocess.Popen] = None
    log: Any = None
    tracker: Any = None
    outbox_offset: int = 0
    inbox: Any = None
    ready: bool = False
    compile_at_ready: Optional[float] = None
    compile_flat: bool = True
    stopped: Optional[dict] = None
    #: True when this slot's process was inherited from a dead incarnation
    #: via the re-adoption handshake rather than spawned by this one.
    adopted: bool = False
    #: last heartbeat payload observed — the autoscaler's load signal
    #: (queue_depth et al.) reads it without re-parsing the file.
    last_hb: Optional[dict] = None


@dataclasses.dataclass
class _Req:
    """Supervisor-side ledger entry for one client request."""

    rid: int
    prompt: list[int]
    max_new: int
    arrival_abs: float
    deadline_abs: Optional[float]
    tenant: str = "default"
    holders: set[int] = dataclasses.field(default_factory=set)
    tokens: Optional[list[int]] = None
    version: Optional[int] = None
    ttft: Optional[float] = None
    shed_reason: Optional[str] = None
    redispatched: bool = False

    @property
    def resolved(self) -> bool:
        return self.tokens is not None or self.shed_reason is not None


@dataclasses.dataclass
class FleetResult:
    """What a :meth:`FleetSupervisor.run` accomplished."""

    ok: bool
    completed: int
    shed: dict[str, int]
    dropped: int  # accepted requests that vanished — the zero-downtime bar
    restarts: int
    failures: dict[str, int]
    redispatched: int
    compile_flat: bool  # serve_compile_total flat after warmup, all workers
    chaos_balanced: Optional[bool]
    ttft: dict[str, Optional[float]]  # {before,during,after}_{p50,p99}
    swap: dict[str, Any]
    requests: dict[int, dict]  # rid -> {"tokens", "version", ...} (wins only)
    snapshot: dict[str, Any]
    #: autoscaler accounting (empty when autoscaling is off):
    #: {"events", "spawned", "retired", "vetoed", "brownout_stage_max",
    #:  "replicas_final"} — events == spawned + retired + vetoed is a
    #: reconciliation invariant checked into ``ok``.
    scale: dict[str, Any] = dataclasses.field(default_factory=dict)
    #: tenant -> {shed_reason -> count} over the supervisor's ledger — the
    #: brownout acceptance check reads it (only the lowest-priority tier
    #: may shed with reason "brownout").
    shed_by_tenant: dict[str, dict[str, int]] = dataclasses.field(
        default_factory=dict
    )
    #: control-plane crash safety: this run's incarnation id and what the
    #: journal-replay recovery did (all zero for a first-boot run).
    incarnation: int = 0
    readopted: int = 0
    respawned: int = 0


class FleetSupervisor(ClusterSupervisor):
    """Spawn N replica workers, route a trace through them, survive
    replica loss, and prove the books balance.

    ``model_spec`` / ``engine_spec`` are kwargs dicts for
    ``TransformerConfig`` / ``EngineConfig`` — shipped to workers as JSON,
    so replicas are constructed from *specs*, never pickled arrays
    (params rebuild from ``(config, seed, version)``; a weight swap ships
    a new seed the same way).

    The supervision bones — liveness tracking, SIGKILL+reap teardown,
    chaos books, JSONL IPC tailing — come from the unified core
    (:class:`~deeplearning_mpi_tpu.resilience.cluster.ClusterSupervisor`),
    shared with the training pod supervisor; this class owns the
    mailbox/router/ledger semantics.
    """

    log_name = "fleet"

    def __init__(
        self,
        model_spec: dict,
        engine_spec: dict,
        num_replicas: int,
        fleet_dir: str | Path,
        *,
        seed: int = 0,
        eos_id: int | None = None,
        warmup: bool = True,
        chaos: str | None = None,
        hedge_ms: float = 0.0,
        heartbeat_deadline_s: float = 2.0,
        heartbeat_interval_s: float = 0.2,
        spawn_grace_s: float = 120.0,
        poll_interval_s: float = 0.02,
        exclusion_s: float = 0.5,
        max_replica_restarts: int = 4,
        timeout_s: float = 600.0,
        registry: Any = None,
        env: Mapping[str, str] | None = None,
        disagg: bool = False,
        tp: int = 1,
        tenants: dict[str, dict[str, Any]] | None = None,
        autoscale: Any = None,
        trace_dir: str | Path | None = None,
        resume: bool = False,
        adopt_grace_s: float = 6.0,
    ) -> None:
        from deeplearning_mpi_tpu.resilience.faults import (
            AUTOSCALE_KINDS,
            CONTROLPLANE_KINDS,
            FLEET_KINDS,
            validate_plan_kinds,
        )

        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        super().__init__(
            fleet_dir,
            chaos=chaos,
            heartbeat_deadline_s=heartbeat_deadline_s,
            heartbeat_interval_s=heartbeat_interval_s,
            spawn_grace_s=spawn_grace_s,
            poll_interval_s=poll_interval_s,
            registry=registry,
            env=env,
        )
        self.model_spec = dict(model_spec)
        self.engine_spec = dict(engine_spec)
        self.num_replicas = num_replicas
        self.fleet_dir = self.dir
        self.seed = seed
        self.eos_id = eos_id
        self.warmup = warmup
        #: topology knobs, shipped to workers inside spec.json. ``disagg``
        #: replicas run a DisaggregatedEngine (prefill/decode split);
        #: ``tp > 1`` shards each replica's params across tp (virtual CPU)
        #: devices via infer_tp_param_sharding.
        self.disagg = bool(disagg)
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        self.tp = int(tp)
        #: per-tenant admission policy shipped to every worker — the
        #: scheduler enforces budgets replica-locally (no global ledger;
        #: the trace's tenant labels ride along with each dispatch).
        self.tenants = dict(tenants) if tenants else None
        #: AutoscalerConfig enabling closed-loop fleet sizing; None keeps
        #: the fixed-size fleet bit-identical to its pre-autoscaler self.
        self.autoscale = autoscale
        if autoscale is not None and not (
            autoscale.min_replicas <= num_replicas <= autoscale.max_replicas
        ):
            raise ValueError(
                f"num_replicas ({num_replicas}) outside the autoscale band "
                f"[{autoscale.min_replicas}, {autoscale.max_replicas}]"
            )
        if self.chaos_spec.strip():
            # CONTROLPLANE_KINDS are valid on any supervised fleet: the
            # supervisor detonates ITSELF and a `resume=True` restart on
            # the same fleet_dir is the recovery path. (serve_lm still
            # rejects them — its CLI run has no restart harness.)
            supported = FLEET_KINDS | CONTROLPLANE_KINDS
            workload = "serving fleet"
            if autoscale is not None:
                # The autoscaler drill kinds are only meaningful with the
                # control loop running.
                supported = supported | AUTOSCALE_KINDS
                workload = "autoscaled serving fleet"
            validate_plan_kinds(self.chaos_spec, supported, workload=workload)
        self.hedge_ms = hedge_ms
        self.exclusion_s = exclusion_s
        self.max_replica_restarts = max_replica_restarts
        self.timeout_s = timeout_s
        #: crash recovery: with ``resume=True``, :meth:`run` replays the
        #: dead incarnation's write-ahead journal, probes its journaled
        #: pids, re-adopts the live orphans, and re-dispatches the rest.
        #: Default False treats a dirty fleet_dir as stale state: any
        #: journaled orphans are SIGKILLed and the journal retired.
        self.resume = bool(resume)
        self.adopt_grace_s = float(adopt_grace_s)
        #: distributed tracing: when set, the supervisor and every worker
        #: each write a SpanRecorder JSONL into this dir (workers get the
        #: path via spec.json) and ``tools/trace_report.py`` merges them.
        #: None keeps the whole fleet tracing-free (costless-off).
        self.trace_dir = Path(trace_dir) if trace_dir else None
        self.tracer: Any = None
        if self.trace_dir is not None:
            from deeplearning_mpi_tpu.telemetry import SpanRecorder

            self.tracer = SpanRecorder(
                self.trace_dir / "trace_supervisor.jsonl",
                proc="supervisor",
                registry=self.registry,
                flight_dir=self.trace_dir / "flight",
            )

    # -- spawning ------------------------------------------------------------
    def _replica_chaos(self) -> dict[int, str]:
        """Distribute fleet chaos entries round-robin across replicas:
        entry i detonates on replica i % N (the drill's 'kill one, hang
        the other' shape with two replicas and two entries)."""
        from deeplearning_mpi_tpu.resilience.faults import fleet_entries

        per: dict[int, list[str]] = {k: [] for k in range(self.num_replicas)}
        for i, entry in enumerate(fleet_entries(self.chaos_spec)):
            per[i % self.num_replicas].append(entry)
        return {k: ",".join(v) for k, v in per.items()}

    def _spawn(self, rep: _Replica) -> None:
        from deeplearning_mpi_tpu.resilience.cluster import (
            ENV_HEARTBEAT_INTERVAL,
        )

        rdir = self.fleet_dir / f"replica{rep.idx}-a{rep.attempt}"
        rdir.mkdir(parents=True, exist_ok=True)
        spec_path = rdir / "spec.json"
        # Atomic: the replica reads spec.json immediately after spawn, and a
        # supervisor kill mid-write must never hand it a torn spec
        # (dmt-lint DMT004 — the atomic-IO contract).
        from deeplearning_mpi_tpu.resilience.integrity import atomic_write_json

        atomic_write_json(spec_path, {
            "model": self.model_spec,
            "engine": self.engine_spec,
            "seed": rep.seed,
            "version": rep.version,
            "eos_id": self.eos_id,
            "warmup": self.warmup,
            "disagg": self.disagg,
            "tp": self.tp,
            "tenants": self.tenants,
            "trace_dir": str(self.trace_dir) if self.trace_dir else None,
            "incarnation": int(self.incarnation or 0),
        })
        (rdir / "inbox.jsonl").touch()
        env = dict(os.environ)
        env.update(self.extra_env)
        env[ENV_HEARTBEAT_INTERVAL] = str(self.heartbeat_interval_s)
        if rep.chaos_spec:
            env["DMT_CHAOS"] = rep.chaos_spec
        else:
            env.pop("DMT_CHAOS", None)
        # A replica is a lone process — leftover rendezvous vars from a
        # surrounding pod run would make its jax runtime wait for peers.
        scrub_rendezvous_env(env)
        log_path = self.fleet_dir / f"replica{rep.idx}-a{rep.attempt}.log"
        rep.log = log_path.open("w")  # dmt-lint: disable=DMT004 — stdout capture stream, not a consumed JSON artifact
        rep.proc = subprocess.Popen(
            [
                sys.executable, "-m", "deeplearning_mpi_tpu.serving.fleet",
                "--replica", str(rep.idx), "--dir", str(rdir),
                "--spec", str(spec_path),
            ],
            env=env,
            stdout=rep.log,
            stderr=subprocess.STDOUT,
            start_new_session=True,  # isolate signals; killpg on teardown
        )
        rep.dir = rdir
        rep.outbox_offset = 0
        rep.ready = False
        rep.compile_at_ready = None
        rep.inbox = (rdir / "inbox.jsonl").open("a")
        rep.tracker = self.new_tracker([0])
        rep.adopted = False
        rep.stopped = None
        if self.journal is not None:
            # Journaled right after the fork so a successor can find (and
            # probe or kill) this pid. The one-Popen-call window where a
            # crash leaks an unjournaled child is closed by the heartbeat
            # file: the worker stamps its own pid there too.
            self.journal.record(
                "spawn", idx=rep.idx, attempt=rep.attempt,
                pid=rep.proc.pid, seed=rep.seed, version=rep.version,
                dir=rdir.name, chaos=rep.chaos_spec,
            )
        self._log(
            f"replica {rep.idx} attempt {rep.attempt}: spawned pid "
            f"{rep.proc.pid} (version {rep.version}, "
            f"chaos={rep.chaos_spec or 'none'})"
        )

    def _send(self, rep: _Replica, obj: dict) -> None:
        rep.inbox.write(json.dumps(obj) + "\n")
        rep.inbox.flush()

    @staticmethod
    def _kill(rep: _Replica) -> None:
        if rep.proc is not None:
            kill_and_reap(rep.proc)
        if rep.log is not None:
            rep.log.close()
            rep.log = None
        if rep.inbox is not None:
            rep.inbox.close()
            rep.inbox = None

    # -- crash recovery (docs/RESILIENCE.md "Control-plane crash safety") ----
    # (`_kill_orphan` lives on ClusterSupervisor — shared with the pod.)

    def _scrub_dead_fleet(self) -> None:
        """Fresh-start hygiene (``resume=False``) on a dirty fleet dir: a
        dead incarnation's journal may name live orphans that would fight
        this run's workers for the per-replica IPC files — SIGKILL them
        and retire the journal before opening a new one. (Recovery is an
        explicit opt-in; the default must never silently inherit another
        run's ledger.)"""
        path = self.dir / JOURNAL_FILE
        if not path.exists():
            return
        for r in replay_journal(path):
            if r.get("ev") in ("spawn", "adopt") and r.get("pid"):
                self._kill_orphan(int(r["pid"]))
        try:
            path.unlink()
        except OSError:
            pass

    def _try_adopt(
        self, rep: _Replica, pid: int
    ) -> tuple[Optional[dict], list[dict]]:
        """Probe one journaled orphan and try to re-adopt it alive.

        Three independent proofs of life: (1) the pid exists and is not a
        zombie; (2) its heartbeat ``progress_seq`` advances during the
        probe window (the heartbeat daemon beats through a wedge, so a
        fresh file with a frozen seq is a hung worker — kill, don't
        adopt); (3) it answers the incarnation handshake — an ``adopt``
        op appended to its inbox, acked by ``adopted`` (stamped with OUR
        incarnation) on its outbox, carrying the rids it still holds.

        Returns ``(ack, history)`` on success, where ``history`` is every
        outbox record that landed before the ack — completions that
        finished while the fleet ran unsupervised are in there and count,
        sparing a re-decode. Returns ``(None, [])`` when the orphan is
        dead, wedged, or deaf; the caller respawns the slot.
        """
        from deeplearning_mpi_tpu.resilience.supervisor import Heartbeat

        if rep.dir is None or not pid_alive(pid):
            return None, []
        hb0 = Heartbeat.read(rep.dir / "heartbeat.json")
        seq0 = hb0.get("progress_seq") if hb0 else None
        rep.inbox = (rep.dir / "inbox.jsonl").open("a")
        self._send(rep, {"op": "adopt", "incarnation": self.incarnation})
        history: list[dict] = []
        seq_advanced = False
        deadline = time.monotonic() + self.adopt_grace_s
        while time.monotonic() < deadline:
            hb = Heartbeat.read(rep.dir / "heartbeat.json")
            if (
                hb is not None and seq0 is not None
                and hb.get("progress_seq", 0) > seq0
            ):
                seq_advanced = True
            msgs, rep.outbox_offset = tail_jsonl(
                rep.dir / "outbox.jsonl", rep.outbox_offset
            )
            for m in msgs:
                if (
                    m.get("op") == "adopted"
                    and int(m.get("incarnation", -1)) == self.incarnation
                ):
                    return m, history
                history.append(m)
            if not pid_alive(pid):
                break
            time.sleep(self.poll_interval_s)
        self._log(
            f"replica {rep.idx}: orphan pid {pid} not adoptable "
            f"(alive={pid_alive(pid)}, progress_advanced={seq_advanced}, "
            "no handshake ack) — respawning"
        )
        if rep.inbox is not None:
            rep.inbox.close()
            rep.inbox = None
        rep.outbox_offset = 0
        return None, []

    @staticmethod
    def _replay_fleet_state(prior: list[dict]) -> dict:
        """Fold a dead predecessor's journal into the bookkeeping a
        restarted supervisor starts from: live replica slots (to probe),
        the request ledger (resolved + orphaned), scale/brownout/chaos
        books, and the trace clock. Pure function of the records — no
        clock, no IO — so the fake-clock unit tests drive it directly.
        """
        slots: dict[int, dict] = {}
        ledger: dict[int, dict] = {}
        fires: list[dict] = []
        recovery_kinds: list[str] = []
        scale_records: list[tuple[str, str]] = []
        brownout_records: list[int] = []
        failures: dict[str, int] = {}
        t0: Optional[float] = None
        restarts = 0
        redispatched = 0
        brownout_stage = 0
        brownout_stage_max = 0
        max_idx = -1
        swap_done_version = 0
        retire_begun: list[int] = []
        retired_done: list[int] = []
        for r in prior:
            ev = r.get("ev")
            if ev == "clock_start":
                t0 = float(r["t0"])
            elif ev == "spawn":
                idx = int(r["idx"])
                max_idx = max(max_idx, idx)
                slots[idx] = {
                    "attempt": int(r["attempt"]), "pid": int(r["pid"]),
                    "seed": int(r["seed"]), "version": int(r["version"]),
                    "dir": r["dir"], "compile_ready": None,
                }
            elif ev == "adopt":
                slot = slots.get(int(r["idx"]))
                if slot is not None:
                    slot["pid"] = int(r["pid"])
                    slot["compile_ready"] = r.get("compile_total")
            elif ev == "ready":
                slot = slots.get(int(r["idx"]))
                if slot is not None and slot["attempt"] == int(r["attempt"]):
                    slot["compile_ready"] = r.get("compile_total")
            elif ev == "retire_begin":
                retire_begun.append(int(r["idx"]))
            elif ev == "retired":
                slots.pop(int(r["idx"]), None)
                retired_done.append(int(r["idx"]))
            elif ev == "failure":
                restarts += 1
                kind = str(r.get("kind", "replica_kill"))
                failures[kind] = failures.get(kind, 0) + 1
            elif ev == "admit":
                ledger[int(r["rid"])] = dict(r)
            elif ev == "redispatch":
                redispatched += 1
                jr = ledger.get(int(r["rid"]))
                if jr is not None:
                    jr["redispatched"] = True
            elif ev == "done":
                jr = ledger.get(int(r["rid"]))
                if jr is not None and jr.get("tokens") is None:
                    jr.update(
                        tokens=r["tokens"], version=r.get("version"),
                        ttft=r.get("ttft"), phase=r.get("phase"),
                    )
            elif ev == "shed":
                jr = ledger.get(int(r["rid"]))
                if jr is not None and jr.get("tokens") is None:
                    jr["shed"] = r.get("reason")
            elif ev == "swapped":
                slot = slots.get(int(r["idx"]))
                if slot is not None:
                    slot["version"] = int(r["version"])
            elif ev == "scale":
                scale_records.append((str(r["direction"]), str(r["outcome"])))
            elif ev == "brownout":
                stage = int(r["stage"])
                brownout_records.append(stage)
                brownout_stage = stage
                brownout_stage_max = max(brownout_stage_max, stage)
            elif ev == "chaos_fire":
                fires.append(r)
            elif ev == "chaos_recovery":
                recovery_kinds.append(str(r["kind"]))
            elif ev == "swap_done":
                swap_done_version = int(r["version"])
        # A retire that began but never completed resumes in the new
        # incarnation — its slot is still live (maybe adoptably so), and
        # the scale books only balance once the drain finishes.
        unfinished = [
            i for i in retire_begun
            if i not in retired_done and i in slots
        ]
        return {
            "slots": slots,
            "ledger": ledger,
            "next_rid": (max(ledger) + 1) if ledger else 0,
            "next_idx": max_idx + 1,
            "t0": t0,
            "restarts": restarts,
            "failures": failures,
            "redispatched": redispatched,
            "fires": fires,
            "recovery_kinds": recovery_kinds,
            "scale_records": scale_records,
            "retired_count": len(retired_done),
            "brownout_records": brownout_records,
            "brownout_stage": brownout_stage,
            "brownout_stage_max": brownout_stage_max,
            "swap_done_version": swap_done_version,
            "retiring": unfinished[0] if unfinished else None,
        }

    # -- the supervision loop ------------------------------------------------
    def run(
        self,
        entries: list[dict],
        *,
        swap_at: int | None = None,
        swap_seed: int | None = None,
    ) -> FleetResult:
        """Replay ``entries`` (serve_lm trace format: ``arrival`` seconds
        from start, ``prompt`` int sequence, ``max_new``, optional
        ``deadline`` seconds after arrival) through the fleet. With
        ``swap_seed`` set, a rolling :meth:`swap_weights` begins once
        ``swap_at`` requests have completed — under live load, by design.
        """
        from deeplearning_mpi_tpu.resilience.supervisor import Heartbeat
        from deeplearning_mpi_tpu.serving.router import Router
        from deeplearning_mpi_tpu.telemetry.registry import labeled

        injector = self._open_books("fleet_metrics.jsonl")
        for name in (FLEET_RESTARTS, FLEET_FAILURES, FLEET_REDISPATCH,
                     SUP_READOPTED, SUP_RESPAWNED):
            self.registry.counter(name)
        # -- write-ahead journal + crash recovery ---------------------------
        replay_wall0 = time.monotonic()
        if not self.resume:
            self._scrub_dead_fleet()
        journal, prior = self._open_journal()
        recovered = (
            self._replay_fleet_state(prior)
            if (self.resume and prior) else None
        )
        self.registry.gauge(SUP_INCARNATION).set(float(self.incarnation))
        policy = None
        if self.autoscale is not None:
            from deeplearning_mpi_tpu.serving.autoscaler import (
                AutoscalerPolicy,
                ReplicaView,
                build_load_signal,
            )

            policy = AutoscalerPolicy(self.autoscale)
            # Explicit zeros so a scale-free autoscaled run still reports.
            self.registry.counter("fleet_scale_total")
            self.registry.counter("fleet_brownout_total")
        slot_ids = (
            sorted(recovered["slots"]) if recovered is not None
            else list(range(self.num_replicas))
        )
        router = Router(
            slot_ids,
            hedge_ms=self.hedge_ms,
            exclusion_s=self.exclusion_s,
            registry=self.registry,
            roles=(
                {r: "disagg" for r in slot_ids}
                if self.disagg else None
            ),
        )
        per_chaos = self._replica_chaos()
        adopted_n = respawned_n = 0
        #: idx -> (adopt ack, pre-ack outbox history) for re-adopted slots;
        #: folded into the ledger once it is rebuilt below.
        adopt_histories: dict[int, tuple[dict, list[dict]]] = {}
        if recovered is None:
            replicas = {
                k: _Replica(idx=k, seed=self.seed,
                            chaos_spec=per_chaos.get(k, ""))
                for k in slot_ids
            }
            for rep in replicas.values():
                router.exclude(rep.idx)  # ineligible until its ready lands
                self._spawn(rep)
        else:
            # Orphan re-adoption: probe every slot the corpse journaled.
            # Live + progressing + handshake-acked ⇒ inherit the process
            # (warmed engine, KV pools, in-flight decodes — zero retraces);
            # anything else ⇒ SIGKILL the pid and respawn the slot.
            replicas = {}
            for idx in slot_ids:
                slot = recovered["slots"][idx]
                rep = _Replica(
                    idx=idx, seed=int(slot["seed"]),
                    version=int(slot.get("version", 0)),
                    # The corpse's worker-side chaos died (or detonated)
                    # with it; a recovered fleet does not re-arm it.
                    chaos_spec="",
                    attempt=int(slot["attempt"]),
                )
                rep.dir = self.fleet_dir / slot["dir"]
                replicas[idx] = rep
                router.exclude(idx)
                ack, history = self._try_adopt(rep, int(slot["pid"]))
                if ack is not None:
                    rep.proc = _AdoptedProc(int(slot["pid"]))
                    rep.adopted = True
                    rep.ready = True
                    rep.version = int(ack.get("version", rep.version))
                    rep.compile_at_ready = float(ack["compile_total"])
                    if (
                        slot.get("compile_ready") is not None
                        and rep.compile_at_ready
                        != float(slot["compile_ready"])
                    ):
                        # The orphan compiled something while unsupervised
                        # — adoption must not launder a retrace.
                        rep.compile_flat = False
                    rep.tracker = self.new_tracker([0])
                    router.mark_alive(idx, time.monotonic())
                    router.include(idx)
                    journal.record(
                        "adopt", idx=idx, attempt=rep.attempt,
                        pid=int(ack["pid"]),
                        compile_total=rep.compile_at_ready,
                        rids=[int(x) for x in ack.get("rids", [])],
                    )
                    adopt_histories[idx] = (ack, history)
                    adopted_n += 1
                    self.registry.counter(SUP_READOPTED).inc()
                    self._log(
                        f"replica {idx}: RE-ADOPTED live orphan pid "
                        f"{ack['pid']} (attempt {rep.attempt}, "
                        f"{len(ack.get('rids', []))} in flight, "
                        f"compile_total {rep.compile_at_ready})"
                    )
                else:
                    self._kill_orphan(int(slot["pid"]))
                    rep.attempt += 1
                    self._spawn(rep)
                    respawned_n += 1
                    self.registry.counter(SUP_RESPAWNED).inc()

        start = time.monotonic()
        # The trace clock starts at the fleet's first ready-ack, not at
        # spawn: arrival offsets time SERVING traffic, and a cold-cache
        # warmup that outlasted the trickle window would collapse every
        # trace into one undifferentiated burst (and hand the autoscaler
        # a huge "backlog" on a fleet that cannot serve anything yet).
        t0: Optional[float] = None
        pending = deque(sorted(entries, key=lambda e: e["arrival"]))
        ledger: dict[int, _Req] = {}
        next_rid = 0
        redispatch_queue: deque[int] = deque()
        # kill/hang recoveries close when every re-dispatched rid resolves
        # (or, for an idle-replica loss, when the respawn reaches ready);
        # slow recoveries close when a hedged request on the slow replica
        # completes — the hedge machinery demonstrably covered the fault.
        pending_recoveries: list[dict] = []
        hedged_primary: dict[int, int] = {}  # rid -> primary at hedge time
        restarts = 0
        failures: dict[str, int] = {}
        redispatched = 0
        completed = 0
        phase = "before"
        ttft_by_phase: dict[str, list[float]] = {
            "before": [], "during": [], "after": [],
        }
        swap: dict[str, Any] = {
            "requested": swap_seed is not None,
            "performed": False, "drain_s": None,
            "completions_during": 0, "compile_flat": True,
        }
        swap_queue: list[int] = []
        swap_stage: Optional[str] = None  # None | "drain" | "await"
        swap_t0: Optional[float] = None
        swap_mark = 0
        target_version = 0
        stopping = False
        # -- autoscaler state (all inert when policy is None) --
        next_idx = self.num_replicas  # replica ids are never reused
        scale_events = spawned = retired = vetoed = 0
        scale_ups = 0  # ordinal for the scale_during_failure trigger
        #: trace-clock stamps (now - t0) of each scale-up spawn — the
        #: predictive drill asserts the first lands BEFORE the flash
        #: crowd's peak arrival.
        up_times: list[float] = []
        brownout_stage = 0
        brownout_stage_max = 0
        retiring: Optional[int] = None  # replica mid-drain, at most one
        retire_stop_sent = False

        def close_recovery(pr: dict, now: float) -> None:
            if injector is not None:
                injector.record_recovery(
                    pr["kind"], latency_s=now - pr["detected"]
                )
            journal.record("chaos_recovery", kind=pr["kind"])
            pending_recoveries.remove(pr)
            self._log(
                f"recovery: {pr['kind']} on replica {pr['replica']} closed "
                f"({now - pr['detected']:.2f}s after detection)"
            )

        def handle_failure(rep: _Replica, kind: str, why: str) -> None:
            nonlocal restarts, redispatched, phase
            now = time.monotonic()
            failures[kind] = failures.get(kind, 0) + 1
            self.registry.counter(FLEET_FAILURES).inc()
            self.registry.counter(labeled(FLEET_FAILURES, kind=kind)).inc()
            if self.tracer is not None:
                # The supervisor's own black box: ring state at the moment
                # the watchdog (or a dead pid) declared the replica lost.
                self.tracer.event(
                    "replica_failure", t=now, replica=rep.idx, kind=kind,
                )
                self.tracer.dump_flight(f"fleet-{kind}-replica{rep.idx}")
            self._kill(rep)
            orphans = router.mark_dead(rep.idx, now)
            hit = injector.fire_observed(kind) if injector else None
            tag = (
                f"matches planned {hit.kind}@{hit.unit}:{hit.at}"
                if hit is not None else "unplanned"
            )
            self._log(
                f"replica {rep.idx} failed ({why}) — {tag}; "
                f"re-dispatching {len(orphans)} in-flight request(s)"
            )
            if hit is not None:
                journal.record("chaos_fire", kind=kind, replica=rep.idx)
                pending_recoveries.append({
                    "kind": kind, "replica": rep.idx, "detected": now,
                    "rids": set(orphans),
                })
            phase = "during"
            for rid in orphans:
                ledger[rid].holders.discard(rep.idx)
                ledger[rid].redispatched = True
                redispatch_queue.append(rid)
                redispatched += 1
                self.registry.counter(FLEET_REDISPATCH).inc()
                journal.record("redispatch", rid=rid)
            # Hedge losers that lived on the dead replica are already
            # forgotten by mark_dead; their primaries carry on elsewhere.
            for rec in ledger.values():
                rec.holders.discard(rep.idx)
            if restarts >= self.max_replica_restarts:
                raise FleetFailure(
                    f"replica restart budget spent "
                    f"({self.max_replica_restarts})"
                )
            restarts += 1
            self.registry.counter(FLEET_RESTARTS).inc()
            journal.record("failure", idx=rep.idx, kind=kind,
                           chaos=hit is not None)
            if injector is not None:
                from deeplearning_mpi_tpu.resilience.faults import (
                    strip_entries,
                )

                fired = [
                    f"{s.kind}@{s.unit}:{s.at}"
                    for s in injector.plan.specs
                    if s.fired and s.kind in ("replica_kill", "replica_hang")
                ]
                rep.chaos_spec = strip_entries(rep.chaos_spec, fired)
            rep.attempt += 1
            self._spawn(rep)
            if policy is not None:
                # Capacity is already in flux from the respawn: hold scale
                # decisions for one cooldown so failover can't thrash the
                # autoscaler (and vice versa).
                policy.note_respawn(now)

        from deeplearning_mpi_tpu.serving.prefix_cache import prefix_signature

        block_size = int(self.engine_spec.get("block_size", 16))

        def req_sig(rec: _Req) -> Optional[int]:
            # The supervisor computes the same leading-block signature the
            # workers' radix caches key their first trie level by, so
            # affinity routing and cache contents agree cross-process.
            return prefix_signature(rec.prompt, block_size)

        def dispatch(rid: int, target: int, now: float) -> None:
            rec = ledger[rid]
            # Write-ahead: the journal record lands before the wire op, so
            # a crash can journal a dispatch the worker never saw (the
            # probe re-discovers it) but never ship one it didn't journal.
            journal.record("dispatch", rid=rid, target=target)
            self._send(replicas[target], {
                "op": "req", "rid": rid, "prompt": rec.prompt,
                "max_new": rec.max_new, "arrival": rec.arrival_abs,
                "deadline": rec.deadline_abs, "tenant": rec.tenant,
                # Trace context rides the wire: every span the worker emits
                # for this request carries the fleet-global key, not its
                # engine-local rid, so the merged timeline stitches.
                "trace": f"r{rid}",
            })
            rec.holders.add(target)
            router.dispatch(
                rid, target, now,
                deadline=rec.deadline_abs, prefix_sig=req_sig(rec),
            )
            if self.tracer is not None:
                self.tracer.event(
                    "dispatch", trace=f"r{rid}", t=now,
                    replica=target,
                    kind="redispatch" if rec.redispatched else "primary",
                )

        def handle_msg(rep: _Replica, m: dict) -> None:
            nonlocal completed, phase, swap_stage
            now = time.monotonic()
            op = m["op"]
            if op == "ready":
                rep.ready = True
                rep.compile_at_ready = float(m["compile_total"])
                journal.record(
                    "ready", idx=rep.idx, attempt=rep.attempt,
                    compile_total=rep.compile_at_ready,
                )
                router.mark_alive(rep.idx, now)
                router.include(rep.idx)
                for pr in list(pending_recoveries):
                    if pr["replica"] == rep.idx and not pr["rids"]:
                        close_recovery(pr, now)
            elif op == "done":
                rid = int(m["rid"])
                verdict, loser = router.on_complete(
                    rid, rep.idx, now, ttft=m.get("ttft")
                )
                if verdict != "win":
                    return
                rec = ledger[rid]
                rec.tokens = [int(t) for t in m["tokens"]]
                rec.version = int(m["version"])
                rec.ttft = m.get("ttft")
                rec.holders.discard(rep.idx)
                completed += 1
                # Tokens ride the journal so a successor's result (and the
                # offline-greedy parity check) spans both incarnations.
                journal.record(
                    "done", rid=rid, tokens=rec.tokens,
                    version=rec.version, ttft=rec.ttft, phase=phase,
                )
                if self.tracer is not None and m.get("t_finished") is not None:
                    # The stream leg: worker finish → supervisor receipt.
                    # Both stamps are system-wide CLOCK_MONOTONIC, so the
                    # span is valid without any clock translation.
                    self.tracer.record_span(
                        "stream", float(m["t_finished"]), now,
                        trace=f"r{rid}", replica=rep.idx,
                    )
                if rec.ttft is not None:
                    ttft_by_phase[phase].append(float(rec.ttft))
                if loser is not None:
                    self._send(replicas[loser], {"op": "cancel", "rid": rid})
                    ledger[rid].holders.discard(loser)
                for pr in list(pending_recoveries):
                    if pr["rids"] and rid in pr["rids"]:
                        pr["rids"].discard(rid)
                        # load_spike recoveries also wait for every spike
                        # entry to be ADMITTED ("awaiting"), not just for
                        # the already-admitted rids to resolve.
                        if not pr["rids"] and not pr.get("awaiting"):
                            close_recovery(pr, now)
                    elif (
                        pr["kind"] == "replica_slow"
                        and hedged_primary.get(rid) == pr["replica"]
                    ):
                        close_recovery(pr, now)
            elif op == "shed":
                rid = int(m["rid"])
                reason = m["reason"]
                rec = ledger.get(rid)
                if rec is None or reason == "cancelled":
                    return
                rec.holders.discard(rep.idx)
                if rec.tokens is None and not rec.holders:
                    rec.shed_reason = reason
                    router.forget(rid)
                    journal.record("shed", rid=rid, reason=reason)
                for pr in list(pending_recoveries):
                    if pr["rids"] and rid in pr["rids"] and rec.resolved:
                        pr["rids"].discard(rid)
                        if not pr["rids"] and not pr.get("awaiting"):
                            close_recovery(pr, now)
            elif op == "fault":
                hit = (
                    injector.fire_observed(m["kind"]) if injector else None
                )
                self._log(
                    f"replica {rep.idx} reported {m['kind']}@step:"
                    f"{m.get('step')} ("
                    f"{'planned' if hit is not None else 'unplanned'})"
                )
                if hit is not None:
                    journal.record(
                        "chaos_fire", kind=m["kind"], replica=rep.idx
                    )
                    pending_recoveries.append({
                        "kind": m["kind"], "replica": rep.idx,
                        "detected": now, "rids": set(),
                    })
                phase = "during"
            elif op == "swapped":
                rep.version = int(m["version"])
                journal.record("swapped", idx=rep.idx, version=rep.version)
                if float(m["compile_total"]) != rep.compile_at_ready:
                    rep.compile_flat = False
                    swap["compile_flat"] = False
                    self._log(
                        f"replica {rep.idx}: COMPILE during swap "
                        f"({rep.compile_at_ready} -> {m['compile_total']})"
                    )
                router.include(rep.idx)
                self._log(
                    f"swap: replica {rep.idx} now serving version "
                    f"{rep.version}"
                )
                if swap_queue and swap_queue[0] == rep.idx:
                    swap_queue.pop(0)
                    swap_stage = "drain" if swap_queue else None
            elif op == "stopped":
                rep.stopped = m
                if (
                    rep.compile_at_ready is not None
                    and float(m["compile_total"]) != rep.compile_at_ready
                ):
                    rep.compile_flat = False

        # -- fold the dead incarnation's books into this run's state --------
        if recovered is not None:
            t0 = recovered["t0"]
            next_rid = recovered["next_rid"]
            next_idx = max(next_idx, recovered["next_idx"])
            restarts = recovered["restarts"]
            redispatched = recovered["redispatched"]
            failures.update(recovered["failures"])
            brownout_stage = recovered["brownout_stage"]
            brownout_stage_max = recovered["brownout_stage_max"]
            scale_events = len(recovered["scale_records"])
            spawned = sum(
                1 for d, o in recovered["scale_records"]
                if d == "up" and o == "ok"
            )
            vetoed = sum(
                1 for _, o in recovered["scale_records"] if o != "ok"
            )
            retired = recovered["retired_count"]
            scale_ups = spawned
            if recovered["swap_done_version"]:
                target_version = recovered["swap_done_version"]
                swap["performed"] = swap["requested"]
            # Seed this incarnation's counters with the corpse's books so
            # fleet_summary reconciles ACROSS incarnations, not per-process.
            if restarts:
                self.registry.counter(FLEET_RESTARTS).inc(restarts)
            for kind, n in recovered["failures"].items():
                self.registry.counter(FLEET_FAILURES).inc(n)
                self.registry.counter(
                    labeled(FLEET_FAILURES, kind=kind)
                ).inc(n)
            if redispatched:
                self.registry.counter(FLEET_REDISPATCH).inc(redispatched)
            for direction, outcome in recovered["scale_records"]:
                self.registry.counter("fleet_scale_total").inc()
                self.registry.counter(labeled(
                    "fleet_scale_total",
                    direction=direction, outcome=outcome,
                )).inc()
            for stage in recovered["brownout_records"]:
                self.registry.counter("fleet_brownout_total").inc()
                self.registry.counter(labeled(
                    "fleet_brownout_total", stage=str(stage)
                )).inc()
            # Ledger: resolved entries carry over (their tokens are part of
            # this run's result and parity bar); unresolved ones become
            # re-adopted in-flight work or re-dispatch orphans below.
            for rid, jr in sorted(recovered["ledger"].items()):
                rec = _Req(
                    rid=rid,
                    prompt=[int(t) for t in jr["prompt"]],
                    max_new=int(jr["max_new"]),
                    arrival_abs=float(jr["arrival_abs"]),
                    deadline_abs=jr.get("deadline_abs"),
                    tenant=str(jr.get("tenant", "default")),
                )
                rec.redispatched = bool(jr.get("redispatched"))
                if jr.get("tokens") is not None:
                    rec.tokens = [int(t) for t in jr["tokens"]]
                    rec.version = jr.get("version")
                    rec.ttft = jr.get("ttft")
                    completed += 1
                    if rec.ttft is not None:
                        ttft_by_phase[jr.get("phase") or "before"].append(
                            float(rec.ttft)
                        )
                elif jr.get("shed") is not None:
                    rec.shed_reason = str(jr["shed"])
                ledger[rid] = rec
            now0 = time.monotonic()
            for idx, (ack, history) in adopt_histories.items():
                # Completions that landed while the fleet ran unsupervised
                # (after the crash, before this restart) still count — the
                # work happened; only the supervisor that asked for it died.
                for m in history:
                    mop = m.get("op")
                    if mop == "done":
                        rec = ledger.get(int(m["rid"]))
                        if rec is None or rec.resolved:
                            continue
                        rec.tokens = [int(t) for t in m["tokens"]]
                        rec.version = int(m["version"])
                        rec.ttft = m.get("ttft")
                        completed += 1
                        if rec.ttft is not None:
                            ttft_by_phase["during"].append(float(rec.ttft))
                        journal.record(
                            "done", rid=rec.rid, tokens=rec.tokens,
                            version=rec.version, ttft=rec.ttft,
                            phase="during",
                        )
                    elif mop == "shed":
                        rec = ledger.get(int(m["rid"]))
                        if (
                            rec is None or rec.resolved
                            or m["reason"] == "cancelled"
                        ):
                            continue
                        rec.shed_reason = str(m["reason"])
                        journal.record(
                            "shed", rid=rec.rid, reason=rec.shed_reason
                        )
                    elif mop == "swapped":
                        replicas[idx].version = int(m["version"])
                # Rids the adopted worker still holds: rebuild the router's
                # outstanding books in place — no re-dispatch, no re-decode.
                for rid in ack.get("rids", []):
                    rec = ledger.get(int(rid))
                    if rec is None or rec.resolved:
                        continue
                    rec.holders.add(idx)
                    router.dispatch(
                        rec.rid, idx, now0,
                        deadline=rec.deadline_abs, prefix_sig=req_sig(rec),
                    )
            # Orphaned in-flight work (admitted, unresolved, held by no
            # adopted replica) re-dispatches from the prompt with its
            # ORIGINAL arrival/deadline — the PR 8 failover bar.
            for rid, rec in sorted(ledger.items()):
                if rec.resolved or rec.holders:
                    continue
                rec.redispatched = True
                redispatch_queue.append(rid)
                redispatched += 1
                self.registry.counter(FLEET_REDISPATCH).inc()
                journal.record("redispatch", rid=rid)
            # Trace entries the corpse already admitted must not be
            # admitted twice: multiset-match on (arrival, prompt, max_new,
            # tenant) — exact floats, JSON round-trips losslessly.
            admitted: Counter = Counter(
                (jr.get("arrival_rel"), tuple(jr["prompt"]),
                 int(jr["max_new"]), str(jr.get("tenant", "default")))
                for jr in recovered["ledger"].values()
                if not jr.get("spike")
            )
            kept = []
            for e in pending:
                key = (
                    float(e["arrival"]),
                    tuple(int(t) for t in e["prompt"]),
                    int(e["max_new"]), str(e.get("tenant", "default")),
                )
                if admitted.get(key, 0) > 0:
                    admitted[key] -= 1
                    continue
                kept.append(e)
            # A load_spike burst is synthetic: its un-admitted tail exists
            # only in the journal and must be re-injected for the spike
            # recovery to ever close.
            spike_admits: Counter = Counter(
                (jr.get("arrival_rel"), tuple(jr["prompt"]))
                for jr in recovered["ledger"].values() if jr.get("spike")
            )
            spike_backlog: list[dict] = []
            for fire in recovered["fires"]:
                for e in fire.get("burst") or []:
                    key = (
                        float(e["arrival"]),
                        tuple(int(t) for t in e["prompt"]),
                    )
                    if spike_admits.get(key, 0) > 0:
                        spike_admits[key] -= 1
                        continue
                    spike_backlog.append(e)
            pending = deque(sorted(
                kept + spike_backlog, key=lambda e: e["arrival"]
            ))
            # Chaos books replay: re-mark every journaled fire, pair the
            # journaled recoveries, and take ownership of what the corpse
            # left open. The supervisor kinds close HERE — re-adoption is
            # their recovery, with latency spanning the crash itself
            # (CLOCK_MONOTONIC is system-wide, so the corpse's fire stamp
            # is directly comparable).
            if injector is not None:
                recov_left: Counter = Counter(recovered["recovery_kinds"])
                for fire in recovered["fires"]:
                    kind = str(fire["kind"])
                    injector.fire_observed(kind)
                    if recov_left.get(kind, 0) > 0:
                        recov_left[kind] -= 1
                        injector.record_recovery(kind, latency_s=0.0)
                        continue
                    if kind in ("supervisor_kill", "supervisor_hang"):
                        injector.record_recovery(
                            kind,
                            latency_s=time.monotonic() - float(fire["t"]),
                        )
                        journal.record("chaos_recovery", kind=kind)
                    elif kind == "load_spike":
                        open_rids = {
                            rid for rid, jr in recovered["ledger"].items()
                            if jr.get("spike") and not ledger[rid].resolved
                        }
                        if not open_rids and not spike_backlog:
                            injector.record_recovery(kind, latency_s=0.0)
                            journal.record("chaos_recovery", kind=kind)
                        else:
                            pending_recoveries.append({
                                "kind": kind, "replica": -1,
                                "detected": now0,
                                "rids": set(open_rids),
                                "awaiting": len(spike_backlog),
                            })
                    else:
                        pending_recoveries.append({
                            "kind": kind,
                            "replica": int(fire.get("replica", -1)),
                            "detected": now0, "rids": set(),
                        })
            phase = (
                "during" if pending_recoveries
                else ("after" if recovered["fires"] else "before")
            )
            # An unfinished scale-down resumes its drain here.
            if recovered["retiring"] is not None:
                retiring = recovered["retiring"]
                retire_stop_sent = False
                router.mark_retired(retiring)
            # Adopted workers kept their brownout stage; respawned ones
            # booted at 0 — re-broadcast so the ladder is uniform again.
            if brownout_stage > 0:
                for r in replicas.values():
                    self._send(r, {"op": "brownout", "stage": brownout_stage})
            replay_s = time.monotonic() - replay_wall0
            self.registry.gauge(SUP_REPLAY_S).set(replay_s)
            journal.record(
                "recovered", readopted=adopted_n, respawned=respawned_n,
                redispatched=len(redispatch_queue), replay_s=replay_s,
            )
            self._log(
                f"incarnation {self.incarnation}: journal replay + orphan "
                f"probe took {replay_s:.2f}s — re-adopted {adopted_n}, "
                f"respawned {respawned_n}, re-dispatching "
                f"{len(redispatch_queue)} orphaned request(s), "
                f"{completed} completion(s) carried over"
            )

        try:
            while True:
                now = time.monotonic()
                if t0 is None and any(
                    r.ready for r in replicas.values()
                ):
                    t0 = now
                    journal.record("clock_start", t0=t0)
                if now - start > self.timeout_s:
                    raise FleetFailure(
                        f"run exceeded timeout_s={self.timeout_s}"
                    )

                # 1. liveness + telemetry in.
                for rep in replicas.values():
                    payload = Heartbeat.read(rep.dir / "heartbeat.json")
                    rep.tracker.observe(0, payload)
                    if payload is not None:
                        router.observe(rep.idx, payload)
                        rep.last_hb = payload

                # 2. worker messages.
                for rep in replicas.values():
                    msgs, rep.outbox_offset = _tail_jsonl(
                        rep.dir / "outbox.jsonl", rep.outbox_offset
                    )
                    for m in msgs:
                        handle_msg(rep, m)

                # 2.5 supervisor-level chaos: the control plane detonates
                # ITSELF (SIGKILL mid-surge / wedge forever), orphaning
                # every live worker. The fire is journaled write-ahead —
                # the dying incarnation's registry is lost, and the journal
                # is how the next incarnation inherits the fire into its
                # books (and closes it by re-adopting the fleet).
                if injector is not None:
                    injector.check_supervisor_fault(
                        step=completed,
                        on_fire=lambda kind: journal.record(
                            "chaos_fire", kind=kind, replica=-1
                        ),
                    )

                # 3. dead replicas (exit observed).
                for rep in replicas.values():
                    if rep.proc is not None and rep.proc.poll() is not None:
                        if rep.stopped is not None:
                            continue  # clean shutdown we asked for
                        handle_failure(
                            rep, "replica_kill",
                            f"exit {rep.proc.poll()}",
                        )

                # 4. hung replicas (alive, progress frozen past deadline).
                for rep in replicas.values():
                    if (
                        rep.proc is not None
                        and rep.proc.poll() is None
                        and rep.tracker.stalled(0)
                    ):
                        handle_failure(
                            rep, "replica_hang",
                            "progress stalled "
                            f"{rep.tracker.progress_age_s(0):.1f}s "
                            "(heartbeat daemon still beating)",
                        )

                # 5. re-dispatch orphans of the dead (original arrival AND
                # deadline ride along — failover never refreshes a budget).
                while redispatch_queue:
                    rid = redispatch_queue[0]
                    target = router.select(
                        now, prefix_sig=req_sig(ledger[rid])
                    )
                    if target is None:
                        break  # whole fleet cold; retry next tick
                    redispatch_queue.popleft()
                    dispatch(rid, target, now)

                # 6. hedged retries for the slow.
                for rid, target in router.maybe_hedge(now):
                    rec = ledger[rid]
                    hedged_primary.setdefault(
                        rid,
                        next(iter(rec.holders)) if rec.holders else -1,
                    )
                    journal.record(
                        "dispatch", rid=rid, target=target, hedge=True
                    )
                    self._send(replicas[target], {
                        "op": "req", "rid": rid, "prompt": rec.prompt,
                        "max_new": rec.max_new, "arrival": rec.arrival_abs,
                        "deadline": rec.deadline_abs, "tenant": rec.tenant,
                        "trace": f"r{rid}",
                    })
                    rec.holders.add(target)
                    if self.tracer is not None:
                        self.tracer.event(
                            "dispatch", trace=f"r{rid}", t=now,
                            replica=target, kind="hedge",
                        )
                    self._log(
                        f"hedge: rid {rid} duplicated onto replica {target}"
                    )

                # 7. rolling weight swap, under load.
                if (
                    swap_seed is not None
                    and not swap["performed"]
                    and swap_t0 is None
                    and completed >= (swap_at or 0)
                ):
                    swap_queue = sorted(replicas)
                    swap_stage = "drain"
                    swap_t0 = now
                    swap_mark = completed
                    target_version += 1
                    self._log(
                        f"swap: rolling weight swap to seed {swap_seed} "
                        f"(version {target_version}) across "
                        f"{len(swap_queue)} replicas"
                    )
                if swap_stage == "drain" and swap_queue:
                    cur = replicas[swap_queue[0]]
                    router.exclude(cur.idx)
                    if (
                        cur.ready
                        and cur.proc is not None
                        and cur.proc.poll() is None
                        and not router.outstanding_on(cur.idx)
                    ):
                        cur.seed = swap_seed
                        cur.version = target_version
                        self._send(cur, {
                            "op": "swap", "seed": swap_seed,
                            "version": target_version,
                        })
                        swap_stage = "await"
                if swap_t0 is not None and not swap_queue and not swap[
                    "performed"
                ]:
                    swap["performed"] = True
                    swap["drain_s"] = now - swap_t0
                    swap["completions_during"] = completed - swap_mark
                    journal.record("swap_done", version=target_version)
                    self._log(
                        f"swap: fleet at version {target_version} in "
                        f"{swap['drain_s']:.2f}s "
                        f"({swap['completions_during']} requests completed "
                        "mid-swap)"
                    )

                # 7.5 autoscale control tick (inert without a policy, and
                # held until the trace clock starts — scaling a fleet that
                # has never served would react to warmup, not load).
                if policy is not None and t0 is not None:
                    # load_spike chaos: a planned synthetic burst detonates
                    # once `at` requests have completed — the scale-up path
                    # must absorb it (recovery closes when every spike
                    # request resolves).
                    if injector is not None:
                        for s in injector.plan.specs:
                            if (
                                s.kind == "load_spike"
                                and not s.fired
                                and completed >= s.at
                            ):
                                injector.fire_observed("load_spike")
                                hi = max(
                                    int(
                                        self.model_spec.get(
                                            "vocab_size", 256
                                        )
                                    )
                                    - 1,
                                    2,
                                )
                                burst = [
                                    {
                                        "arrival": now - t0,
                                        "prompt": [
                                            (13 * i + j) % hi
                                            for j in range(8)
                                        ],
                                        "max_new": 4,
                                        "spike": True,
                                    }
                                    for i in range(8)
                                ]
                                # The burst is synthetic — it exists only
                                # in memory, so the journal must carry the
                                # entries themselves or a successor could
                                # never finish absorbing the spike.
                                journal.record(
                                    "chaos_fire", kind="load_spike",
                                    replica=-1, burst=burst,
                                )
                                pending = deque(sorted(
                                    list(pending) + burst,
                                    key=lambda e: e["arrival"],
                                ))
                                pending_recoveries.append({
                                    "kind": "load_spike", "replica": -1,
                                    "detected": now, "rids": set(),
                                    "awaiting": len(burst),
                                })
                                phase = "during"
                                self._log(
                                    f"chaos: load_spike — injected "
                                    f"{len(burst)} synthetic request(s)"
                                )

                    # Retire drain progression (at most one in flight).
                    if retiring is not None:
                        vrep = replicas[retiring]
                        if vrep.stopped is not None:
                            journal.record("retired", idx=retiring)
                            self._kill(vrep)
                            del replicas[retiring]
                            router.remove_replica(retiring)
                            retired += 1
                            self._log(
                                f"autoscale: replica {retiring} retired "
                                f"(fleet now {len(replicas)})"
                            )
                            retiring = None
                            retire_stop_sent = False
                        elif not vrep.ready:
                            # Died mid-drain and was respawned by the
                            # failure path: re-drain once it's back.
                            retire_stop_sent = False
                        elif (
                            not retire_stop_sent
                            and not router.outstanding_on(retiring)
                        ):
                            # Zero-drop drain complete: ask it to stop.
                            self._send(vrep, {"op": "stop"})
                            retire_stop_sent = True

                    # Assemble this tick's load signal through the shared
                    # helper (autoscaler.build_load_signal) — the
                    # simulator builds its signal through the SAME code,
                    # so sim and production cannot drift on how load is
                    # measured.
                    due = sum(
                        1 for e in pending if t0 + e["arrival"] <= now
                    )
                    slots_cap = int(self.engine_spec.get("max_slots", 1))
                    sig = build_load_signal(
                        (
                            ReplicaView(
                                idx=r.idx,
                                ready=r.ready,
                                alive=(
                                    r.proc is not None
                                    and r.proc.poll() is None
                                ),
                                retiring=r.idx == retiring,
                                queue_depth=(
                                    int(r.last_hb.get("queue_depth", 0))
                                    if r.last_hb is not None else 0
                                ),
                                outstanding=len(
                                    router.outstanding_on(r.idx)
                                ),
                                ttft_p50=(
                                    float(r.last_hb.get("ttft_p50") or 0.0)
                                    if r.last_hb is not None else 0.0
                                ),
                            )
                            for r in replicas.values()
                        ),
                        backlog=due + len(redispatch_queue),
                        slots_cap=slots_cap,
                        shed_total=sum(
                            1
                            for rec in ledger.values()
                            if rec.shed_reason is not None
                        ),
                        tokens_in_flight=sum(
                            len(rec.prompt) + rec.max_new
                            for rec in ledger.values()
                            if not rec.resolved
                        ),
                    )
                    self.registry.gauge("fleet_replicas").set(len(replicas))

                    decision = (
                        policy.decide(now, sig)
                        if retiring is None and sig.ready > 0
                        else None
                    )
                    if decision is not None:
                        direction, outcome = decision
                        victim: Optional[int] = None
                        if direction == "down" and outcome == "ok":
                            cand = {
                                r.idx: (
                                    router.prefix_ledger_size(r.idx),
                                    len(router.outstanding_on(r.idx)),
                                )
                                for r in replicas.values()
                                if r.ready
                                and r.proc is not None
                                and r.proc.poll() is None
                            }
                            if cand:
                                victim = policy.pick_retire(cand)
                            else:
                                outcome = "vetoed:no_ready_candidate"
                                policy.note_scale_event(now)
                        scale_events += 1
                        self.registry.counter("fleet_scale_total").inc()
                        self.registry.counter(labeled(
                            "fleet_scale_total",
                            direction=direction,
                            outcome="ok" if outcome == "ok" else "vetoed",
                        )).inc()
                        journal.record(
                            "scale", direction=direction,
                            outcome="ok" if outcome == "ok" else "vetoed",
                        )
                        if outcome != "ok":
                            vetoed += 1
                            self._log(
                                f"autoscale: {direction} {outcome} "
                                f"(load/replica "
                                f"{sig.load_per_replica:.2f})"
                            )
                        elif direction == "up":
                            policy.note_scale_event(now)
                            newr = _Replica(
                                idx=next_idx,
                                # Spawn at the fleet's CURRENT weights —
                                # a scale-up during/after a rolling swap
                                # must serve the target version.
                                seed=(
                                    swap_seed
                                    if target_version > 0 else self.seed
                                ),
                                version=target_version,
                            )
                            next_idx += 1
                            replicas[newr.idx] = newr
                            router.add_replica(
                                newr.idx,
                                role="disagg" if self.disagg else None,
                            )
                            # A cold replica never eats live traffic:
                            # excluded until its ready-ack lands (the
                            # ready handler includes it).
                            router.exclude(newr.idx)
                            self._spawn(newr)
                            spawned += 1
                            scale_ups += 1
                            up_times.append(now - t0)
                            forecast_note = (
                                f", forecast {policy.last_forecast:.2f}"
                                if policy.last_forecast is not None else ""
                            )
                            self._log(
                                f"autoscale: scale-up -> replica "
                                f"{newr.idx} warming (load/replica "
                                f"{sig.load_per_replica:.2f}"
                                f"{forecast_note}, fleet "
                                f"{len(replicas)})"
                            )
                            # scale_during_failure chaos: SIGKILL a live
                            # replica during the `at`-th scale-up, while
                            # the new replica is still warming.
                            if injector is not None:
                                for s in injector.plan.specs:
                                    if (
                                        s.kind == "scale_during_failure"
                                        and not s.fired
                                        and s.at <= scale_ups
                                    ):
                                        live = [
                                            r
                                            for r in replicas.values()
                                            if r.idx != newr.idx
                                            and r.idx != retiring
                                            and r.ready
                                            and r.proc is not None
                                            and r.proc.poll() is None
                                        ]
                                        if live:
                                            handle_failure(
                                                min(
                                                    live,
                                                    key=lambda r: r.idx,
                                                ),
                                                "scale_during_failure",
                                                "chaos SIGKILL "
                                                "mid-scale-up",
                                            )
                                        break
                        else:
                            policy.note_scale_event(now)
                            retiring = victim
                            retire_stop_sent = False
                            journal.record("retire_begin", idx=victim)
                            router.mark_retired(victim)
                            self._log(
                                f"autoscale: scale-down — retiring "
                                f"replica {victim} (prefix ledger "
                                f"{cand[victim][0]}, outstanding "
                                f"{cand[victim][1]})"
                            )

                    # Brownout ladder: escalate/clear + broadcast changes
                    # (held while nothing is ready — a fleet that cannot
                    # serve is cold, not saturated).
                    stage = (
                        policy.brownout(now, sig)
                        if sig.ready > 0 else brownout_stage
                    )
                    if stage != brownout_stage:
                        self.registry.counter("fleet_brownout_total").inc()
                        self.registry.counter(labeled(
                            "fleet_brownout_total", stage=str(stage)
                        )).inc()
                        journal.record("brownout", stage=stage)
                        self._log(
                            f"brownout: stage {brownout_stage} -> {stage} "
                            f"(load/replica {sig.load_per_replica:.2f})"
                        )
                        for r in replicas.values():
                            if (
                                r.proc is not None
                                and r.proc.poll() is None
                            ):
                                self._send(
                                    r,
                                    {"op": "brownout", "stage": stage},
                                )
                        brownout_stage = stage
                        brownout_stage_max = max(brownout_stage_max, stage)

                # 8. admit due trace entries (held until the trace clock
                # starts at first ready).
                while (
                    t0 is not None
                    and pending
                    and t0 + pending[0]["arrival"] <= now
                ):
                    target = router.select(
                        now,
                        prefix_sig=prefix_signature(
                            [int(t) for t in pending[0]["prompt"]],
                            block_size,
                        ),
                    )
                    if target is None:
                        break  # fleet saturated/cold — hold at the door
                    e = pending.popleft()
                    rid = next_rid
                    next_rid += 1
                    deadline = e.get("deadline") or 0
                    ledger[rid] = _Req(
                        rid=rid,
                        prompt=[int(t) for t in e["prompt"]],
                        max_new=int(e["max_new"]),
                        arrival_abs=t0 + float(e["arrival"]),
                        deadline_abs=(
                            t0 + float(e["arrival"]) + float(deadline)
                            if deadline > 0 else None
                        ),
                        tenant=str(e.get("tenant", "default")),
                    )
                    # Admission is journaled with both clocks: the absolute
                    # stamps let a successor re-dispatch with the ORIGINAL
                    # arrival/deadline, the relative one lets it match this
                    # entry against its own copy of the trace.
                    journal.record(
                        "admit", rid=rid, prompt=ledger[rid].prompt,
                        max_new=ledger[rid].max_new,
                        arrival_rel=float(e["arrival"]),
                        arrival_abs=ledger[rid].arrival_abs,
                        deadline_abs=ledger[rid].deadline_abs,
                        tenant=ledger[rid].tenant,
                        spike=bool(e.get("spike")),
                    )
                    if e.get("spike"):
                        # Tie the admitted spike request back to its open
                        # load_spike recovery.
                        for pr in pending_recoveries:
                            if (
                                pr["kind"] == "load_spike"
                                and pr.get("awaiting")
                            ):
                                pr["awaiting"] -= 1
                                pr["rids"].add(rid)
                                break
                    dispatch(rid, target, now)

                # 9. done?
                if (
                    not pending
                    and not redispatch_queue
                    and swap_stage is None
                    and retiring is None
                    and all(r.resolved for r in ledger.values())
                    and (swap["performed"] or swap_seed is None)
                ):
                    break
                if phase == "during" and not pending_recoveries:
                    phase = "after"
                time.sleep(self.poll_interval_s)

            if phase == "during" and not pending_recoveries:
                phase = "after"
            stopping = True
            for rep in replicas.values():
                if rep.proc is not None and rep.proc.poll() is None:
                    self._send(rep, {"op": "stop"})
            stop_deadline = time.monotonic() + 15.0
            while time.monotonic() < stop_deadline and any(
                rep.stopped is None
                and rep.proc is not None
                and rep.proc.poll() is None
                for rep in replicas.values()
            ):
                for rep in replicas.values():
                    msgs, rep.outbox_offset = _tail_jsonl(
                        rep.dir / "outbox.jsonl", rep.outbox_offset
                    )
                    for m in msgs:
                        handle_msg(rep, m)
                time.sleep(self.poll_interval_s)
        except BaseException as err:
            # Watchdog timeout, spent restart budget, operator interrupt —
            # whatever aborts the run dumps the supervisor's ring first.
            if self.tracer is not None:
                self.tracer.dump_flight(
                    f"fleet-abort-{type(err).__name__}"
                )
            raise
        finally:
            for rep in replicas.values():
                self._kill(rep)
            journal.record("supervisor_stop", pid=os.getpid())
            journal.close()
            self.journal = None

        # -- accounting out ---------------------------------------------------
        def pct(vals: list[float], q: float) -> Optional[float]:
            if not vals:
                return None
            d = sorted(vals)
            return d[int(q * (len(d) - 1))]

        shed: dict[str, int] = {}
        shed_by_tenant: dict[str, dict[str, int]] = {}
        for rec in ledger.values():
            if rec.shed_reason is not None:
                shed[rec.shed_reason] = shed.get(rec.shed_reason, 0) + 1
                per = shed_by_tenant.setdefault(rec.tenant, {})
                per[rec.shed_reason] = per.get(rec.shed_reason, 0) + 1
        dropped = sum(1 for rec in ledger.values() if not rec.resolved)
        compile_flat = all(r.compile_flat for r in replicas.values())
        chaos_balanced = injector.balanced() if injector else None
        if injector is not None:
            self._log(injector.summary())
        ttft_summary = {
            f"{ph}_{name}": pct(vals, q)
            for ph, vals in ttft_by_phase.items()
            for name, q in (("p50", 0.50), ("p99", 0.99))
        }
        scale_balanced = scale_events == spawned + retired + vetoed
        ok = (
            dropped == 0
            and compile_flat
            and (chaos_balanced is not False)
            and (swap["performed"] or swap_seed is None)
            and scale_balanced
        )
        values: dict[str, Any] = {
            **self.registry.snapshot(),
            "ok": ok,
            "replicas": self.num_replicas,
            "completed_total": completed,
            "shed_total": sum(shed.values()),
            "dropped_total": dropped,
            "redispatched_total": redispatched,
            "swap_performed": swap["performed"],
            "swap_drain_s": swap["drain_s"],
            "swap_completions_during": swap["completions_during"],
            "compile_flat": compile_flat,
        }
        # snapshot() already carries supervisor_incarnation and the
        # readopted/respawned counters; these flat copies make the
        # cross-incarnation reconciliation greppable in fleet_summary.
        values["supervisor_readopted"] = adopted_n
        values["supervisor_respawned"] = respawned_n
        scale_summary: dict[str, Any] = {}
        if self.autoscale is not None:
            scale_summary = {
                "events": scale_events,
                "spawned": spawned,
                "retired": retired,
                "vetoed": vetoed,
                "brownout_stage_max": brownout_stage_max,
                "replicas_final": len(replicas),
                #: trace-clock seconds of each scale-up spawn (the
                #: predictive drill checks these against the crowd peak).
                "up_times": [round(t, 3) for t in up_times],
            }
            values.update({
                "scale_events": scale_events,
                "scale_spawned": spawned,
                "scale_retired": retired,
                "scale_vetoed": vetoed,
                "scale_balanced": scale_balanced,
                "brownout_stage_max": brownout_stage_max,
                "replicas_final": len(replicas),
            })
        if chaos_balanced is not None:
            values["chaos_balanced"] = chaos_balanced
        for key, v in ttft_summary.items():
            if v is not None:
                values[f"ttft_{key}"] = v
        self.registry.emit("fleet_summary", values)
        result = FleetResult(
            ok=ok,
            completed=completed,
            shed=shed,
            dropped=dropped,
            restarts=restarts,
            failures=failures,
            redispatched=redispatched,
            compile_flat=compile_flat,
            chaos_balanced=chaos_balanced,
            ttft=ttft_summary,
            swap=swap,
            requests={
                rid: {
                    "tokens": rec.tokens,
                    "version": rec.version,
                    "prompt": rec.prompt,
                    "max_new": rec.max_new,
                    "redispatched": rec.redispatched,
                    "ttft": rec.ttft,
                    "tenant": rec.tenant,
                }
                for rid, rec in ledger.items()
                if rec.tokens is not None
            },
            snapshot=self.registry.snapshot(),
            scale=scale_summary,
            shed_by_tenant=shed_by_tenant,
            incarnation=int(self.incarnation or 0),
            readopted=adopted_n,
            respawned=respawned_n,
        )
        if self.tracer is not None:
            self.tracer.close()
        if self._own_registry:
            self.registry.close()
        return result

    def swap_weights(self, entries: list[dict], *, seed: int,
                     swap_at: int = 0) -> FleetResult:
        """Convenience wrapper: :meth:`run` with a rolling weight swap —
        drain each replica (in-flight requests finish, new ones route to
        peers), swap params from ``seed`` in place with zero retraces,
        re-include, next replica. The drill calls :meth:`run` directly to
        compose the swap with chaos; this entry exists for callers that
        only want the zero-downtime deploy."""
        return self.run(entries, swap_at=swap_at, swap_seed=seed)


if __name__ == "__main__":
    sys.exit(worker_main())
