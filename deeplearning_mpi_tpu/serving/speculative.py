"""Draft-model speculative decoding for the paged serving engine.

Plain decode emits one token per jitted step, and at serving batch sizes
each step's cost is dominated by streaming the target model's weights —
the arithmetic for one token per row is nearly free next to the memory
traffic. Speculative decoding (Leviathan et al.; Chen et al. 2023) buys
more tokens per weight-stream: a cheap DRAFT model proposes ``k`` tokens
per sequence, and the target model scores all ``k + 1`` positions in ONE
batched forward (``PagedForward.verify_step``) whose weight traffic is the
same as a single decode step. Because this engine is greedy-only, the
acceptance rule collapses to **exact greedy match**: a proposal is
accepted iff it equals the target's own argmax at that position, so the
emitted stream is bit-identical to plain greedy decode for ANY draft —
a bad draft costs throughput (rejections), never correctness. That is the
same parity oracle ``tests/test_serving.py`` pins for the plain engine,
now covering the speculative path.

The draft here is a full ``TransformerLM`` sharing the target's vocab —
usually the target's own first N layers via
``models.transformer.truncate_lm_params`` (a "self-draft": the tied
embedding doubles as the draft's output head, so the draft reuses the
target's logit geometry and needs no training of its own), but any dense
config/params pair works. The draft keeps its OWN paged KV pools (its
layer count and head dims differ from the target's) written through the
SAME block tables and free list: block geometry (``block_size``,
``max_blocks_per_seq``) is an engine property, not a model property, so
one allocation decision covers both models and eviction/rollback never
needs draft-specific bookkeeping.

Draft KV discipline (the part that is easy to get wrong): before a
propose loop at known length ``L``, the draft's cache must be correct for
positions ``0..L-2`` — position ``L-1`` belongs to the token being fed.
The prompt part comes from ``prefill_chunk`` (run alongside the target's
prefill). During propose, step ``j`` writes position ``L-1+j``; the
accepted prefix of those writes used exactly the tokens that were
emitted, so the invariant self-maintains, and the loop deliberately runs
one step past the last collected proposal (``j = n_prop``) so a FULLY
accepted round still leaves position ``L'-2`` written. Rejected-tail
positions hold garbage that the next round overwrites at the exact step
each position first becomes causally visible — the same
overwrite-before-read argument the engine makes for recycled blocks.
Crash recovery and eviction need no draft handling at all: re-prefill
rewrites the draft pools through the same tables.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_mpi_tpu.models.transformer import TransformerConfig
from deeplearning_mpi_tpu.serving.kv_pool import init_kv_buffers

__all__ = ["SpeculativeDecoder"]


class SpeculativeDecoder:
    """The draft side of speculative decoding: owns the draft model's
    params, paged KV pools, and jitted propose/prefill programs. The
    engine drives it with host numpy arrays shaped exactly like its own
    slot-indexed decode inputs; :meth:`propose` is also the seam tests
    override to script adversarial or oracle proposal streams (the
    engine's verify step guards correctness either way)."""

    def __init__(
        self,
        config: TransformerConfig,
        params: Any,
        *,
        target_config: TransformerConfig,
        engine: Any,  # EngineConfig (not imported: engine.py imports us)
        dtype: Any,
        tick: Callable[[], None] | None = None,
        donate: tuple[int, ...] = (),
        kv_dtype: Any = None,
        kv_buffers: Any = None,
        prefix_cache: bool = False,
    ) -> None:
        if config.vocab_size != target_config.vocab_size:
            raise ValueError(
                "draft and target must share one tokenizer: vocab "
                f"{config.vocab_size} != {target_config.vocab_size}"
            )
        if config.moe_experts > 0:
            raise NotImplementedError("draft model must be dense (no MoE)")
        if "kernel" not in params["layer_0"]["attn"]["q_proj"]:
            raise NotImplementedError(
                "draft takes the raw f32 param tree (no quantized trees)"
            )
        # engine.py imports this module lazily; import the forward the same
        # way to keep the cycle one-directional at module load.
        from deeplearning_mpi_tpu.serving.engine import KVBuffers, PagedForward

        self.config = config
        self.params = params
        self.engine = engine
        self.spec_k = engine.spec_k
        self._fwd = PagedForward(
            config, engine, dtype, tick=tick, kv_dtype=kv_dtype
        )
        # Same storage dtype as the target: the int8 capacity win applies
        # to the draft's pools too. ``kv_buffers`` injects a SHARED holder
        # (disaggregation: the prefill role's draft writes the prompt, the
        # decode role's draft proposes from it); omitted, the draft owns
        # its pools privately, exactly as before.
        if kv_buffers is None:
            kv_buffers = KVBuffers(init_kv_buffers(
                config.num_layers, engine.num_blocks, engine.block_size,
                config.num_kv_heads or config.num_heads, config.head_dim,
                kv_dtype if kv_dtype is not None else dtype,
            ))
        self._kvh = kv_buffers
        # The draft always decodes through the einsum schedule: its
        # gathered KV shape differs from the target's, so target bucket
        # tuning does not transfer, and draft steps are small enough that
        # kernel dispatch has nothing to win on CPU-class drafts.
        self._decode_jit = jax.jit(
            functools.partial(self._fwd.decode_step, use_kernel=False),
            donate_argnums=donate,
        )
        self._prefill_jit = jax.jit(
            self._fwd.prefill_chunk, donate_argnums=donate
        )
        self._decode_fn: Callable[..., Any] = self._decode_jit
        self._prefill_fn: Callable[..., Any] = self._prefill_jit
        # Prefix-cache CoW mirror: an adopted prefix exists in the draft's
        # pools too (written by the original prefill through the shared
        # tables), so the engine mirrors every target-pool block copy here
        # — cache hits then keep the draft's prefix KV valid and the
        # acceptance rate intact.
        self._copy_jit = None
        self._copy_fn: Callable[..., Any] | None = None
        if prefix_cache:
            self._copy_jit = jax.jit(
                self._fwd.copy_block, donate_argnums=(0,) if donate else ()
            )
            self._copy_fn = self._copy_jit

    @property
    def _kv(self) -> tuple[Any, ...]:
        return self._kvh.bufs

    @_kv.setter
    def _kv(self, bufs: tuple[Any, ...]) -> None:
        self._kvh.bufs = bufs

    # -- warmup (driven by ServingEngine.warmup) -----------------------------
    def register_warmup(self, reg: Any) -> None:
        e = self.engine
        reg.register(
            "serve_draft_decode_step", self._decode_jit,
            self.params, self._kv,
            jnp.zeros((e.max_slots, e.max_blocks_per_seq), jnp.int32),
            jnp.zeros((e.max_slots,), jnp.int32),
            jnp.zeros((e.max_slots,), jnp.int32),
            jnp.zeros((e.max_slots,), bool),
        )
        reg.register(
            "serve_draft_prefill_chunk", self._prefill_jit,
            self.params, self._kv,
            jnp.zeros((e.max_blocks_per_seq,), jnp.int32),
            jnp.zeros((e.prefill_chunk,), jnp.int32),
            jnp.int32(0), jnp.int32(1),
        )
        if self._copy_jit is not None:
            reg.register(
                "serve_draft_copy_block", self._copy_jit,
                self._kv, jnp.int32(0), jnp.int32(0),
            )

    def adopt_warmup(self, programs: dict[str, Any]) -> None:
        from deeplearning_mpi_tpu.compiler import aot

        self._decode_fn = aot.WarmProgram(
            programs["serve_draft_decode_step"], self._decode_jit
        )
        self._prefill_fn = aot.WarmProgram(
            programs["serve_draft_prefill_chunk"], self._prefill_jit
        )
        if self._copy_jit is not None:
            self._copy_fn = aot.WarmProgram(
                programs["serve_draft_copy_block"], self._copy_jit
            )

    def pretrace_width(
        self, tables: Any, idle: Any, off: Any
    ) -> None:
        """Compile the draft decode program for one narrower gather-width
        bucket (ServingEngine.warmup drives this with all-inactive rows —
        scratch-block writes, harmless execution)."""
        self._kv, _ = self._decode_jit(
            self.params, self._kv, tables, idle, idle, off
        )

    # -- engine hooks --------------------------------------------------------
    def copy_block(self, src: int, dst: int) -> None:
        """Mirror the target pools' CoW copy in the draft pools (engine
        ``_phase_cow``; same physical block ids — the tables are shared)."""
        assert self._copy_fn is not None, "draft built without prefix_cache"
        self._kv = self._copy_fn(self._kv, jnp.int32(src), jnp.int32(dst))

    def prefill_chunk(
        self,
        table: np.ndarray,
        chunk: np.ndarray,
        start: int,
        n_valid: int,
    ) -> None:
        """Ingest one prompt chunk into the draft's KV pools (same chunk,
        same block table, draft dims); the logits are discarded — the
        target's prefill owns the first generated token."""
        self._kv, _ = self._prefill_fn(
            self.params, self._kv,
            jnp.asarray(table), jnp.asarray(chunk),
            jnp.int32(start), jnp.int32(n_valid),
        )

    def propose(
        self,
        tables: np.ndarray,   # [S, MB] int32 block tables (0-padded)
        lengths: np.ndarray,  # [S] int32 known tokens per slot
        last: np.ndarray,     # [S] int32 each slot's last known token
        n_prop: np.ndarray,   # [S] int32 proposal budget per slot (<= K)
        active: np.ndarray,   # [S] bool
    ) -> tuple[np.ndarray, int]:
        """Run the draft autoregressively for this engine step.

        Step ``j`` feeds each active row's current token at absolute
        position ``lengths - 1 + j`` (writing its draft K/V there) and
        argmaxes the draft logits into proposal ``j``. Rows whose budget
        is exhausted go inactive (scratch writes, ignored outputs), and
        the loop runs through ``j = max(n_prop)`` — one step PAST the last
        collected proposal — so a fully-accepted round leaves the draft
        cache complete (see the module docstring). Returns the ``[S, K]``
        proposal matrix and the number of draft steps spent (the engine's
        ``spec_draft_steps`` counter).
        """
        S = tables.shape[0]
        K = self.spec_k
        props = np.zeros((S, K), np.int32)
        cur = np.asarray(last, np.int32).copy()
        act_rows = np.asarray(active, bool)
        budget = np.asarray(n_prop, np.int32)
        last_j = int(budget[act_rows].max()) if act_rows.any() else 0
        steps = 0
        for j in range(min(last_j, K) + 1):
            act = act_rows & (j <= budget)
            self._kv, out = self._decode_fn(
                self.params, self._kv,
                jnp.asarray(tables),
                jnp.asarray(lengths + j, dtype=np.int32),
                jnp.asarray(cur), jnp.asarray(act),
            )
            steps += 1
            out_np = np.asarray(jax.device_get(out), np.int32)  # dmt-lint: disable=DMT003 — the draft's one audited fetch per propose step: proposals feed the host-side accept loop
            if j < K:
                take = act & (j < budget)
                props[take, j] = out_np[take]
            cur = np.where(act, out_np, cur).astype(np.int32)
        return props, steps
