"""Deterministic policy-parameter sweep over the fake-clock simulator.

Serving policy knobs (autoscaler hysteresis/cooldown, router hedge
threshold, brownout thresholds, decode-bucket sets, predictive-forecast
horizon) have always been hand-tuned against drills. The simulator makes
them *searchable*: every candidate runs the same trace through the real
policy objects in seconds, scored on **SLO-attained completions per
replica-second** (``SimResult.slo_per_chip``) — attainment alone rewards
overscaling; per-chip scoring charges for the capacity used to buy it.

Winners land in the existing autotune JSON DB
(:class:`~deeplearning_mpi_tpu.compiler.autotune.TuningDB`) under
``simpolicy|<trace_digest>|band:<min>-<max>`` keys — the same
record/lookup/provenance machinery kernel tunings use, keyed by workload
digest so a tuning only applies to the traffic shape it was searched on.

Everything is deterministic: the grid order is the iteration order,
each sim is seedless (the trace carries all randomness), and ties break
toward the earliest candidate.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Iterable, Optional

from deeplearning_mpi_tpu.compiler.autotune import TuningDB
from deeplearning_mpi_tpu.serving.autoscaler import AutoscalerConfig
from deeplearning_mpi_tpu.sim.simulator import FleetSimulator, SimConfig

__all__ = ["SweepResult", "apply_params", "default_grid", "run_sweep"]

_AUTOSCALE_FIELDS = frozenset(
    f.name for f in dataclasses.fields(AutoscalerConfig)
)
_SIM_FIELDS = frozenset(f.name for f in dataclasses.fields(SimConfig))


def apply_params(base: SimConfig, params: dict[str, Any]) -> SimConfig:
    """Overlay one candidate's flat param dict onto a base config.
    Autoscaler knobs route into the nested :class:`AutoscalerConfig`;
    fleet knobs (``hedge_ms``, ``decode_buckets``, ...) into
    :class:`SimConfig` itself. Unknown keys are an error — a typo'd sweep
    axis silently sweeping nothing would invalidate the whole search."""
    auto: dict[str, Any] = {}
    top: dict[str, Any] = {}
    for k, v in params.items():
        if k in _AUTOSCALE_FIELDS:
            auto[k] = v
        elif k in _SIM_FIELDS:
            top[k] = tuple(v) if k == "decode_buckets" else v
        else:
            raise ValueError(f"unknown sweep parameter: {k!r}")
    cfg = base
    if auto:
        cfg = dataclasses.replace(
            cfg, autoscale=dataclasses.replace(cfg.autoscale, **auto)
        )
    if top:
        cfg = dataclasses.replace(cfg, **top)
    return cfg


def default_grid() -> list[dict[str, Any]]:
    """A compact default search: the axes the drills showed matter most.
    The empty dict is the baseline (the base config unchanged) so every
    sweep reports whether tuning beat the defaults at all."""
    grid: list[dict[str, Any]] = [{}]
    for hysteresis_s in (0.2, 0.4):
        for cooldown_s in (0.5, 1.0):
            grid.append(
                {"hysteresis_s": hysteresis_s, "cooldown_s": cooldown_s}
            )
    grid.append({"predictive": True, "forecast_horizon_s": 2.0})
    grid.append({"hedge_ms": 400.0})
    return grid


@dataclasses.dataclass
class SweepResult:
    """Everything one sweep learned, in grid order."""

    key: str
    trials: list[dict[str, Any]]
    winner: dict[str, Any]
    winner_score: float
    baseline_score: Optional[float]
    db_path: Optional[str] = None

    def summary(self) -> dict[str, Any]:
        return {
            "sim_sweep_key": self.key,
            "sim_sweep_trials": len(self.trials),
            "sim_sweep_winner": dict(self.winner),
            "sim_sweep_winner_score": round(self.winner_score, 6),
            "sim_sweep_baseline_score": (
                round(self.baseline_score, 6)
                if self.baseline_score is not None else None
            ),
        }


def run_sweep(
    entries: list[dict],
    base: SimConfig,
    grid: Optional[Iterable[dict[str, Any]]] = None,
    *,
    trace_key: str,
    db: TuningDB | str | Path | None = None,
) -> SweepResult:
    """Run every grid candidate against ``entries`` and record the winner.

    ``trace_key`` is the workload identity — callers pass
    ``traces.trace_digest(entries)`` so the DB key binds the tuning to
    this exact traffic shape. ``db`` may be a :class:`TuningDB`, a path
    (loaded-or-created, then saved), or None (no persistence — tests).
    """
    candidates = list(default_grid() if grid is None else grid)
    if not candidates:
        raise ValueError("run_sweep needs at least one candidate")
    band = (base.autoscale.min_replicas, base.autoscale.max_replicas)
    key = f"simpolicy|{trace_key}|band:{band[0]}-{band[1]}"

    trials: list[dict[str, Any]] = []
    baseline_score: Optional[float] = None
    for params in candidates:
        cfg = apply_params(base, params)
        res = FleetSimulator(cfg).run(entries)
        trial = {
            "params": dict(params),
            "score": res.slo_per_chip,
            "slo_attainment": res.slo_attainment,
            "completed": res.completed,
            "shed_total": res.shed_total,
            "replica_seconds": round(res.replica_seconds, 3),
            "scale_ups": res.scale_ups,
            "brownout_max_stage": res.brownout_max_stage,
        }
        trials.append(trial)
        if not params and baseline_score is None:
            baseline_score = res.slo_per_chip

    best = max(
        range(len(trials)), key=lambda i: (trials[i]["score"], -i)
    )
    winner = dict(candidates[best])
    result = SweepResult(
        key=key,
        trials=trials,
        winner=winner,
        winner_score=trials[best]["score"],
        baseline_score=baseline_score,
    )

    if db is not None:
        tdb = db if isinstance(db, TuningDB) else TuningDB.load(db)
        tdb.record_key(
            key,
            winner,
            candidates=[
                {"params": t["params"], "score": t["score"]} for t in trials
            ],
            score=trials[best]["score"],
            slo_attainment=trials[best]["slo_attainment"],
            trace_requests=len(entries),
        )
        if tdb.path is not None:
            tdb.save()
            result.db_path = str(tdb.path)
    return result
