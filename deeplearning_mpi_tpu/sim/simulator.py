"""Fake-clock fleet simulator over the *real* serving policy objects.

The serving policy stack (``serving/router.py`` selection + hedging,
``serving/autoscaler.py`` decide loop + brownout ladder, the scheduler's
tenant-budget / priority / deadline admission, the prefix-affinity
ledger) is clock-pure by construction — every decision is a function of
(config, injected clock, telemetry). This module exploits that contract:
it instantiates the SAME classes the live fleet runs, injects a
discrete-event fake clock, and replaces only the engine compute with an
analytic :class:`ServiceModel` calibrated from measured TTFT/TPOT
telemetry. No processes spawn, no device work happens, and a whole-day
trace (10^5..10^6 requests) simulates in seconds — which is what makes
the ``sim/search.py`` parameter sweep and the predictive-autoscaler A/B
in ``tests/test_sim.py`` affordable.

What is real (bit-identical objects and code paths to production):
:class:`~..serving.router.Router` scoring/affinity/hedging/dedup,
:class:`~..serving.scheduler.Scheduler` + :class:`~..serving.kv_pool.
PagedKVPool` admission (queue bound, length gate, tenant budgets,
priorities, deadline shed, brownout door), and
:class:`~..serving.autoscaler.AutoscalerPolicy` with the shared
:func:`~..serving.autoscaler.build_load_signal` aggregation.

What is modeled analytically (the documented fidelity limits —
``docs/SIMULATION.md``): prefill/decode service times, batch-size
interference, prefix-cache hit payoff (a flat prefill discount when the
router's affinity ledger says the replica recently served this prefix),
and spawn-to-ready warmup. Per-token KV growth, eviction under OOM, and
speculative decoding are not simulated.
"""

from __future__ import annotations

import dataclasses
import heapq
from collections import deque
from typing import Any, Optional

import numpy as np

from deeplearning_mpi_tpu.serving.autoscaler import (
    AutoscalerConfig,
    AutoscalerPolicy,
    ReplicaView,
    build_load_signal,
)
from deeplearning_mpi_tpu.serving.kv_pool import PagedKVPool
from deeplearning_mpi_tpu.serving.prefix_cache import prefix_signature
from deeplearning_mpi_tpu.serving.router import Router
from deeplearning_mpi_tpu.serving.scheduler import Request, Scheduler

__all__ = ["FleetSimulator", "ServiceModel", "SimConfig", "SimResult"]


@dataclasses.dataclass(frozen=True)
class ServiceModel:
    """Analytic replica service model — the one piece of the fleet the
    simulator replaces. Calibrate from measured telemetry with
    :meth:`from_telemetry` (the calibration test pins sim output against
    a real ``tools/autoscale_drill.py`` run)."""

    #: fixed per-request overhead before the first token (dispatch, queue
    #: pickup, sampling) — the prompt-independent part of TTFT.
    ttft_base_s: float = 0.03
    #: prefill seconds per prompt token at batch size 1.
    prefill_s_per_token: float = 0.0005
    #: decode seconds per output token at batch size 1.
    tpot_s: float = 0.01
    #: batch interference: service times stretch by
    #: ``1 + decode_penalty * (active-1)/(max_slots-1)`` — batch of 2
    #: costs nearly what batch of ``max_slots`` does (weight streaming
    #: dominates), so the penalty is sublinear in practice; one linear
    #: knob captures the first-order effect.
    decode_penalty: float = 0.8
    #: prefill cost multiplier when the router's affinity ledger says the
    #: target replica recently served this prefix signature (radix-cache
    #: hit: only the private tail prefills).
    prefix_hit_factor: float = 0.35
    #: spawn-to-ready warmup for scale-up replicas (compile + weight
    #: load); predictive scale-up exists to hide exactly this latency.
    warmup_s: float = 1.0

    @classmethod
    def from_telemetry(
        cls,
        *,
        ttft_p50_s: float,
        tpot_p50_s: float,
        mean_prompt_len: float,
        warmup_s: float = 1.0,
        **overrides: Any,
    ) -> "ServiceModel":
        """Calibrate from measured medians: split observed TTFT evenly
        between fixed overhead and prompt-proportional prefill at the
        measured mean prompt length (the split is a modeling choice; the
        sum — what SLO attainment depends on — matches the measurement
        exactly at the calibration point)."""
        base = 0.5 * ttft_p50_s
        per_tok = 0.5 * ttft_p50_s / max(mean_prompt_len, 1.0)
        return cls(
            ttft_base_s=base,
            prefill_s_per_token=per_tok,
            tpot_s=tpot_p50_s,
            warmup_s=warmup_s,
            **overrides,
        )

    def batch_factor(self, active: int, max_slots: int) -> float:
        return 1.0 + self.decode_penalty * (
            max(active - 1, 0) / max(max_slots - 1, 1)
        )

    def ttft_s(self, prompt_len: int, *, active: int, max_slots: int,
               prefix_hit: bool) -> float:
        prefill = self.prefill_s_per_token * prompt_len
        if prefix_hit:
            prefill *= self.prefix_hit_factor
        return (self.ttft_base_s + prefill) * self.batch_factor(
            active, max_slots
        )

    def decode_s(self, max_new: int, *, active: int, max_slots: int) -> float:
        return (
            max(max_new - 1, 0)
            * self.tpot_s
            * self.batch_factor(active, max_slots)
        )


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Fleet + clock shape for one simulation run. Defaults mirror the
    compressed-clock drills; the sweep varies the policy knobs."""

    #: tick resolution — policy decisions quantize to this.
    dt_s: float = 0.05
    #: autoscaler control-tick cadence (the fleet's phase 7.5).
    control_interval_s: float = 0.25
    #: heartbeat cadence: how often replica snapshots reach the router
    #: (models the one-beat staleness the live scorer sees).
    heartbeat_s: float = 0.1
    initial_replicas: int = 2
    max_slots: int = 8
    max_seq_len: int = 2048
    max_queue: int = 64
    kv_blocks: int = 1024
    kv_block_size: int = 16
    decode_buckets: tuple[int, ...] = ()
    autoscale: AutoscalerConfig = dataclasses.field(
        default_factory=AutoscalerConfig
    )
    #: router knobs (0 disables hedging, as in the live fleet).
    hedge_ms: float = 0.0
    exclusion_s: float = 1.0
    #: per-tenant scheduler policy: name -> {"budget_tokens", "priority"}
    #: (use ``traces.tenant_policies`` so sim and replay agree).
    tenants: dict[str, dict[str, Any]] = dataclasses.field(
        default_factory=dict
    )
    service: ServiceModel = dataclasses.field(default_factory=ServiceModel)
    #: TTFT SLO bound a completion must meet to count as attained.
    slo_ttft_s: float = 2.0
    #: SLO/utilization curve resolution.
    curve_window_s: float = 60.0
    #: after the last arrival, how long the sim drains before declaring
    #: leftovers shed (bounds runaway configs; generous by default).
    drain_grace_s: float = 60.0


@dataclasses.dataclass
class SimResult:
    """Aggregates + time-series curves from one simulated trace."""

    requests: int = 0
    completed: int = 0
    slo_ok: int = 0
    #: terminal sheds by reason (hedge-dedup "cancelled" excluded — the
    #: client got its answer from the winning copy).
    shed: dict[str, int] = dataclasses.field(default_factory=dict)
    hedges_fired: int = 0
    scale_ups: int = 0
    scale_downs: int = 0
    scale_vetoed: int = 0
    #: sim-clock stamps of scale-up spawns (predictive drills assert the
    #: first one lands BEFORE the flash-crowd peak).
    up_times: list[float] = dataclasses.field(default_factory=list)
    brownout_max_stage: int = 0
    #: integral of ready replicas over time — the "chips" denominator.
    replica_seconds: float = 0.0
    sim_seconds: float = 0.0
    #: per-window curves: arrivals/completions/sheds/ready/load/slo_ok.
    curves: list[dict[str, float]] = dataclasses.field(default_factory=list)
    #: winning copies' time-to-first-token samples (sim clock) — the
    #: calibration observable compared against measured drill TTFT.
    ttfts: list[float] = dataclasses.field(default_factory=list)

    @property
    def shed_total(self) -> int:
        return sum(self.shed.values())

    def ttft_quantile(self, q: float) -> Optional[float]:
        if not self.ttfts:
            return None
        xs = sorted(self.ttfts)
        return xs[min(int(q * len(xs)), len(xs) - 1)]

    @property
    def slo_attainment(self) -> float:
        return self.slo_ok / max(self.requests, 1)

    @property
    def slo_per_chip(self) -> float:
        """SLO-attained completions per replica-second — the sweep's
        scoring objective (serving MORE within SLO on FEWER chips wins;
        overscaling buys attainment but pays here)."""
        return self.slo_ok / max(self.replica_seconds, 1e-9)

    def summary(self) -> dict[str, Any]:
        return {
            "sim_requests_total": self.requests,
            "sim_completed_total": self.completed,
            "sim_slo_ok_total": self.slo_ok,
            "sim_shed_total": self.shed_total,
            "sim_shed_by_reason": dict(sorted(self.shed.items())),
            "sim_hedge_fired_total": self.hedges_fired,
            "sim_scale_ups": self.scale_ups,
            "sim_scale_downs": self.scale_downs,
            "sim_scale_vetoed": self.scale_vetoed,
            "sim_up_times": [round(t, 3) for t in self.up_times],
            "sim_brownout_max_stage": self.brownout_max_stage,
            "sim_replica_seconds": round(self.replica_seconds, 3),
            "sim_clock_seconds": round(self.sim_seconds, 3),
            "sim_slo_attainment": round(self.slo_attainment, 6),
            "sim_slo_per_chip": round(self.slo_per_chip, 6),
            "sim_ttft_p50_s": (
                round(self.ttft_quantile(0.5), 4) if self.ttfts else None
            ),
            "sim_ttft_p95_s": (
                round(self.ttft_quantile(0.95), 4) if self.ttfts else None
            ),
        }


@dataclasses.dataclass
class _SimReplica:
    """The simulator's stand-in for one worker process: a REAL scheduler
    over a real KV pool, plus the analytic service state."""

    idx: int
    sched: Scheduler
    #: sim time this replica acks ready (spawn warmup); initial fleet
    #: members are ready at t=0.
    ready_at: float = 0.0
    retiring: bool = False
    #: per-replica TTFT EWMA — what the heartbeat reports as ttft_p50.
    ttft_ewma: float = 0.0

    def ready(self, now: float) -> bool:
        return now >= self.ready_at


class FleetSimulator:
    """Discrete-event replay of a trace against the real policy stack.

    One :meth:`run` call consumes entries in the ``FleetSupervisor.run``
    schema (``traces.to_fleet_entries`` output: prompt as token-id list,
    ``arrival``/``max_new``/optional ``deadline``/``tenant``) and returns
    a :class:`SimResult`. Deterministic: same (config, entries) ->
    identical result, always — no wall clock, no randomness.
    """

    def __init__(self, config: SimConfig,
                 registry: Optional[Any] = None) -> None:
        self.cfg = config
        self.registry = registry
        self._t = 0.0
        self.router = Router(
            range(config.initial_replicas),
            clock=lambda: self._t,
            hedge_ms=config.hedge_ms,
            exclusion_s=config.exclusion_s,
        )
        self.policy = AutoscalerPolicy(config.autoscale)
        self.replicas: dict[int, _SimReplica] = {
            i: self._make_replica(i) for i in range(config.initial_replicas)
        }
        self._next_idx = config.initial_replicas
        #: rid -> {replica: Request} — every live copy (primary + hedge)
        #: of each in-flight request, for hedge-loser cancellation.
        self._copies: dict[int, dict[int, Request]] = {}
        #: rid -> entry (for re-dispatch bookkeeping / prefix sigs).
        self._prompts: dict[int, np.ndarray] = {}
        self._deadlines: dict[int, Optional[float]] = {}
        #: completion events: (t_fin, seq, rid, replica).
        self._events: list[tuple[float, int, int, int]] = []
        self._seq = 0
        self._backlog: deque[tuple[int, dict]] = deque()
        self._last_door_reason = "queue_full"
        self._last_load = 0.0
        self.result = SimResult()

    def _make_replica(self, idx: int, *, ready_at: float = 0.0
                      ) -> _SimReplica:
        cfg = self.cfg
        return _SimReplica(
            idx=idx,
            sched=Scheduler(
                PagedKVPool(
                    num_blocks=cfg.kv_blocks, block_size=cfg.kv_block_size
                ),
                max_slots=cfg.max_slots,
                max_seq_len=cfg.max_seq_len,
                max_queue=cfg.max_queue,
                decode_buckets=cfg.decode_buckets,
                tenants=cfg.tenants,
            ),
            ready_at=ready_at,
        )

    # -- request lifecycle ---------------------------------------------------
    def _record_shed(self, reason: str) -> None:
        self.result.shed[reason] = self.result.shed.get(reason, 0) + 1

    def _copy_gone(self, rid: int, replica: int, reason: str) -> None:
        """A copy of ``rid`` on ``replica`` died (deadline/door/evict).
        The request only becomes a terminal shed when NO copy remains."""
        copies = self._copies.get(rid)
        if copies is not None:
            copies.pop(replica, None)
            if copies:
                return  # the other copy (hedge or primary) still runs
            del self._copies[rid]
        self.router.forget(rid)
        self._prompts.pop(rid, None)
        self._deadlines.pop(rid, None)
        self._record_shed(reason)
        self._window["sheds"] += 1

    def _submit_copy(self, rid: int, replica: int, entry: dict,
                     prompt: np.ndarray) -> Optional[Request]:
        """Build a fresh Request object for one copy and push it through
        the replica's REAL admission stack. Returns the accepted Request,
        or None on a door shed (the reason was already accounted via
        :meth:`_copy_gone` by the caller reading ``req.shed_reason``)."""
        req = Request(
            rid=rid,
            prompt=prompt,
            max_new_tokens=int(entry["max_new"]),
            arrival=float(entry["arrival"]),
            deadline=self._deadlines[rid],
            tenant=str(entry.get("tenant", "default")),
        )
        if not self.replicas[replica].sched.submit(req):
            self._last_door_reason = req.shed_reason or "queue_full"
            return None
        self._copies.setdefault(rid, {})[replica] = req
        return req

    def _dispatch_backlog(self) -> None:
        cfg = self.cfg
        while self._backlog:
            rid, entry = self._backlog[0]
            prompt = self._prompts.get(rid)
            if prompt is None:
                prompt = np.asarray(entry["prompt"], dtype=np.int32)
                self._prompts[rid] = prompt
                dl = entry.get("deadline")
                self._deadlines[rid] = (
                    float(entry["arrival"]) + float(dl)
                    if dl is not None else None
                )
            sig = prefix_signature(prompt, cfg.kv_block_size)
            target = self.router.select(self._t, prefix_sig=sig)
            if target is None:
                return  # whole fleet warming/excluded: retry next tick
            self._backlog.popleft()
            self.router.dispatch(
                rid, target, self._t,
                deadline=self._deadlines[rid], prefix_sig=sig,
            )
            if self._submit_copy(rid, target, entry, prompt) is None:
                self._copy_gone(rid, target, self._last_door_reason)

    def _schedule_completions(self, replica: int,
                              admitted: list[Request]) -> None:
        """Stamp analytic service times on just-admitted requests and
        queue their completion events."""
        cfg = self.cfg
        sim_r = self.replicas[replica]
        active = sim_r.sched.slots_active()
        for req in admitted:
            sig = prefix_signature(req.prompt, cfg.kv_block_size)
            hit = self.router.has_prefix_affinity(replica, sig)
            ttft_service = cfg.service.ttft_s(
                req.prompt_len, active=active, max_slots=cfg.max_slots,
                prefix_hit=hit,
            )
            t_first = self._t + ttft_service
            req.t_first_token = t_first
            fin = t_first + cfg.service.decode_s(
                req.max_new_tokens, active=active, max_slots=cfg.max_slots
            )
            self._seq += 1
            heapq.heappush(
                self._events, (fin, self._seq, req.rid, replica)
            )

    def _complete(self, t_fin: float, rid: int, replica: int) -> None:
        sim_r = self.replicas.get(replica)
        copies = self._copies.get(rid)
        req = copies.get(replica) if copies else None
        if sim_r is None or req is None or req.state.value in (
            "shed", "finished"
        ):
            return  # copy was cancelled/evicted/replica removed meanwhile
        sim_r.sched.finish(req, t_fin)
        copies.pop(replica, None)
        ttft = req.ttft or 0.0
        a = 0.3
        sim_r.ttft_ewma += a * (ttft - sim_r.ttft_ewma)
        verdict, loser = self.router.on_complete(
            rid, replica, t_fin, ttft=ttft
        )
        if verdict != "win":
            return  # duplicate: client already has the stream
        if loser is not None and copies:
            lose_req = copies.pop(loser, None)
            lose_rep = self.replicas.get(loser)
            if lose_req is not None and lose_rep is not None:
                lose_rep.sched.cancel(lose_req)
        self._copies.pop(rid, None)
        self._prompts.pop(rid, None)
        deadline = self._deadlines.pop(rid, None)
        res = self.result
        res.completed += 1
        ok = (deadline is None or t_fin <= deadline) and (
            ttft <= self.cfg.slo_ttft_s
        )
        if ok:
            res.slo_ok += 1
        res.ttfts.append(ttft)
        self._window["completions"] += 1
        self._window["slo_ok"] += 1 if ok else 0

    # -- control tick --------------------------------------------------------
    def _control_tick(self) -> None:
        cfg, res = self.cfg, self.result
        views = [
            ReplicaView(
                idx=r.idx,
                ready=r.ready(self._t),
                alive=True,
                retiring=r.retiring,
                queue_depth=r.sched.queue_depth(),
                outstanding=len(self.router.outstanding_on(r.idx)),
                ttft_p50=r.ttft_ewma,
            )
            for r in self.replicas.values()
        ]
        sig = build_load_signal(
            views,
            backlog=len(self._backlog),
            slots_cap=cfg.max_slots,
            shed_total=res.shed_total,
        )
        self._last_load = sig.load_per_replica
        decision = self.policy.decide(self._t, sig)
        if decision is not None:
            direction, outcome = decision
            if outcome != "ok":
                res.scale_vetoed += 1
            elif direction == "up":
                idx = self._next_idx
                self._next_idx += 1
                self.router.add_replica(idx)
                self.router.exclude(idx)  # cold until ready-ack
                self.replicas[idx] = self._make_replica(
                    idx, ready_at=self._t + cfg.service.warmup_s
                )
                res.scale_ups += 1
                res.up_times.append(self._t)
                self.policy.note_scale_event(self._t)
            else:
                candidates = {
                    r.idx: (
                        self.router.prefix_ledger_size(r.idx),
                        len(self.router.outstanding_on(r.idx)),
                    )
                    for r in self.replicas.values()
                    if r.ready(self._t) and not r.retiring
                }
                if candidates:
                    victim = self.policy.pick_retire(candidates)
                    self.router.mark_retired(victim)
                    self.replicas[victim].retiring = True
                    res.scale_downs += 1
                    self.policy.note_scale_event(self._t)
        stage = self.policy.brownout(self._t, sig)
        res.brownout_max_stage = max(res.brownout_max_stage, stage)
        for r in self.replicas.values():
            if r.sched.brownout_stage != stage:
                r.sched.set_brownout(stage)
        # Reap fully drained retirees.
        for idx in [
            r.idx for r in self.replicas.values()
            if r.retiring
            and r.sched.idle()
            and not self.router.outstanding_on(r.idx)
        ]:
            self.router.remove_replica(idx)
            del self.replicas[idx]

    def _flush_window(self, t_end: float) -> None:
        w = self._window
        w["t"] = round(t_end, 3)
        w["ready"] = sum(
            1 for r in self.replicas.values()
            if r.ready(t_end) and not r.retiring
        )
        w["load"] = round(self._last_load, 4)
        self.result.curves.append(dict(w))
        self._window = {
            "t": 0.0, "arrivals": 0, "completions": 0, "sheds": 0,
            "slo_ok": 0, "ready": 0, "load": 0.0,
        }

    # -- main loop -----------------------------------------------------------
    def run(self, entries: list[dict]) -> SimResult:
        cfg, res = self.cfg, self.result
        res.requests = len(entries)
        arrivals = sorted(
            range(len(entries)), key=lambda i: float(entries[i]["arrival"])
        )
        last_arrival = (
            float(entries[arrivals[-1]]["arrival"]) if entries else 0.0
        )
        ai = 0
        self._window = {
            "t": 0.0, "arrivals": 0, "completions": 0, "sheds": 0,
            "slo_ok": 0, "ready": 0, "load": 0.0,
        }
        self._last_load = 0.0
        next_control = 0.0
        next_heartbeat = 0.0
        next_window = cfg.curve_window_s
        deadline_t = last_arrival + cfg.drain_grace_s
        while True:
            t = self._t
            # 1. arrivals due this tick enter the dispatch backlog.
            while ai < len(arrivals) and (
                float(entries[arrivals[ai]]["arrival"]) <= t
            ):
                i = arrivals[ai]
                self._backlog.append((i, entries[i]))
                self._window["arrivals"] += 1
                ai += 1
            # 2. warming replicas that reached ready join the fleet.
            for r in self.replicas.values():
                if 0.0 < r.ready_at <= t:
                    self.router.include(r.idx)
                    r.ready_at = 0.0  # ready from now on
            # 3. dispatch + per-replica step (deadline shed, admission).
            self._dispatch_backlog()
            for r in list(self.replicas.values()):
                if not r.ready(t):
                    continue
                if r.sched.queue_depth():
                    for req in r.sched.shed_expired(t):
                        self._copy_gone(req.rid, r.idx, "deadline")
                    admitted = r.sched.admit(t)
                    if admitted:
                        self._schedule_completions(r.idx, admitted)
            # 4. completions due this tick (in event order).
            while self._events and self._events[0][0] <= t:
                t_fin, _, rid, replica = heapq.heappop(self._events)
                self._complete(t_fin, rid, replica)
            # 5. hedged retries.
            if cfg.hedge_ms > 0:
                for rid, target in self.router.maybe_hedge(t):
                    prompt = self._prompts.get(rid)
                    primary = self._copies.get(rid)
                    if prompt is None or not primary:
                        continue
                    entry = {
                        "arrival": next(iter(primary.values())).arrival,
                        "max_new": next(
                            iter(primary.values())
                        ).max_new_tokens,
                        "tenant": next(iter(primary.values())).tenant,
                    }
                    if self._submit_copy(
                        rid, target, entry, prompt
                    ) is not None:
                        res.hedges_fired += 1
            # 6. heartbeats: snapshots reach the router at their cadence.
            if t >= next_heartbeat:
                for r in self.replicas.values():
                    self.router.observe(r.idx, {
                        "queue_depth": r.sched.queue_depth(),
                        "slots_active": r.sched.slots_active(),
                        "ttft_p50": r.ttft_ewma,
                    })
                next_heartbeat = t + cfg.heartbeat_s
            # 7. autoscaler control tick.
            if t >= next_control:
                self._control_tick()
                next_control = t + cfg.control_interval_s
            # 8. curves + chip-seconds integral.
            res.replica_seconds += cfg.dt_s * sum(
                1 for r in self.replicas.values()
                if r.ready(t) and not r.retiring
            )
            if t >= next_window:
                self._flush_window(t)
                next_window = t + cfg.curve_window_s
            # 9. done?
            drained = (
                ai >= len(arrivals)
                and not self._backlog
                and not self._events
                and not self._copies
            )
            if drained or t > deadline_t:
                break
            self._t = t + cfg.dt_s
        # Anything still in flight past the grace window is a truncation
        # shed — NEVER silently dropped (the curves and totals must add
        # up to the trace size).
        for rid in list(self._copies):
            for replica in list(self._copies[rid]):
                self._copy_gone(rid, replica, "sim_truncated")
        for rid, _entry in self._backlog:
            self._record_shed("sim_truncated")
        self._backlog.clear()
        self._flush_window(self._t)
        res.sim_seconds = self._t
        if self.registry is not None:
            self._emit_metrics()
        return res

    # -- telemetry out -------------------------------------------------------
    def _emit_metrics(self) -> None:
        """Mirror the result into a telemetry registry under the ``sim_*``
        namespace (docs/OBSERVABILITY.md) so drill summaries and
        ``metrics_report.py`` read simulator output through the same
        pipeline as live serving metrics."""
        from deeplearning_mpi_tpu.telemetry.registry import labeled

        reg = self.registry
        res = self.result
        reg.counter("sim_requests_total").inc(res.requests)
        reg.counter("sim_completed_total").inc(res.completed)
        reg.counter("sim_slo_ok_total").inc(res.slo_ok)
        reg.counter("sim_shed_total").inc(res.shed_total)
        for reason, n in sorted(res.shed.items()):
            reg.counter(labeled("sim_shed_total", reason=reason)).inc(n)
        reg.counter("sim_hedge_fired_total").inc(res.hedges_fired)
        reg.gauge("sim_replica_seconds").set(res.replica_seconds)
        reg.gauge("sim_slo_attainment").set(res.slo_attainment)
        reg.gauge("sim_brownout_max_stage").set(res.brownout_max_stage)
