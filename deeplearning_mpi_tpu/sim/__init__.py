"""Million-user load harness: seeded trace generation, a fake-clock
fleet simulator over the *real* serving policy objects, and a
deterministic policy-parameter sweep (ROADMAP item 3).

Entry points: :func:`~deeplearning_mpi_tpu.sim.traces.generate_entries`
(multi-tenant workload traces in the ``serve_lm`` JSONL replay schema),
:class:`~deeplearning_mpi_tpu.sim.simulator.FleetSimulator` (whole-day
traces in seconds, no engines spawned), and
:func:`~deeplearning_mpi_tpu.sim.search.run_sweep` (SLO-per-chip scored
parameter search writing winners to the autotune DB). Design doc:
``docs/SIMULATION.md``; drilled by ``tools/sim_drill.py`` / ``make
sim-smoke``.
"""

from deeplearning_mpi_tpu.sim.simulator import (
    FleetSimulator,
    ServiceModel,
    SimConfig,
    SimResult,
)
from deeplearning_mpi_tpu.sim.search import (
    SweepResult,
    apply_params,
    default_grid,
    run_sweep,
)
from deeplearning_mpi_tpu.sim.traces import (
    FlashCrowd,
    TenantSpec,
    TraceConfig,
    generate_entries,
    tenant_policies,
    to_fleet_entries,
    trace_digest,
    write_jsonl,
)

__all__ = [
    "FlashCrowd",
    "FleetSimulator",
    "ServiceModel",
    "SimConfig",
    "SimResult",
    "SweepResult",
    "TenantSpec",
    "TraceConfig",
    "apply_params",
    "default_grid",
    "generate_entries",
    "run_sweep",
    "tenant_policies",
    "to_fleet_entries",
    "trace_digest",
    "write_jsonl",
]
