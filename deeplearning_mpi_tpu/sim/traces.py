"""Seeded multi-tenant workload trace generator.

Produces request traces at millions-of-requests scale in the SAME JSONL
replay schema ``cli/serve_lm.py --trace`` consumes (``arrival`` seconds
from start, ``prompt`` text, ``max_new``, optional ``deadline`` seconds
after arrival, optional ``tenant``) — a generated trace replays through
the real fleet byte-for-byte, and :func:`to_fleet_entries` converts the
same entries to the tokenized form :class:`~..serving.fleet.FleetSupervisor`
takes directly.

Traffic model (the regimes the Gemma-on-TPU serving measurements
distinguish — see PAPERS.md):

- **diurnal cycle** — a sinusoidal rate modulation over
  ``diurnal_period_s`` (amplitude 0..1);
- **Poisson bursts** — a Poisson-distributed number of Gaussian rate
  bumps at uniform times (prefill-bound burst regime);
- **flash crowds** — :class:`FlashCrowd` events with a linear onset ramp
  to a peak multiplier and an exponential decay, the shape the
  predictive autoscaler must warm capacity ahead of;
- **prefix-sharing skew** — each tenant draws its prompt preamble from a
  Zipf-weighted pool of shared prefixes, so affinity routing and radix
  caches have something real to hit;
- **adversarial tenants** — arrivals re-clustered into submit storms
  with tight deadlines, the traffic shape tenant budgets and the
  brownout ladder exist to contain.

Everything is driven by one ``numpy`` Generator seed: the same seed
yields a byte-identical JSONL file (asserted by ``tests/test_sim.py``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from pathlib import Path
from typing import Iterable, Optional

import numpy as np

__all__ = [
    "FlashCrowd",
    "TenantSpec",
    "TraceConfig",
    "generate_entries",
    "tenant_policies",
    "to_fleet_entries",
    "trace_digest",
    "write_jsonl",
]


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic shape plus its admission policy knobs (the
    policy half feeds :func:`tenant_policies`, which hands the scheduler
    the same ``{"budget_tokens", "priority"}`` dict the live fleet
    ships to every worker)."""

    name: str
    #: relative arrival-rate weight (normalized across tenants).
    share: float = 1.0
    #: scheduler admission priority (higher admits first; the brownout
    #: ladder sheds strictly-below-top tiers at stage 1+).
    priority: float = 0.0
    #: committed-token budget (prompt + max_new in flight); 0 = unlimited.
    budget_tokens: int = 0
    #: prompt length distribution (lognormal around the mean, tokens).
    prompt_mean: int = 48
    prompt_jitter: float = 0.4
    #: output length distribution (lognormal around the mean, tokens).
    output_mean: int = 16
    output_jitter: float = 0.4
    #: per-request SLO deadline, seconds after arrival; 0 = no deadline.
    deadline_s: float = 8.0
    deadline_jitter: float = 0.25
    #: prefix sharing: preambles per tenant pool, preamble length, and
    #: the Zipf exponent skewing draws toward the pool's head.
    prefix_pool: int = 8
    prefix_len: int = 24
    prefix_skew: float = 1.1
    #: adversarial traffic: arrivals re-clustered into submit storms
    #: every ``storm_window_s`` and deadlines squeezed.
    adversarial: bool = False
    storm_window_s: float = 5.0


@dataclasses.dataclass(frozen=True)
class FlashCrowd:
    """One flash-crowd event: rate ramps linearly over ``ramp_s`` up to
    ``amplitude`` x base at ``at_s``, then decays exponentially with
    time constant ``decay_s``. The onset ramp is what makes the crowd
    *forecastable* — a zero-lead step has no trend to extrapolate."""

    at_s: float
    amplitude: float = 6.0
    ramp_s: float = 4.0
    decay_s: float = 3.0


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Workload-level knobs. Defaults describe a compressed day; scale
    ``duration_s``/``base_rps`` for million-request traces."""

    duration_s: float = 3600.0
    base_rps: float = 10.0
    #: diurnal modulation: rate *= 1 + amplitude * sin(2*pi*t/period).
    diurnal_amplitude: float = 0.4
    diurnal_period_s: float = 86400.0
    diurnal_phase: float = 0.0
    #: Poisson bursts: expected bursts/second, each a Gaussian rate bump
    #: of ``burst_amplitude`` x base and sigma ``burst_width_s``.
    burst_rate_per_s: float = 0.001
    burst_amplitude: float = 2.0
    burst_width_s: float = 20.0
    flash_crowds: tuple[FlashCrowd, ...] = ()
    tenants: tuple[TenantSpec, ...] = (TenantSpec("default"),)
    #: arrival binning resolution for the inhomogeneous Poisson draw.
    bin_s: float = 1.0


def _rate_curve(cfg: TraceConfig, t: np.ndarray, rng: np.random.Generator
                ) -> np.ndarray:
    """Requests/second at each bin center — the inhomogeneous Poisson
    intensity all regimes compose into."""
    rate = np.full_like(t, float(cfg.base_rps))
    if cfg.diurnal_amplitude > 0:
        rate *= 1.0 + cfg.diurnal_amplitude * np.sin(
            2.0 * math.pi * (t / cfg.diurnal_period_s + cfg.diurnal_phase)
        )
    n_bursts = int(rng.poisson(cfg.burst_rate_per_s * cfg.duration_s))
    for _ in range(n_bursts):
        center = float(rng.uniform(0.0, cfg.duration_s))
        rate += (
            cfg.burst_amplitude * cfg.base_rps
            * np.exp(-0.5 * ((t - center) / cfg.burst_width_s) ** 2)
        )
    for crowd in cfg.flash_crowds:
        onset = np.clip((t - (crowd.at_s - crowd.ramp_s)) / crowd.ramp_s,
                        0.0, 1.0)
        decay = np.where(
            t > crowd.at_s,
            np.exp(-(t - crowd.at_s) / max(crowd.decay_s, 1e-9)),
            1.0,
        )
        rate += crowd.amplitude * cfg.base_rps * onset * decay
    return np.maximum(rate, 0.0)


def _lognormal(rng: np.random.Generator, mean: float, jitter: float,
               n: int, lo: int, hi: int) -> np.ndarray:
    if jitter <= 0:
        return np.full(n, int(round(mean)), dtype=np.int64)
    draw = rng.lognormal(math.log(max(mean, 1.0)), jitter, n)
    return np.clip(draw.round().astype(np.int64), lo, hi)


def _preambles(rng: np.random.Generator, spec: TenantSpec) -> list[str]:
    """The tenant's shared-prefix pool: deterministic lowercase-ascii
    preambles (byte-vocab friendly — ``serve_lm`` tokenizes prompt text
    as UTF-8 bytes)."""
    out = []
    for _ in range(max(spec.prefix_pool, 1)):
        chars = rng.integers(97, 123, size=max(spec.prefix_len, 1))
        out.append(bytes(chars.tolist()).decode("ascii"))
    return out


def generate_entries(cfg: TraceConfig, seed: int) -> list[dict]:
    """Generate one trace: a list of serve_lm-schema entry dicts sorted
    by arrival. Same ``(cfg, seed)`` -> identical entries, always."""
    rng = np.random.default_rng(seed)
    n_bins = max(int(math.ceil(cfg.duration_s / cfg.bin_s)), 1)
    edges = np.arange(n_bins) * cfg.bin_s
    centers = edges + 0.5 * cfg.bin_s
    rate = _rate_curve(cfg, centers, rng)
    counts = rng.poisson(rate * cfg.bin_s)
    total = int(counts.sum())
    arrivals = np.repeat(edges, counts) + rng.random(total) * cfg.bin_s
    arrivals = np.minimum(arrivals, cfg.duration_s)

    shares = np.asarray([max(t.share, 0.0) for t in cfg.tenants], float)
    if shares.sum() <= 0:
        raise ValueError("tenant shares must sum to a positive value")
    tenant_idx = rng.choice(len(cfg.tenants), size=total,
                            p=shares / shares.sum())

    prompt_len = np.zeros(total, dtype=np.int64)
    max_new = np.zeros(total, dtype=np.int64)
    deadline = np.zeros(total, dtype=np.float64)
    prefix_choice = np.zeros(total, dtype=np.int64)
    pools: list[list[str]] = []
    for ti, spec in enumerate(cfg.tenants):
        mask = tenant_idx == ti
        n = int(mask.sum())
        pools.append(_preambles(rng, spec))
        if n == 0:
            continue
        prompt_len[mask] = _lognormal(
            rng, spec.prompt_mean, spec.prompt_jitter, n,
            lo=max(spec.prefix_len + 1, 2), hi=4 * spec.prompt_mean + 64,
        )
        max_new[mask] = _lognormal(
            rng, spec.output_mean, spec.output_jitter, n,
            lo=1, hi=4 * spec.output_mean + 16,
        )
        dl = spec.deadline_s
        if spec.adversarial:
            dl *= 0.5  # storm traffic demands tight SLOs, by design
        if dl > 0:
            deadline[mask] = dl * (
                1.0 + spec.deadline_jitter * (rng.random(n) - 0.5)
            )
        k = np.arange(1, max(spec.prefix_pool, 1) + 1, dtype=float)
        w = k ** -max(spec.prefix_skew, 0.0)
        prefix_choice[mask] = rng.choice(len(k), size=n, p=w / w.sum())
        if spec.adversarial:
            # Submit storms: quantize arrivals to the storm window's
            # leading edge (plus a small spread) — a burst of
            # simultaneous submissions every window.
            a = arrivals[mask]
            arrivals[mask] = (
                np.floor(a / spec.storm_window_s) * spec.storm_window_s
                + rng.random(n) * 0.2
            )

    # Per-request private suffix text, drawn in one vectorized block.
    suffix_len = np.maximum(
        prompt_len - np.asarray(
            [cfg.tenants[i].prefix_len for i in tenant_idx]
        ),
        1,
    )
    buf = rng.integers(97, 123, size=int(suffix_len.sum()),
                       dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(suffix_len)])
    text = bytes(buf.tolist()).decode("ascii")

    order = np.argsort(arrivals, kind="stable")
    entries: list[dict] = []
    for i in order.tolist():
        spec = cfg.tenants[tenant_idx[i]]
        preamble = pools[tenant_idx[i]][int(prefix_choice[i])]
        prompt = preamble + text[int(offsets[i]):int(offsets[i + 1])]
        e: dict = {
            "arrival": round(float(arrivals[i]), 4),
            "prompt": prompt,
            "max_new": int(max_new[i]),
            "tenant": spec.name,
        }
        if deadline[i] > 0:
            e["deadline"] = round(float(deadline[i]), 4)
        entries.append(e)
    return entries


def tenant_policies(cfg: TraceConfig) -> dict[str, dict]:
    """The scheduler/fleet ``tenants=`` dict matching this trace's
    tenant specs — budgets and priorities travel with the workload so
    sim and real-process replays enforce the same admission policy."""
    return {
        t.name: {"budget_tokens": int(t.budget_tokens),
                 "priority": float(t.priority)}
        for t in cfg.tenants
    }


def to_fleet_entries(entries: Iterable[dict]) -> list[dict]:
    """Convert serve_lm-schema entries (prompt as text) to the tokenized
    form ``FleetSupervisor.run`` takes directly: prompt as a list of
    UTF-8 byte token ids — exactly the ``serve_lm._load_trace``
    tokenization, so both replay paths see identical token streams."""
    out = []
    for e in entries:
        fe = dict(e)
        fe["prompt"] = [
            int(b) for b in str(e["prompt"]).encode("utf-8")
        ]
        out.append(fe)
    return out


def write_jsonl(entries: Iterable[dict], path: str | Path) -> Path:
    """Serialize a trace to the serve_lm JSONL replay schema. Key order
    is fixed per entry, so the same entries always produce byte-identical
    files (the determinism test hashes the output)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("w") as fh:  # dmt-lint: disable=DMT005 — trace file generator is its single writer (fresh artifact, not a live IPC stream)
        for e in entries:
            fh.write(json.dumps(e, sort_keys=True) + "\n")
    return path


def trace_digest(entries: Iterable[dict]) -> str:
    """Short content digest of a trace — the sweep DB keys winners by it
    so tuned parameters only apply to the workload they were tuned on."""
    h = hashlib.sha256()
    for e in entries:
        h.update(json.dumps(e, sort_keys=True).encode())
        h.update(b"\n")
    return h.hexdigest()[:12]
