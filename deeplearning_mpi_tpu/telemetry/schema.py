"""Canonical metric schema: the one list of instrument names and label
keys this repo is allowed to emit.

Why a schema module and not a grep: the drills' reconciliation invariants
(``fault_injected_total == recovery_total + rollback_total``, ``spec_proposed
== spec_accepted + spec_rollback``, the fleet books) are arithmetic over
metric *names* — a typo'd name is not an error anywhere at runtime, it is a
silently-always-zero column that makes an invariant unfalsifiable. The
``dmt-lint`` telemetry-schema rule (DMT007, ``analysis/passes.py``) checks
every literal name and label key at instrument call sites against THIS
module at lint time, so "metric exists" is a build fact.

Adding a metric is a two-line change: the call site and the schema entry.
Kinds are documentation (the registry itself stays duck-typed); labels are
the allowed ``labeled(name, key=...)`` encodings per base name.
"""

from __future__ import annotations

__all__ = ["LABEL_KEYS", "METRICS", "is_canonical"]

#: Every label key any ``labeled(...)`` call may use.
LABEL_KEYS: frozenset[str] = frozenset(
    {
        "direction",
        "dtype",
        "kind",
        "outcome",
        "reason",
        "replica",
        "role",
        "stage",
        "tenant",
    }
)

#: name -> (kind, {allowed label keys}). Kind is one of
#: "counter" | "gauge" | "histogram".
METRICS: dict[str, tuple[str, frozenset[str]]] = {
    # -- compilation service (PR 4, compiler/) ------------------------------
    "compile_cache_evicted_total": ("counter", frozenset()),
    "compile_cache_hit_total": ("counter", frozenset()),
    "compile_cache_miss_total": ("counter", frozenset()),
    "compile_cache_quarantined_total": ("counter", frozenset()),
    "compile_seconds": ("histogram", frozenset()),
    "train_compile_seconds": ("gauge", frozenset()),
    "xla_bytes_per_step": ("gauge", frozenset()),
    "xla_flops_per_step": ("gauge", frozenset()),
    # -- serving engine (PR 2/7/9, serving/) --------------------------------
    "serve_compile_seconds": ("histogram", frozenset()),
    "serve_compile_total": ("counter", frozenset()),
    "serve_decode_held_steps": ("counter", frozenset()),
    "serve_decode_steps": ("counter", frozenset()),
    "serve_handoff_depth": ("gauge", frozenset()),
    "serve_handoff_stalls_total": ("counter", frozenset()),
    "serve_handoffs_total": ("counter", frozenset()),
    "serve_kv_blocks_in_use": ("gauge", frozenset({"role"})),
    "serve_kv_bytes": ("gauge", frozenset({"dtype", "role"})),
    "serve_prefill_chunks": ("counter", frozenset()),
    # -- radix prefix cache + multi-tenancy (PR 11) --------------------------
    "serve_prefix_blocks": ("gauge", frozenset()),
    "serve_prefix_cow_copies_total": ("counter", frozenset()),
    "serve_prefix_evictions_total": ("counter", frozenset()),
    "serve_prefix_hits_total": ("counter", frozenset()),
    "serve_prefix_nodes": ("gauge", frozenset()),
    "serve_prefix_tokens_reused_total": ("counter", frozenset()),
    "serve_tenant_shed_total": ("counter", frozenset({"tenant"})),
    "serve_tenant_tokens_in_flight": ("gauge", frozenset({"tenant"})),
    "serve_queue_depth": ("gauge", frozenset({"role"})),
    "serve_requests_admitted": ("counter", frozenset()),
    "serve_requests_completed": ("counter", frozenset()),
    "serve_requests_shed": ("counter", frozenset()),
    "serve_requests_submitted": ("counter", frozenset()),
    "serve_requeued_total": ("counter", frozenset()),
    "serve_shed_total": ("counter", frozenset({"reason"})),
    "serve_slots_active": ("gauge", frozenset({"role"})),
    "serve_tokens_discarded_total": ("counter", frozenset()),
    "serve_tokens_generated": ("counter", frozenset()),
    "serve_tpot_s": ("histogram", frozenset()),
    "serve_ttft_s": ("histogram", frozenset({"replica"})),
    # -- speculative decode (PR 7) ------------------------------------------
    "spec_accepted_total": ("counter", frozenset()),
    "spec_blocks_rolled_back_total": ("counter", frozenset()),
    "spec_degraded_total": ("counter", frozenset()),
    "spec_draft_steps": ("counter", frozenset()),
    "spec_proposed_total": ("counter", frozenset()),
    "spec_rollback_total": ("counter", frozenset()),
    "spec_verify_steps": ("counter", frozenset()),
    # -- serving fleet + router (PR 8) --------------------------------------
    "fleet_redispatch_total": ("counter", frozenset()),
    "fleet_replica_failures_total": ("counter", frozenset({"kind"})),
    "fleet_replica_restarts_total": ("counter", frozenset()),
    "serve_hedge_total": ("counter", frozenset({"outcome"})),
    # -- fleet autoscaler (PR 13) -------------------------------------------
    "fleet_brownout_total": ("counter", frozenset({"stage"})),
    "fleet_replicas": ("gauge", frozenset()),
    "fleet_scale_total": ("counter", frozenset({"direction", "outcome"})),
    # -- control-plane crash safety (PR 20, resilience/cluster.py) ----------
    "supervisor_incarnation": ("gauge", frozenset()),
    "supervisor_journal_replay_s": ("gauge", frozenset()),
    "supervisor_readopted_total": ("counter", frozenset()),
    "supervisor_respawned_total": ("counter", frozenset()),
    # -- chaos / resilience (PR 3/5) ----------------------------------------
    "fault_injected_total": ("counter", frozenset({"kind"})),
    "recovery_latency_s": ("histogram", frozenset()),
    "recovery_total": ("counter", frozenset()),
    "rollback_total": ("counter", frozenset()),
    "train_restarts_total": ("counter", frozenset()),
    # -- numerics guardrails (PR 18, resilience/guardrails.py) --------------
    "guard_checks_total": ("counter", frozenset()),
    "guard_digest_mismatch_total": ("counter", frozenset()),
    "guard_digest_total": ("counter", frozenset()),
    "guard_poisoned_total": ("counter", frozenset()),
    "guard_quarantine_total": ("counter", frozenset()),
    "guard_rollback_total": ("counter", frozenset()),
    "guard_spike_total": ("counter", frozenset()),
    # -- elastic pod (PR 5) -------------------------------------------------
    "elastic_restore_total": ("counter", frozenset()),
    "pod_rank_failures_total": ("counter", frozenset({"kind"})),
    "pod_restarts_total": ("counter", frozenset()),
    "pod_straggler_flags_total": ("counter", frozenset()),
    "pod_world_size": ("gauge", frozenset()),
    # -- distributed tracing + flight recorder (PR 16, telemetry/spans.py) --
    "flight_dump_total": ("counter", frozenset({"reason"})),
    "span_dropped_total": ("counter", frozenset()),
    "span_recorded_total": ("counter", frozenset()),
    "trace_clock_offset_s": ("gauge", frozenset()),
    # -- load simulator (PR 19, sim/) ---------------------------------------
    "sim_brownout_max_stage": ("gauge", frozenset()),
    "sim_completed_total": ("counter", frozenset()),
    "sim_hedge_fired_total": ("counter", frozenset()),
    "sim_replica_seconds": ("gauge", frozenset()),
    "sim_requests_total": ("counter", frozenset()),
    "sim_shed_total": ("counter", frozenset({"reason"})),
    "sim_slo_attainment": ("gauge", frozenset()),
    "sim_slo_ok_total": ("counter", frozenset()),
    # -- runtime sanitizer (analysis/sanitizer.py) --------------------------
    "sanitize_donation_canary_trips_total": ("counter", frozenset()),
    "sanitize_kv_cow_violation_total": ("counter", frozenset()),
    "sanitize_kv_double_free_total": ("counter", frozenset()),
    "sanitize_kv_refcount_underflow_total": ("counter", frozenset()),
    "sanitize_kv_use_after_free_total": ("counter", frozenset()),
    "sanitize_retrace_trips_total": ("counter", frozenset()),
}


def is_canonical(name: str) -> bool:
    """True when ``name`` (a base instrument name, labels stripped) is in
    the schema."""
    return name.split("{", 1)[0] in METRICS
