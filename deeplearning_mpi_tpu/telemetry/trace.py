"""Trace annotations for the parallel hot paths.

``Profiler`` traces (``utils.profiling``) were unreadable before this
module: every ring rotation, all-to-all, pipeline step, and Pallas kernel
launch appeared as anonymous XLA fusions. :func:`annotate` stamps both
layers a trace has:

- ``jax.named_scope`` — trace-time: the scope name lands in the HLO op
  metadata of every op created inside it, so the device timeline in
  TensorBoard/Perfetto groups ops under ``ring_attention/rotation``-style
  names instead of ``fusion.1234``.
- ``jax.profiler.TraceAnnotation`` — host-side runtime: dispatch/placement
  work executed while the context is open shows on the Python track.

Annotation is pure metadata — it must never change computed values. The
``enabled`` switch exists so tests can prove that (run a step annotated and
un-annotated, assert bit-identical outputs) and so a paranoid run can strip
annotations wholesale; the compute inside the context is identical either
way.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Iterator

import jax

_ENABLED = True


def set_enabled(flag: bool) -> bool:
    """Globally enable/disable annotation emission; returns the old value.

    Exists for the no-op proof in tests and for excluding annotation
    overhead from microbenchmarks — NOT a perf knob (named_scope costs
    nothing at runtime; TraceAnnotation costs nothing outside an active
    profiler session).
    """
    global _ENABLED
    old = _ENABLED
    _ENABLED = bool(flag)
    return old


def enabled() -> bool:
    return _ENABLED


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Context manager: HLO named scope + host trace annotation for ``name``."""
    if not _ENABLED:
        yield
        return
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def annotate_fn(name: str) -> Callable[[Callable], Callable]:
    """Decorator form of :func:`annotate` for whole hot-path entry points."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapped(*args: Any, **kwargs: Any):
            with annotate(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco
