"""Static collective-byte accounting — bytes per step from shapes alone.

Every collective this framework issues has a closed-form per-device byte
cost on a ring/bidirectional-ICI topology (the standard algorithmic-
bandwidth accounting, e.g. the NCCL/ICI literature):

- all-reduce:      2·(n−1)/n · B   (reduce-scatter + all-gather halves)
- reduce-scatter:    (n−1)/n · B
- all-gather:        (n−1)/n · B
- all-to-all:        (n−1)/n · B   (each device keeps 1/n locally)
- ppermute:                    B   (every element moves one hop)

``B`` is the device-local buffer size in bytes. These are *per device*;
multiply by the axis size for fleet totals. Static accounting at wrap time
is deliberately chosen over runtime measurement: it costs nothing per step,
it is exact for the SPMD programs this repo builds (the collectives are in
the compiled program, not data-dependent), and disagreement between this
number and a measured profile is itself diagnostic (XLA fused or elided
something).

The per-model helpers mirror where the collectives actually are:
``dp_grad_allreduce_bytes`` (every model, backward), plus the LM extras —
Ulysses all-to-alls, ring-attention K/V rotations, pipeline activation
shifts, MoE dispatch/combine — with backward costed as a mirror of forward
(each forward collective's transpose is a collective of the same volume).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def _nbytes(shape: tuple[int, ...], dtype: Any = jnp.float32) -> float:
    size = 1
    for s in shape:
        size *= s
    return float(size * jnp.dtype(dtype).itemsize)


def allreduce_bytes(buffer_bytes: float, n: int) -> float:
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) / n * buffer_bytes


def reduce_scatter_bytes(buffer_bytes: float, n: int) -> float:
    if n <= 1:
        return 0.0
    return (n - 1) / n * buffer_bytes


def all_gather_bytes(buffer_bytes: float, n: int) -> float:
    if n <= 1:
        return 0.0
    return (n - 1) / n * buffer_bytes


def all_to_all_bytes(buffer_bytes: float, n: int) -> float:
    if n <= 1:
        return 0.0
    return (n - 1) / n * buffer_bytes


def ppermute_bytes(buffer_bytes: float, n: int) -> float:
    return buffer_bytes if n > 1 else 0.0


def param_count(params: Any) -> int:
    """Leaf-size sum of a pytree (device arrays never fetched)."""
    return sum(int(jnp.size(leaf)) for leaf in jax.tree.leaves(params))


def dp_grad_allreduce_bytes(
    n_params: int, dp: int, *, dtype: Any = jnp.float32, zero: bool = False
) -> float:
    """Per-device gradient-sync bytes per step over the ``data`` axis.

    Plain DP all-reduces the full gradient. ZeRO-1 replaces it with
    reduce-scatter (grads) + all-gather (updated params) — same leading
    term, so the byte cost is identical; the win is optimizer-state memory,
    not wire volume.
    """
    buf = n_params * float(jnp.dtype(dtype).itemsize)
    if zero:
        return reduce_scatter_bytes(buf, dp) + all_gather_bytes(buf, dp)
    return allreduce_bytes(buf, dp)


def ulysses_attention_bytes(
    batch_local: int,
    seq_local: int,
    heads: int,
    head_dim: int,
    seq_axis: int,
    *,
    kv_heads: int | None = None,
    num_layers: int = 1,
    dtype: Any = jnp.bfloat16,
    training: bool = True,
) -> float:
    """Per-device bytes for the Ulysses schedule: 4 all-to-alls per layer
    forward (q, k, v in; context out — k/v at the GROUPED head count when
    GQA rides the collective), mirrored in backward when training."""
    if seq_axis <= 1:
        return 0.0
    kv = kv_heads or heads
    q_buf = _nbytes((batch_local, seq_local, heads, head_dim), dtype)
    kv_buf = _nbytes((batch_local, seq_local, kv, head_dim), dtype)
    fwd = (
        all_to_all_bytes(q_buf, seq_axis) * 2      # q in, context out
        + all_to_all_bytes(kv_buf, seq_axis) * 2   # k, v in
    )
    return num_layers * fwd * (2.0 if training else 1.0)


def ring_attention_bytes(
    batch_local: int,
    seq_local: int,
    heads: int,
    head_dim: int,
    seq_axis: int,
    *,
    kv_heads: int | None = None,
    rotations: int | None = None,
    num_layers: int = 1,
    dtype: Any = jnp.bfloat16,
    training: bool = True,
) -> float:
    """Per-device bytes for ring attention: (rotations − 1) K/V ppermute
    pairs per layer (the final rotation's send is elided — see
    ``parallel.ring_attention``), GQA-grouped, mirrored in backward."""
    if seq_axis <= 1:
        return 0.0
    kv = kv_heads or heads
    n_rot = (rotations if rotations is not None else seq_axis) - 1
    if n_rot <= 0:
        return 0.0
    kv_buf = _nbytes((batch_local, seq_local, kv, head_dim), dtype)
    fwd = num_layers * n_rot * 2 * ppermute_bytes(kv_buf, seq_axis)
    return fwd * (2.0 if training else 1.0)


def pipeline_bytes(
    microbatch_shape: tuple[int, ...],
    num_microbatches: int,
    pipe_axis: int,
    *,
    dtype: Any = jnp.bfloat16,
    training: bool = True,
) -> float:
    """Per-device bytes for the GPipe schedule: one activation ppermute per
    schedule step, ``M + S − 1`` steps, mirrored in backward."""
    if pipe_axis <= 1:
        return 0.0
    buf = _nbytes(microbatch_shape, dtype)
    steps = num_microbatches + pipe_axis - 1
    fwd = steps * ppermute_bytes(buf, pipe_axis)
    return fwd * (2.0 if training else 1.0)


def moe_dispatch_bytes(
    tokens_local: int,
    d_model: int,
    expert_axis: int,
    *,
    top_k: int = 1,
    capacity_factor: float = 1.0,
    num_layers: int = 1,
    dtype: Any = jnp.bfloat16,
    training: bool = True,
) -> float:
    """Per-device bytes for expert-parallel MoE: dispatch + combine are each
    an all-to-all of the routed token activations (top_k · capacity_factor
    slots per token upper bound), per MoE layer, mirrored in backward."""
    if expert_axis <= 1:
        return 0.0
    buf = _nbytes((tokens_local, d_model), dtype) * top_k * capacity_factor
    fwd = num_layers * 2 * all_to_all_bytes(buf, expert_axis)
    return fwd * (2.0 if training else 1.0)
