"""Distributed request tracing + crash flight recorder.

Three pieces, in order of dependency:

1. :class:`Span` — an explicit span model: id, parent id, a *trace*
   correlation key (request id or ``step:N``), monotonic start/end, and a
   small label dict. Spans are plain records, not context-manager magic,
   because the serving spans we care about (queue dwell, prefill, the
   prefill→decode handoff, decode) are NOT lexical scopes — they open in
   one engine step and close many steps later, sometimes in a different
   process.
2. :class:`SpanRecorder` — the per-process writer. One recorder owns one
   JSONL file for its whole life (the single-writer contract, DMT005):
   newline-terminated ``json.dumps`` per record, flushed immediately, so a
   crashed process still leaves every completed span on disk and a torn
   final line is the only damage possible. The FIRST line of every trace
   file is a ``trace_meta`` record carrying the process's
   monotonic-vs-epoch clock offset (``time.time() - time.monotonic()``,
   sampled once): CLOCK_MONOTONIC is system-wide on Linux but has an
   arbitrary epoch, so the offset is what lets ``tools/trace_report.py``
   merge a fleet of per-process files onto one wall-clock timeline — and
   detect genuinely skewed recorders (tests inject skew through the
   ``epoch_clock`` hook).
3. The **flight recorder** — every recorder keeps a bounded in-memory ring
   of its most recent records. :meth:`SpanRecorder.dump_flight` writes the
   ring atomically (tmp + rename) and the module-level :func:`dump_all`
   dumps every live recorder in the process: the sanitizer calls it on a
   trip, the chaos injector calls it before a ``replica_kill``/``rank_kill``
   detonates or a hang wedges the thread, and supervisors call it on
   watchdog timeouts — so "the last moments before the wedge" survive even
   when the JSONL trail was cut mid-line.

Costless-off contract (the ``DMT_SANITIZE`` pattern): nothing here is a
global switch. Hot paths hold ``tracer = None`` unless a trace dir was
configured and guard every hook with ``if tracer is not None`` — one
pointer test, no allocation, when tracing is off. ``tests/
test_observability.py`` pins that with an allocation-counting micro-test.

Recording never raises into the caller: a failed write degrades to a
``span_dropped_total`` count, mirroring the metrics-sink contract.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Iterable, Optional

__all__ = [
    "SCHEMA_VERSION",
    "Span",
    "SpanRecorder",
    "dump_all",
    "load_trace_file",
    "span_tree",
]

#: bumped when the on-disk record shape changes; readers check it.
SCHEMA_VERSION = 1

#: live recorders in this process, in creation order — what :func:`dump_all`
#: walks. A recorder leaves on :meth:`SpanRecorder.close`.
_RECORDERS: list["SpanRecorder"] = []
_RECORDERS_LOCK = threading.Lock()


class Span:
    """One timed interval. ``t0``/``t1`` are process-monotonic seconds;
    ``t1 is None`` while the span is open. ``trace`` is the correlation
    key that stitches spans across processes (a fleet rid like ``"r5"``,
    or ``"step:12"`` for a training step)."""

    __slots__ = ("name", "sid", "parent", "trace", "t0", "t1", "labels")

    def __init__(
        self,
        name: str,
        sid: str,
        *,
        parent: Optional[str] = None,
        trace: Optional[str] = None,
        t0: float = 0.0,
        t1: Optional[float] = None,
        labels: Optional[dict[str, Any]] = None,
    ) -> None:
        self.name = name
        self.sid = sid
        self.parent = parent
        self.trace = trace
        self.t0 = t0
        self.t1 = t1
        self.labels = labels or {}

    @property
    def duration(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def to_record(self) -> dict[str, Any]:
        rec: dict[str, Any] = {
            "kind": "span",
            "name": self.name,
            "sid": self.sid,
            "t0": self.t0,
            "t1": self.t1,
        }
        if self.parent is not None:
            rec["parent"] = self.parent
        if self.trace is not None:
            rec["trace"] = self.trace
        if self.labels:
            rec["labels"] = self.labels
        return rec

    def __repr__(self) -> str:  # debugging aid only
        return (
            f"Span({self.name!r}, sid={self.sid!r}, trace={self.trace!r}, "
            f"t0={self.t0:.6f}, t1={self.t1})"
        )


class SpanRecorder:
    """Per-process span writer + bounded flight ring.

    Parameters
    ----------
    path:
        The JSONL trace file. Opened append-mode once and held for the
        recorder's life (single writer per file — fleet workers encode
        their pid into the filename so respawned attempts never share).
    proc:
        Human-readable process name (``"supervisor"``, ``"replica0"``,
        ``"trainer"``) — goes into the meta line and every span id.
    clock / epoch_clock:
        Monotonic and wall clocks, injectable for deterministic tests and
        for the clock-skew regression test (skew the ``epoch_clock`` of
        one recorder and assert the merged timeline still lines up).
    ring:
        Flight-recorder depth: how many recent records survive to a dump.
    registry:
        Optional :class:`~..registry.MetricsRegistry` to mirror counts
        into (``span_recorded_total`` etc.) so ``metrics_report`` can
        render a Tracing table from an ordinary snapshot.
    """

    def __init__(
        self,
        path: str | Path,
        *,
        proc: str = "proc",
        clock: Callable[[], float] = time.monotonic,
        epoch_clock: Callable[[], float] = time.time,
        ring: int = 256,
        registry: Any = None,
        flight_dir: str | Path | None = None,
    ) -> None:
        self.path = Path(path)
        self.proc = proc
        self.pid = os.getpid()
        self._clock = clock
        # Sampled ONCE: the offset is a constant property of this process's
        # monotonic epoch; re-sampling per record would smear real wall-clock
        # adjustments (NTP steps) across the trace.
        self.mono_offset = epoch_clock() - clock()
        self.flight_dir = Path(flight_dir) if flight_dir else self.path.parent
        self._ring: deque[dict[str, Any]] = deque(maxlen=max(1, int(ring)))
        self._lock = threading.Lock()
        self._next_sid = 0
        self._registry = registry
        self.spans_total = 0
        self.events_total = 0
        self.dropped_total = 0
        self.dumps_total = 0
        self._closed = False
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self.path.open("a")
        if registry is not None:
            registry.counter("span_recorded_total")
            registry.counter("span_dropped_total")
            registry.counter("flight_dump_total")
            registry.gauge("trace_clock_offset_s").set(self.mono_offset)
        self._write(
            {
                "kind": "trace_meta",
                "schema": SCHEMA_VERSION,
                "proc": proc,
                "pid": self.pid,
                "mono_offset": self.mono_offset,
                "ts": self.mono_offset + clock(),
            }
        )
        with _RECORDERS_LOCK:
            _RECORDERS.append(self)

    # -- span lifecycle ----------------------------------------------------
    def begin(
        self,
        name: str,
        *,
        trace: Optional[str] = None,
        parent: Optional[str] = None,
        t0: Optional[float] = None,
        **labels: Any,
    ) -> Span:
        """Open a span. Nothing is written until :meth:`end` — an open
        span that dies with the process is reconstructable only from the
        flight ring of whoever dumped, which is exactly the semantics a
        crash report wants."""
        with self._lock:
            sid = f"{self.proc}/{self.pid}:{self._next_sid}"
            self._next_sid += 1
        return Span(
            name,
            sid,
            parent=parent,
            trace=trace,
            t0=self._clock() if t0 is None else t0,
            labels=dict(labels) if labels else None,
        )

    def end(self, span: Span, *, t1: Optional[float] = None) -> Span:
        """Close ``span`` and write it."""
        span.t1 = self._clock() if t1 is None else t1
        self._emit_span(span)
        return span

    def record_span(
        self,
        name: str,
        t0: float,
        t1: float,
        *,
        trace: Optional[str] = None,
        parent: Optional[str] = None,
        **labels: Any,
    ) -> Span:
        """Write a complete span retroactively from existing timestamps.

        This is the serving hot path's preferred form: the engine already
        stamps ``arrival`` / ``t_admitted`` / ``t_first_token`` /
        ``t_finished`` on every request, so the queue/prefill/decode spans
        are derived in one call at finish time instead of tracking open
        span objects through the scheduler."""
        span = self.begin(name, trace=trace, parent=parent, t0=t0, **labels)
        span.t1 = t1
        self._emit_span(span)
        return span

    def event(
        self,
        name: str,
        *,
        trace: Optional[str] = None,
        t: Optional[float] = None,
        **labels: Any,
    ) -> None:
        """Instantaneous marker (a dispatch, a hedge, a failover)."""
        rec: dict[str, Any] = {
            "kind": "event",
            "name": name,
            "t": self._clock() if t is None else t,
        }
        if trace is not None:
            rec["trace"] = trace
        if labels:
            rec["labels"] = labels
        self.events_total += 1
        self._write(rec)

    def _emit_span(self, span: Span) -> None:
        self.spans_total += 1
        if self._registry is not None:
            self._registry.counter("span_recorded_total").inc()
        self._write(span.to_record())

    # -- plumbing ----------------------------------------------------------
    def _write(self, rec: dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(rec)
            if self._closed:
                return
            try:
                self._f.write(json.dumps(rec) + "\n")
                self._f.flush()
            except Exception:
                self.dropped_total += 1
                if self._registry is not None:
                    self._registry.counter("span_dropped_total").inc()

    # -- flight recorder ---------------------------------------------------
    def dump_flight(self, reason: str) -> Optional[Path]:
        """Atomically write the ring to ``flight_dir`` and return the path
        (``None`` on failure — a dump must never mask the original fault).
        The filename encodes proc, pid, and reason so every dump of a
        multi-process incident lands side by side."""
        safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)
        out = self.flight_dir / f"flight-{self.proc}-{self.pid}-{safe}.json"
        payload = {
            "kind": "flight_dump",
            "schema": SCHEMA_VERSION,
            "reason": reason,
            "proc": self.proc,
            "pid": self.pid,
            "mono_offset": self.mono_offset,
            "t_dump": self._clock(),
            "spans_total": self.spans_total,
            "events_total": self.events_total,
            "dropped_total": self.dropped_total,
            "ring": list(self._ring),
        }
        try:
            out.parent.mkdir(parents=True, exist_ok=True)
            tmp = out.with_suffix(f".tmp.{self.pid}")
            # tmp + rename by hand (not resilience.integrity.atomic_write_json)
            # to keep telemetry import-free of resilience.
            with tmp.open("w") as f:  # dmt-lint: disable=DMT004
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, out)
        except Exception:
            return None
        self.dumps_total += 1
        if self._registry is not None:
            self._registry.counter("flight_dump_total").inc()
        return out

    def close(self) -> None:
        with _RECORDERS_LOCK:
            if self in _RECORDERS:
                _RECORDERS.remove(self)
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.close()
            except Exception:
                pass


def dump_all(reason: str) -> list[Path]:
    """Dump every live recorder's flight ring — the one call sanitizer
    trips, chaos detonations, and watchdog timeouts make. Best-effort by
    construction: a failed dump is skipped, never raised."""
    with _RECORDERS_LOCK:
        recorders = list(_RECORDERS)
    paths = []
    for rec in recorders:
        p = rec.dump_flight(reason)
        if p is not None:
            paths.append(p)
    return paths


# -- readers (shared by tools/trace_report.py and the tests) ---------------

def load_trace_file(path: str | Path) -> tuple[Optional[dict], list[dict]]:
    """Parse one trace JSONL file into ``(meta, records)``.

    Tolerates the single-writer failure mode: a torn (unterminated or
    half-written) final line is dropped, everything before it is kept —
    the mirror of ``tail_jsonl``'s newline-delimited read contract."""
    raw = Path(path).read_bytes()
    meta: Optional[dict] = None
    records: list[dict] = []
    lines = raw.split(b"\n")
    if lines and lines[-1] != b"":
        lines = lines[:-1]  # torn final line: no newline ⇒ maybe no JSON
    for line in lines:
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # half-flushed garbage; keep reading (defensive)
        if rec.get("kind") == "trace_meta" and meta is None:
            meta = rec
        else:
            records.append(rec)
    return meta, records


def span_tree(
    spans: Iterable[dict],
) -> tuple[dict[str, dict], dict[str, list[dict]], list[dict]]:
    """Index span records into ``(by_sid, children_by_parent, orphans)``.

    An *orphan* names a parent sid that is not present in ``spans`` —
    either its process died before flushing the parent or the correlation
    key was mangled in transit; both are bugs the smoke asserts against."""
    by_sid = {s["sid"]: s for s in spans if s.get("kind") == "span"}
    children: dict[str, list[dict]] = {}
    orphans: list[dict] = []
    for s in by_sid.values():
        parent = s.get("parent")
        if parent is None:
            continue
        if parent in by_sid:
            children.setdefault(parent, []).append(s)
        else:
            orphans.append(s)
    return by_sid, children, orphans
