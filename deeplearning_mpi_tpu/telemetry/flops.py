"""Analytic FLOPs estimates and MFU — the scaling literature's headline metric.

MFU (model FLOPs utilization) divides the *useful* model FLOPs by what the
hardware could have done in the same wall time:

    mfu = flops_per_step / (step_seconds * n_devices * peak_flops_per_device)

"Useful" means the analytic cost of the model's math — matmuls and convs —
NOT what XLA executed (rematerialization, padding, and masked positions all
burn hardware FLOPs that don't count). That convention is what makes MFU
comparable across frameworks and papers (PaLM's appendix B formulation).

Training cost uses the standard factor-3 rule: backward ≈ 2× forward
(one matmul per input gradient, one per weight gradient), so
``train = 3 × forward``. Attention scores/values matmuls are counted at
the causally-visible positions (S/2 average, windowed where applicable) —
the kernels here (`ops/pallas/flash_attention.py` trimmed grids,
`parallel/ring_attention.py` rotation skipping) genuinely skip the dead
half, so counting full S² would overstate MFU on exactly the paths this
repo optimized.

Peak FLOPs per device come from a small table of TPU generations (bf16
peak, the training dtype) with a ``DMT_PEAK_FLOPS`` env override. On CPU
there is no meaningful peak; a nominal constant keeps MFU *defined* (the
report needs a non-null column and relative comparisons across runs on the
same host are still valid) and the override makes it honest if anyone
calibrates their machine.
"""

from __future__ import annotations

import os
from typing import Any

import jax

#: bf16 peak FLOPs/s per chip by TPU generation (public spec sheets).
PEAK_FLOPS: dict[str, float] = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

#: Nominal CPU "peak" — a few AVX cores' worth. Arbitrary but stable, so
#: CPU-mesh MFU is non-null and comparable run-to-run on one host.
CPU_NOMINAL_PEAK_FLOPS = 200e9

#: Aggregate ICI bandwidth per chip in bytes/s (public per-chip interconnect
#: specs, bits/8). Nominal: real achievable bandwidth depends on topology and
#: collective — these set the scale for the overlap estimate, and
#: ``DMT_LINK_BANDWIDTH`` overrides with a calibrated number.
LINK_BANDWIDTH: dict[str, float] = {
    "v2": 62e9,
    "v3": 82e9,
    "v4": 300e9,
    "v5e": 200e9,
    "v5p": 600e9,
    "v6e": 448e9,
}

#: Nominal CPU "interconnect" (shared-memory transfers between virtual
#: devices) — same convention as CPU_NOMINAL_PEAK_FLOPS: stable, not real.
CPU_NOMINAL_LINK_BANDWIDTH = 10e9


def device_peak_flops(device: Any | None = None) -> float:
    """Peak FLOPs/s for ``device`` (default: first local device).

    Resolution order: ``DMT_PEAK_FLOPS`` env var (calibrated override) →
    TPU generation table via ``device_kind`` → CPU nominal constant.
    """
    env = os.environ.get("DMT_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for gen, peak in PEAK_FLOPS.items():
        if gen in kind.replace(" ", ""):
            return peak
    if getattr(device, "platform", "") == "tpu":
        return PEAK_FLOPS["v4"]  # unknown TPU: assume mid-generation
    return CPU_NOMINAL_PEAK_FLOPS


def device_link_bandwidth(device: Any | None = None) -> float:
    """Nominal interconnect bytes/s for ``device`` (default: first local).

    Resolution order mirrors :func:`device_peak_flops`:
    ``DMT_LINK_BANDWIDTH`` env var → TPU generation table → CPU nominal.
    """
    env = os.environ.get("DMT_LINK_BANDWIDTH")
    if env:
        return float(env)
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for gen, bw in LINK_BANDWIDTH.items():
        if gen in kind.replace(" ", ""):
            return bw
    if getattr(device, "platform", "") == "tpu":
        return LINK_BANDWIDTH["v4"]
    return CPU_NOMINAL_LINK_BANDWIDTH


def overlap_fraction(
    comm_bytes_per_step: float,
    issued_flops_per_step: float,
    *,
    n_devices: int | None = None,
    peak_flops_per_device: float | None = None,
    link_bandwidth_per_device: float | None = None,
) -> float | None:
    """Estimated fraction of per-step collective time hideable under compute.

    Roofline-style: compute time ≈ issued FLOPs / (n · peak), collective
    time ≈ per-device wire bytes / link bandwidth. When compute covers the
    comms entirely the scheduler *can* hide them (fraction 1.0 — whether it
    *does* is what ``mfu_gap`` and the profiler answer); when comms exceed
    compute, at most compute/comm of them can hide and the step is
    communication-bound. None on degenerate inputs; 1.0 when there are no
    collective bytes to hide.
    """
    if not issued_flops_per_step or issued_flops_per_step <= 0:
        return None
    if comm_bytes_per_step is None or comm_bytes_per_step < 0:
        return None
    if not comm_bytes_per_step:
        return 1.0
    if n_devices is None:
        n_devices = jax.device_count()
    if peak_flops_per_device is None:
        peak_flops_per_device = device_peak_flops()
    if link_bandwidth_per_device is None:
        link_bandwidth_per_device = device_link_bandwidth()
    compute_s = issued_flops_per_step / (n_devices * peak_flops_per_device)
    comm_s = (comm_bytes_per_step / n_devices) / link_bandwidth_per_device
    if comm_s <= 0:
        return 1.0
    return min(1.0, compute_s / comm_s)


def xla_cost_analysis(compiled: Any) -> dict[str, float]:
    """FLOPs / bytes the compiled executable will actually execute, from
    XLA's own cost analysis — the *measured* complement to the analytic
    estimators below (which count only the model's useful math and are what
    MFU is defined over; XLA's number additionally includes remat, padding,
    and masked work, so comparing the two bounds the overhead).

    Accepts a ``jax.stages.Compiled`` (``compiler/aot.py`` passes one per
    warmed program). jaxlib 0.4.x returns a list of one dict keyed
    ``'flops'`` / ``'bytes accessed'``; newer jax returns the dict
    directly — both are handled. Returns ``{}`` where the backend exposes
    nothing (keys absent, never faked — same convention as ``hbm_usage``).
    """
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out: dict[str, float] = {}
    flops = ca.get("flops")
    if isinstance(flops, (int, float)) and flops > 0:
        out["flops"] = float(flops)
    nbytes = ca.get("bytes accessed")
    if isinstance(nbytes, (int, float)) and nbytes > 0:
        out["bytes_accessed"] = float(nbytes)
    return out


def mfu(
    flops_per_step: float,
    step_seconds: float,
    *,
    n_devices: int | None = None,
    peak_flops_per_device: float | None = None,
) -> float | None:
    """Model FLOPs utilization in [0, ~1]; None when inputs are degenerate."""
    if not flops_per_step or not step_seconds or step_seconds <= 0:
        return None
    if n_devices is None:
        n_devices = jax.device_count()
    if peak_flops_per_device is None:
        peak_flops_per_device = device_peak_flops()
    return flops_per_step / (step_seconds * n_devices * peak_flops_per_device)


def mfu_gap_attribution(
    phase_seconds: dict[str, float],
    duration_s: float,
    *,
    mfu_issued: float | None,
    mfu_gap: float | None,
) -> dict[str, float]:
    """Decompose ``mfu_gap`` into the trainer's measured step phases.

    ``mfu_gap = mfu_issued - mfu`` is the utilization lost to everything
    that isn't useful model math. With per-phase wall-clock attribution
    (``train/trainer.py`` tracing: data_wait / h2d / collective_tail / …),
    each non-compute phase's share of the epoch directly forfeits that
    fraction of the *achievable* utilization:

        mfu_gap_<phase> = mfu_issued · (phase_seconds / duration)

    The remainder — remat recompute, padding, kernel inefficiency, and any
    stall the fences didn't isolate — lands in ``mfu_gap_residual`` so the
    returned values sum to ``mfu_gap`` exactly (the report can render the
    decomposition as shares of a closed total). The ``compute`` phase is
    the useful-work bucket and never charged to the gap.

    Returns ``{}`` on degenerate inputs (no duration, or the run didn't
    compute MFU at all) — keys absent, never faked.
    """
    if not duration_s or duration_s <= 0:
        return {}
    if mfu_issued is None or mfu_gap is None:
        return {}
    out: dict[str, float] = {}
    explained = 0.0
    for name, secs in phase_seconds.items():
        if name == "compute":
            continue
        share = mfu_issued * (float(secs) / duration_s)
        out[f"mfu_gap_{name}"] = share
        explained += share
    out["mfu_gap_residual"] = mfu_gap - explained
    return out


# ---------------------------------------------------------------------------
# Transformer / MoE (models/transformer.py, models/moe.py)
# ---------------------------------------------------------------------------

def transformer_fwd_flops(config: Any, batch: int, seq_len: int) -> float:
    """Forward FLOPs for one ``TransformerLM`` batch.

    Counts matmuls only (norms/activations/RoPE are O(d) noise):

    - embedding lookup is a gather (0 FLOPs); the LM head is a matmul,
      2·d·V per token (tied or not, the matmul runs);
    - per block: q/k/v/out projections (GQA-aware: k/v project to
      ``num_kv_heads·head_dim``), attention scores+values at
      2 · 2 · S_visible · H · Dh per token with S_visible the average
      causally-visible positions (S/2, capped by the sliding window), and
      SwiGLU MLP — three matmuls (gate, up, down), 6·d·ff per token;
    - MoE blocks swap the dense MLP for router (2·d·E) + top_k experts'
      worth of SwiGLU (GShard counts only ACTIVE expert FLOPs).
    """
    d = config.d_model
    h = config.num_heads
    hkv = getattr(config, "num_kv_heads", None) or h
    dh = config.head_dim
    ff = config.d_ff
    layers = config.num_layers
    vocab = config.vocab_size
    tokens = batch * seq_len

    window = getattr(config, "attention_window", 0)
    s_visible = seq_len / 2.0
    if window:  # 0/None = full causal attention, no cap
        s_visible = min(s_visible, float(window))

    per_token_block = 0.0
    # Projections: q (d→H·Dh), k+v (d→Hkv·Dh each), out (H·Dh→d).
    per_token_block += 2 * d * (h * dh) * 2       # q + out
    per_token_block += 2 * d * (hkv * dh) * 2     # k + v
    # Attention: scores (2·S_vis·H·Dh) + values (2·S_vis·H·Dh) per token.
    per_token_block += 4 * s_visible * h * dh

    experts = getattr(config, "moe_experts", None) or 0
    if experts:
        top_k = getattr(config, "moe_top_k", 1) or 1
        per_token_block += 2 * d * experts        # router logits
        per_token_block += top_k * 6 * d * ff     # active experts' SwiGLU
    else:
        per_token_block += 6 * d * ff             # gate + up + down

    head = 2 * d * vocab  # LM head matmul per token
    return tokens * (layers * per_token_block + head)


def transformer_train_flops(config: Any, batch: int, seq_len: int) -> float:
    return 3.0 * transformer_fwd_flops(config, batch, seq_len)


def transformer_remat_flops(
    config: Any, batch: int, seq_len: int, *, remat: Any = "none"
) -> float:
    """Extra matmul FLOPs one train step RECOMPUTES under rematerialization.

    These are issued by the hardware but are not model FLOPs — MFU's
    definition excludes them, so they belong on the issued side of the
    ledger (:func:`transformer_issued_flops`), where ``mfu_gap`` makes the
    overhead visible instead of silently inflating utilization.

    Policies (``TransformerLM.remat``):

    - ``"none"``/``False``: nothing recomputed — 0.
    - ``"dots"`` (``jax.checkpoint_policies.checkpoint_dots``): matmul
      *outputs* are saved; only the elementwise glue between them is
      recomputed, which this module counts as O(d) noise everywhere — 0
      extra matmul FLOPs, at ~the activation memory of the dots.
    - ``"full"``/``True``: every block's forward is re-executed inside the
      backward pass — one extra forward's worth of block FLOPs. The LM head
      is outside the remat boundary (``nn.remat`` wraps ``Block``) and is
      not recomputed.
    """
    if isinstance(remat, str):
        remat = remat.lower()
    if remat in ("none", "", None, False):
        return 0.0
    if remat == "dots":
        return 0.0
    if remat in ("full", True):
        head = 2.0 * config.d_model * config.vocab_size * batch * seq_len
        return transformer_fwd_flops(config, batch, seq_len) - head
    raise ValueError(f"unknown remat policy {remat!r}")


def transformer_issued_flops(
    config: Any, batch: int, seq_len: int, *, remat: Any = "none"
) -> float:
    """FLOPs the hardware issues per train step: model train FLOPs plus
    remat recompute. Feed this to ``Trainer(issued_flops_per_step=...)`` /
    ``mfu`` to get ``mfu_issued``; the difference from plain ``mfu`` is the
    remat tax."""
    return transformer_train_flops(config, batch, seq_len) + (
        transformer_remat_flops(config, batch, seq_len, remat=remat)
    )


# ---------------------------------------------------------------------------
# ResNet (models/resnet.py)
# ---------------------------------------------------------------------------

_RESNET_STAGES = {
    "resnet18": ((2, 2, 2, 2), False),
    "resnet34": ((3, 4, 6, 3), False),
    "resnet50": ((3, 4, 6, 3), True),
    "resnet101": ((3, 4, 23, 3), True),
    "resnet152": ((3, 8, 36, 3), True),
}


def _conv_flops(k: int, cin: int, cout: int, oh: float, ow: float) -> float:
    return 2.0 * k * k * cin * cout * oh * ow


def resnet_fwd_flops(
    arch: str,
    batch: int,
    image_size: int = 32,
    *,
    num_classes: int = 10,
    stem: str = "cifar",
) -> float:
    """Forward FLOPs for one ResNet batch (models/resnet.py topology).

    Walks the stages exactly as the model builds them: stem, then four
    stages of Basic (2×3×3) or Bottleneck (1×1 → 3×3 → 1×1·4) blocks with
    stride 2 at each stage boundary after the first, projection shortcut
    where shape changes, then the Dense head.
    """
    stages, bottleneck = _RESNET_STAGES[arch]
    s = float(image_size)
    flops = 0.0
    cin = 3
    if stem == "imagenet":
        s /= 2  # 7×7 stride-2 stem
        flops += _conv_flops(7, cin, 64, s, s)
        s /= 2  # 3×3 stride-2 maxpool
    else:
        flops += _conv_flops(3, cin, 64, s, s)
    cin = 64
    for stage_idx, num_blocks in enumerate(stages):
        width = 64 * (2 ** stage_idx)
        for block_idx in range(num_blocks):
            stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
            s_out = s / stride
            if bottleneck:
                cout = width * 4
                flops += _conv_flops(1, cin, width, s_out, s_out)
                flops += _conv_flops(3, width, width, s_out, s_out)
                flops += _conv_flops(1, width, cout, s_out, s_out)
            else:
                cout = width
                flops += _conv_flops(3, cin, width, s_out, s_out)
                flops += _conv_flops(3, width, cout, s_out, s_out)
            if stride != 1 or cin != cout:
                flops += _conv_flops(1, cin, cout, s_out, s_out)  # projection
            cin, s = cout, s_out
    flops += 2.0 * cin * num_classes  # head
    return batch * flops


def resnet_train_flops(arch: str, batch: int, image_size: int = 32, **kw: Any) -> float:
    return 3.0 * resnet_fwd_flops(arch, batch, image_size, **kw)


# ---------------------------------------------------------------------------
# UNet (models/unet.py)
# ---------------------------------------------------------------------------

def unet_fwd_flops(
    batch: int,
    image_size: int,
    *,
    features: tuple[int, ...] = (64, 128, 256, 512),
    in_channels: int = 1,
    out_channels: int = 2,
    dim: int = 2,
) -> float:
    """Forward FLOPs for one UNet batch (models/unet.py topology).

    Encoder: DoubleConv (2 × conv3^dim) per level + 2× downsample;
    bottleneck DoubleConv at 2·features[-1]; decoder: ConvTranspose
    (2^dim kernel, stride 2, halving channels) then DoubleConv on the
    skip-concatenated input; 1×1 head. ``dim`` generalizes to 3-D (voxel
    counts scale as size^dim, conv kernels as 3^dim).
    """
    def conv(k_vol: float, cin: int, cout: int, vox: float) -> float:
        return 2.0 * k_vol * cin * cout * vox

    k3 = 3.0 ** dim
    kt = 2.0 ** dim
    size = float(image_size)
    vox = size ** dim
    flops = 0.0
    cin = in_channels
    enc_vox = []
    for f in features:
        flops += conv(k3, cin, f, vox) + conv(k3, f, f, vox)
        enc_vox.append(vox)
        cin = f
        size /= 2
        vox = size ** dim
    bott = features[-1] * 2
    flops += conv(k3, cin, bott, vox) + conv(k3, bott, bott, vox)
    cin = bott
    for f, up_vox in zip(reversed(features), reversed(enc_vox)):
        flops += conv(kt, cin, f, up_vox)                 # transposed conv
        flops += conv(k3, 2 * f, f, up_vox) + conv(k3, f, f, up_vox)
        cin = f
    flops += conv(1.0, cin, out_channels, enc_vox[0])     # 1×1 head
    return batch * flops


def unet_train_flops(batch: int, image_size: int, **kw: Any) -> float:
    return 3.0 * unet_fwd_flops(batch, image_size, **kw)
