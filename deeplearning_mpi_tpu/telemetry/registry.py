"""Metrics registry: counters/gauges/histograms with pluggable sinks.

Design constraints, in order:

1. **Never add a device sync.** Per-step scalars come out of the jitted
   train step as aux outputs (already the Trainer's contract); the registry
   buffers the *device* arrays via :meth:`MetricsRegistry.record_step` and
   fetches them in ONE ``jax.device_get`` at :meth:`flush_steps` — called
   on the StepTimer's sync cadence or at epoch end, when the host was going
   to block anyway.
2. **One canonical record shape.** Every emission — step, epoch, eval,
   system — is a flat JSON-serializable dict ``{"ts": float, "kind": str,
   **values}``. ``RunLogger.log_metrics`` consumes exactly this shape (via
   :class:`LoggerSink`), ``tools/metrics_report.py`` parses exactly this
   shape, and tests round-trip it.
3. **Sinks are dumb.** A sink implements ``write(record: dict)`` and
   optionally ``close()``. The registry fans each record out to all of
   them; a sink must never raise into the training loop (JSONL write
   failures degrade to a dropped record, not a dead run).

Instruments follow the Prometheus taxonomy because it is the vocabulary
every operator already knows: ``Counter`` (monotonic, ``inc``), ``Gauge``
(set-to-current), ``Histogram`` (observations + percentile summary).
"""

from __future__ import annotations

import json
import math
import time
from pathlib import Path
from typing import Any, Iterable, Mapping

import jax


def _jsonable(v: Any) -> Any:
    """Coerce numpy/JAX scalars to plain floats; leave JSON types alone."""
    if isinstance(v, (str, bool, int, type(None))):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    return f if math.isfinite(f) else None


def labeled(name: str, **labels: str) -> str:
    """Prometheus-style labeled instrument name: ``name{k="v",...}``.

    The registry keys instruments by plain string, so labels are an encoding
    convention, not a type: ``labeled("serve_shed_total", reason="deadline")``
    → ``serve_shed_total{reason="deadline"}``. Keys are sorted so the same
    label set always maps to the same instrument, whatever the call-site
    spelling. The base (unlabeled) counter is maintained separately by
    callers — `snapshot()` reports both.
    """
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonically increasing count (steps run, tokens seen, bytes moved)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        self.value += amount


class Gauge:
    """Point-in-time value (HBM bytes in use, learning rate, MFU)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.value: float | None = None

    def set(self, value: float) -> None:
        self.value = float(value)


class Histogram:
    """Observation stream with percentile summaries (step latency)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.observations: list[float] = []

    def observe(self, value: float) -> None:
        self.observations.append(float(value))

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile over everything observed; ``q`` in [0, 1]."""
        if not self.observations:
            return None
        d = sorted(self.observations)
        return d[int(q * (len(d) - 1))]

    def summary(self) -> dict[str, float]:
        if not self.observations:
            return {}
        return {
            "count": float(len(self.observations)),
            "mean": sum(self.observations) / len(self.observations),
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "max": max(self.observations),
        }


class InMemorySink:
    """Keeps every record in a list — tests and ad-hoc inspection."""

    def __init__(self) -> None:
        self.records: list[dict] = []

    def write(self, record: dict) -> None:
        self.records.append(record)

    def close(self) -> None:
        pass


class JsonlSink:
    """One JSON object per line, flushed per record so a crashed run still
    has its telemetry (the metrics file doubles as a black box)."""

    def __init__(self, path: str | Path) -> None:
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self.path.open("a")

    def write(self, record: dict) -> None:
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class LoggerSink:
    """Adapter onto ``RunLogger.log_metrics`` — the canonical record IS the
    RunLogger record (satellite: one schema, two consumers)."""

    def __init__(self, logger: Any) -> None:
        self._logger = logger

    def write(self, record: dict) -> None:
        self._logger.log_metrics(record)

    def close(self) -> None:
        pass


class TensorBoardSink:
    """Optional scalar export. Soft dependency: constructing it without a
    TensorBoard writer available raises ImportError — callers gate on it;
    nothing else in the registry imports tensorboard."""

    def __init__(self, log_dir: str | Path) -> None:
        try:
            from flax.metrics import tensorboard as _tb  # type: ignore

            self._writer = _tb.SummaryWriter(str(log_dir))
        except ImportError:
            try:
                from torch.utils import tensorboard as _tb  # type: ignore

                self._writer = _tb.SummaryWriter(str(log_dir))
            except ImportError as e:
                raise ImportError(
                    "TensorBoardSink needs flax.metrics.tensorboard or "
                    "torch.utils.tensorboard"
                ) from e

    def write(self, record: dict) -> None:
        step = int(record.get("step", record.get("epoch", 0)) or 0)
        for key, value in record.items():
            if key in ("ts", "kind", "step", "epoch"):
                continue
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                self._writer.scalar(f"{record.get('kind', 'run')}/{key}", value, step)

    def close(self) -> None:
        self._writer.flush()


class MetricsRegistry:
    """Named instruments + record emission + step-scalar buffering.

    ``emit(kind, values)`` is the only path a record takes to the sinks, so
    the canonical shape is enforced in one place. ``record_step`` /
    ``flush_steps`` implement the no-extra-syncs contract described in the
    module docstring.
    """

    def __init__(self, sinks: Iterable[Any] = ()) -> None:
        self.sinks: list[Any] = list(sinks)
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        # [(step, {name: device-or-host scalar})] awaiting one device_get.
        self._pending_steps: list[tuple[int, dict[str, Any]]] = []

    # -- instruments (get-or-create, Prometheus style) ---------------------
    def counter(self, name: str) -> Counter:
        return self._counters.setdefault(name, Counter(name))

    def gauge(self, name: str) -> Gauge:
        return self._gauges.setdefault(name, Gauge(name))

    def histogram(self, name: str) -> Histogram:
        return self._histograms.setdefault(name, Histogram(name))

    # -- sinks -------------------------------------------------------------
    def add_sink(self, sink: Any) -> None:
        self.sinks.append(sink)

    def emit(self, kind: str, values: Mapping[str, Any]) -> dict:
        """Fan one canonical record out to every sink; returns the record."""
        record = {"ts": time.time(), "kind": kind}
        record.update({k: _jsonable(v) for k, v in values.items()})
        for sink in self.sinks:
            try:
                sink.write(record)
            except Exception:
                pass  # a sink must never kill the training loop
        return record

    # -- per-step scalars out of the jitted step ---------------------------
    def record_step(self, step: int, scalars: Mapping[str, Any]) -> None:
        """Buffer one step's aux-output scalars WITHOUT reading them.

        ``scalars`` values may be live device arrays; they are not fetched
        here — the train loop keeps running ahead of the device.
        """
        self._pending_steps.append((step, dict(scalars)))

    def flush_steps(self, extra: Mapping[str, Any] | None = None) -> list[dict]:
        """One ``jax.device_get`` for everything buffered, then emit one
        ``"step"`` record per step. ``extra`` keys (e.g. the step-duration
        estimates the StepTimer attributed to this window) are merged into
        every record of the flush."""
        if not self._pending_steps:
            return []
        pending, self._pending_steps = self._pending_steps, []
        fetched = jax.device_get([s for _, s in pending])
        extra = dict(extra or {})
        out = []
        for (step, _), scalars in zip(pending, fetched):
            values = {"step": step, **scalars, **extra}
            out.append(self.emit("step", values))
        return out

    def drop_pending_steps(self) -> int:
        """Discard the buffered (unfetched) step scalars; returns the count.

        Rollback path (numerics guardrails): a ``poisoned`` verdict means
        the steps since the episode opened never happened — their buffered
        records must not reach the sinks as if they were real training
        progress. Dropping device references is free (no device_get).
        """
        n = len(self._pending_steps)
        self._pending_steps.clear()
        return n

    def snapshot(self) -> dict[str, Any]:
        """Current instrument values as one flat dict (for epoch records)."""
        snap: dict[str, Any] = {}
        for c in self._counters.values():
            snap[c.name] = c.value
        for g in self._gauges.values():
            if g.value is not None:
                snap[g.name] = g.value
        for h in self._histograms.values():
            for stat, v in h.summary().items():
                snap[f"{h.name}_{stat}"] = v
        return snap

    def close(self) -> None:
        try:
            self.flush_steps()  # a crashed/short run still keeps its buffer
        except Exception:
            pass
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:
                pass
