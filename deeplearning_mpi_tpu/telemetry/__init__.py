"""Unified telemetry: metrics registry + sinks, trace annotations, and
derived accounting (analytic FLOPs → MFU, HBM usage, collective bytes).

The split, by question answered:

- :mod:`.registry` — *what happened*: counters/gauges/histograms, per-step
  device scalars buffered without extra syncs, canonical JSONL records.
- :mod:`.trace` — *where time went*: named scopes + trace annotations on
  every parallel hot path, so profiler timelines are readable.
- :mod:`.spans` — *what happened to THIS request/step*: explicit spans with
  a cross-process correlation key, per-process JSONL recorders with clock
  alignment, and the crash flight recorder. ``tools/trace_report.py``
  merges them into a Perfetto timeline.
- :mod:`.flops` — *how fast it could have been*: analytic per-model FLOPs
  and MFU against device peak.
- :mod:`.memory` — *how close to the HBM wall*: ``device.memory_stats()``.
- :mod:`.comms` — *what crossed the wires*: static collective-byte
  accounting from shapes and mesh axis sizes.

``tools/metrics_report.py`` renders the JSONL these produce into the
summary table; ``docs/OBSERVABILITY.md`` explains the columns.
"""

from deeplearning_mpi_tpu.telemetry.registry import (
    InMemorySink,
    JsonlSink,
    LoggerSink,
    MetricsRegistry,
    TensorBoardSink,
    labeled,
)
from deeplearning_mpi_tpu.telemetry.spans import Span, SpanRecorder
from deeplearning_mpi_tpu.telemetry.trace import annotate, annotate_fn

__all__ = [
    "InMemorySink",
    "JsonlSink",
    "LoggerSink",
    "MetricsRegistry",
    "Span",
    "SpanRecorder",
    "TensorBoardSink",
    "annotate",
    "annotate_fn",
    "labeled",
]
