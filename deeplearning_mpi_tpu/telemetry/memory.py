"""Live HBM accounting via ``device.memory_stats()``.

On TPU (and GPU) backends every device reports ``bytes_in_use`` /
``bytes_limit`` and peak counters; on CPU the method returns ``None`` (there
is no device allocator to meter). Everything here is None-safe: the metrics
records simply omit HBM columns on CPU meshes rather than inventing numbers
— unlike MFU, where a nominal peak keeps the column defined (see
``telemetry.flops``), fake memory numbers would mask real OOM headroom.
"""

from __future__ import annotations

from typing import Any, Iterable

import jax


def device_memory_stats(device: Any) -> dict[str, float] | None:
    """One device's allocator stats, or None where unsupported (CPU)."""
    stats = None
    try:
        stats = device.memory_stats()
    except (AttributeError, NotImplementedError, RuntimeError):
        return None
    if not stats:
        return None
    return {k: float(v) for k, v in stats.items() if isinstance(v, (int, float))}


def hbm_usage(devices: Iterable[Any] | None = None) -> dict[str, float] | None:
    """Fleet-level HBM summary for the metrics record.

    Returns ``{"hbm_bytes_in_use", "hbm_bytes_limit", "hbm_peak_bytes",
    "hbm_utilization"}`` aggregated over the *max-loaded* device (the one
    that OOMs first is the one that matters), or None when no device
    reports stats.
    """
    if devices is None:
        devices = jax.local_devices()
    per_device = [s for d in devices if (s := device_memory_stats(d))]
    if not per_device:
        return None
    worst = max(per_device, key=lambda s: s.get("bytes_in_use", 0.0))
    out = {"hbm_bytes_in_use": worst.get("bytes_in_use", 0.0)}
    limit = worst.get("bytes_limit")
    if limit:
        out["hbm_bytes_limit"] = limit
        out["hbm_utilization"] = out["hbm_bytes_in_use"] / limit
    peak = worst.get("peak_bytes_in_use")
    if peak is not None:
        out["hbm_peak_bytes"] = peak
    return out
