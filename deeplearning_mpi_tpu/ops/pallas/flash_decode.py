"""Fused flash-decode kernel: one grid over the KV cache's filled prefix.

The long-buffer decode schedule (`ops.attention.decode_attention`'s blockwise
walk) pays a measured ~40 µs of loop overhead per `lax.fori_loop` iteration —
~45% of the HBM roofline at block 512, amortized but not gone at 2048
(`docs/PERF_ANALYSIS.md` §9). This kernel replaces the host-orchestrated walk
with ONE `pallas_call`: the kv-block axis is a sequential grid dimension, the
online-softmax accumulator lives in VMEM scratch, and the dynamic fill level
rides a scalar-prefetch argument:

- the **index map clamps both ends**: out-of-prefix grid steps collapse
  onto the last filled block, and (for sliding-window models) pre-window
  steps onto the window's first block — Mosaic skips the DMA when
  consecutive steps map to the same block, so HBM traffic stays O(index)
  (O(window) with a window), the walk's defining advantage over the
  read-everything dense path;
- the **compute gate** (`pl.when(j_lo <= j < n_valid)`) skips their FLOPs;
- masking inside the boundary blocks uses the prefetched `index` scalar
  (both the filled-prefix end and the window's trailing edge).

The prefetched index is PER ROW (`[B]`; a scalar broadcasts): every batch
row clamps, gates, and masks against its own fill level. That is the shape
continuous batching needs — a serving engine's decode slots all sit at
different sequence lengths, and one fixed-shape kernel call covers them
(`serving/engine.py` gathers each slot's pages and hands the per-slot
lengths straight in).

Layout: the cache is BSHD (`[B, L, Hkv, D]`) and the kernel blocks over L
only, keeping each row's full `Hkv x D` contiguous — the same access pattern
the dense einsum path achieves roofline with. Grouped-query heads are
consumed natively (Hkv < H reads Hkv rows, like the walk). No reference
analog (the reference has no attention at all — SURVEY.md §5.7).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning_mpi_tpu.runtime.compat import tpu_compiler_params
from deeplearning_mpi_tpu.telemetry.trace import annotate

from deeplearning_mpi_tpu.ops.attention import NEG_INF


def _window_start_block(index, window: int, block: int):
    """First cache block intersecting the window — ONE definition shared by
    the kernel's compute gate and the index map's clamp: if the two drift,
    a gated-on grid step could score a block whose DMA was collapsed onto
    a different one (silently wrong output)."""
    return jnp.maximum(index - window + 1, 0) // block


def quantize_kv(buf: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-row-per-head int8 for KV cache buffers.

    ``[B, L, Hkv, D]`` float → ``(int8 [B, L, Hkv, D], f32 scales
    [B, L, Hkv])``. Halves the decode phase's per-row cache bytes — the
    term batching cannot amortize (PERF_ANALYSIS §10: ~75 MB/step/row at
    2k MHA vs the 220 MB batch-invariant weight read) — at a per-element
    quantization error ≤ scale/2, the same contract as the weight-only
    int8 kernels (`ops/quant.py`).
    """
    amax = jnp.max(jnp.abs(buf.astype(jnp.float32)), axis=-1)
    scales = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.round(buf.astype(jnp.float32) / scales[..., None])
    return q.astype(jnp.int8), scales


#: Module default for the KV block — what an untuned :func:`flash_decode`
#: call resolves to. A tuning DB entry for the buffer's exact (shape,
#: dtype, backend) overrides it; an explicit ``block=`` kwarg overrides
#: everything (``ops.attention`` passes the fitted block explicitly).
DEFAULT_DECODE_BLOCK = 1024


def resolve_decode_block(block: int | None, shape: tuple[int, ...], dtype) -> int:
    """Block resolution: explicit kwarg > tuning-DB ``flash_decode`` entry
    for this ``[B, L, Hkv, D]`` buffer > module default. Never raises."""
    if block is not None:
        return block
    try:
        from deeplearning_mpi_tpu.compiler.autotune import (
            tuned_decode_schedule,
        )

        tuned = tuned_decode_schedule(tuple(shape), dtype)
        if tuned and tuned.get("block"):
            return int(tuned["block"])
    except Exception:
        pass
    return DEFAULT_DECODE_BLOCK


def _decode_kernel(
    idx_ref, q_ref, *refs,
    block: int, kv_heads: int, group: int, scale: float,
    window: int | None = None, quantized: bool = False,
):
    if quantized:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, acc, m, l = refs
    else:
        (k_ref, v_ref, o_ref, acc, m, l), ks_ref, vs_ref = refs, None, None
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m[...] = jnp.full_like(m, NEG_INF)
        l[...] = jnp.zeros_like(l)

    index = idx_ref[pl.program_id(0)]  # this row's fill level
    n_valid = (index + block) // block  # blocks with >= 1 filled row
    run = j < n_valid
    if window is not None:
        # Sliding-window models: blocks wholly before the window are
        # skipped (their DMAs collapse onto the window's first block via
        # the clamped index map) — O(window) traffic per token, like the
        # walk's start-block skip.
        run = run & (j >= _window_start_block(index, window, block))

    @pl.when(run)
    def _update():
        # Rows beyond the filled prefix are masked (only the boundary block
        # has any; interior blocks mask nothing and the where folds away).
        pos = j * block + lax.broadcasted_iota(jnp.int32, (1, block), 1)
        valid = pos <= index  # [1, block]
        if window is not None:
            valid &= pos > index - window
        for h in range(kv_heads):
            q_h = q_ref[0, 0, h * group : (h + 1) * group, :]  # [G, D]
            # int8 buffers: cast to the q dtype for fast MXU dots and
            # factor the per-row scales OUT of the contractions (the
            # QuantDense dot-then-scale form, ops/quant.py — bf16's 8
            # mantissa bits represent ±127 exactly): the K scales multiply
            # the score columns after the dot, the V scales fold into p
            # before the V dot — O(block) scale work, not O(block·D).
            k_h = k_ref[0, :, h, :].astype(q_h.dtype)  # [block, D]
            v_h = v_ref[0, :, h, :].astype(q_h.dtype)
            s = lax.dot_general(
                q_h, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G, block]
            if quantized:
                s = s * ks_ref[0, :, h][None, :]
            s = jnp.where(valid, s, NEG_INF)
            rows = slice(h * group, (h + 1) * group)
            m_prev = m[rows, :1]  # [G, 1]
            l_prev = l[rows, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            p = jnp.where(valid, p, 0.0)  # finite NEG_INF ⇒ re-zero masked
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
            if quantized:
                p = p * vs_ref[0, :, h][None, :]
            pv = lax.dot_general(
                p.astype(v_h.dtype), v_h, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [G, D]
            acc[rows, :] = acc[rows, :] * alpha + pv
            m[rows, :] = jnp.broadcast_to(m_new, (group, m.shape[1]))
            l[rows, :] = jnp.broadcast_to(l_new, (group, l.shape[1]))

    @pl.when(j == nb - 1)
    def _finalize():
        # Block 0 always holds >= 1 filled row (index >= 0), so l > 0 on
        # the real rows; scratch is sublane-padded, so slice them out.
        heads = kv_heads * group
        o_ref[0, 0] = (acc[:heads, :] / l[:heads, :1]).astype(o_ref.dtype)


def flash_decode(
    q: jax.Array,
    k_buf: jax.Array,
    v_buf: jax.Array,
    index: jax.Array,
    *,
    block: int | None = None,
    interpret: bool | None = None,
    window: int | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """One fused decode step over the cache's filled prefix.

    Same contract as the blockwise walk in
    :func:`~deeplearning_mpi_tpu.ops.attention.decode_attention`: ``q``
    ``[B, 1, H, D]``, grouped cache buffers ``[B, L, Hkv, D]``, positions
    ``0..index`` filled (``window``: attend the last ``window`` of them
    only); returns ``[B, 1, H, D]``. Caller guarantees ``L % block == 0``
    (see :func:`decode_block_fits`). ``block=None`` resolves through
    :func:`resolve_decode_block` — a tuning-DB entry for this buffer shape
    when installed, else the 1024 module default.

    ``index`` may be a scalar (every row at the same fill — the single-
    sequence CLI path) or ``[B]`` (per-row fills — continuous-batching
    slots); HBM traffic stays O(own index) per row either way.

    ``k_scale``/``v_scale`` (``[B, L, Hkv]`` f32, from :func:`quantize_kv`)
    switch the buffers to int8: the kernel reads half the cache bytes per
    step — the batched-decode term §10's roofline says batching can't
    amortize — and dequantizes per block in VMEM.
    """
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")
    if quantized and (k_buf.dtype != jnp.int8 or v_buf.dtype != jnp.int8):
        raise ValueError(
            f"scales given but buffers are not int8 (k={k_buf.dtype}, "
            f"v={v_buf.dtype}) — quantize BOTH with quantize_kv first"
        )
    batch, q_len, heads, head_dim = q.shape
    length, kv_heads = k_buf.shape[1], k_buf.shape[2]
    group = heads // kv_heads
    block = resolve_decode_block(block, k_buf.shape, k_buf.dtype)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_blocks = length // block
    index = jnp.asarray(index, jnp.int32)
    if index.ndim == 0:
        index = jnp.broadcast_to(index[None], (batch,))
    elif index.shape != (batch,):
        raise ValueError(
            f"index must be a scalar or [{batch}] (one fill level per row), "
            f"got shape {index.shape}"
        )

    def q_map(b, j, idx_ref):
        del idx_ref, j
        return (b, 0, 0, 0)

    def kv_map(b, j, idx_ref):
        # Index maps receive the prefetched scalars AFTER the grid indices,
        # as a ([B],)-shaped ref: row b clamps against its own fill level.
        idx = idx_ref[b]
        n_valid = (idx + block) // block
        # Clamp both ends: steps past the prefix revisit the last filled
        # block, pre-window steps the window's first block — Mosaic skips
        # the DMA on consecutive identical indices either way.
        j_eff = jnp.minimum(j, n_valid - 1)
        if window is not None:
            j_eff = jnp.maximum(
                j_eff, _window_start_block(idx, window, block)
            )
        return (b, j_eff, 0, 0)

    kv_spec = pl.BlockSpec((1, block, kv_heads, head_dim), kv_map,
                           memory_space=pltpu.VMEM)
    scale_spec = pl.BlockSpec(
        (1, block, kv_heads), lambda b, j, idx_ref: kv_map(b, j, idx_ref)[:3],
        memory_space=pltpu.VMEM,
    )
    in_specs = [
        pl.BlockSpec((1, 1, heads, head_dim), q_map, memory_space=pltpu.VMEM),
        kv_spec,
    ]
    operands = [q, k_buf]
    if quantized:
        in_specs.append(scale_spec)
        operands.append(k_scale)
    in_specs.append(kv_spec)
    operands.append(v_buf)
    if quantized:
        in_specs.append(scale_spec)
        operands.append(v_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch, n_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, heads, head_dim), q_map,
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            # Rows padded to the 8-row sublane (H = 12 at the 110M config);
            # the kernel touches only the first `heads` rows.
            pltpu.VMEM((-(-heads // 8) * 8, head_dim), jnp.float32),  # acc
            pltpu.VMEM((-(-heads // 8) * 8, 128), jnp.float32),  # running max
            pltpu.VMEM((-(-heads // 8) * 8, 128), jnp.float32),  # denom
        ],
    )
    with annotate("pallas/flash_decode"):
        return pl.pallas_call(
            functools.partial(
                _decode_kernel,
                block=block, kv_heads=kv_heads, group=group,
                scale=head_dim**-0.5, window=window, quantized=quantized,
            ),
            out_shape=jax.ShapeDtypeStruct((batch, 1, heads, head_dim), q.dtype),
            grid_spec=grid_spec,
            compiler_params=tpu_compiler_params(
                dimension_semantics=("parallel", "arbitrary"),
            ),
            interpret=interpret,
        )(index, *operands)


#: Smallest block the kernel accepts: below this the grid degenerates into
#: the near-scalar slicing the walk's full-size-block design exists to
#: avoid (attention.py's non-dividing-length comment) — fall back to the
#: walk instead of silently running a 100+-step tiny-block grid.
_MIN_DECODE_BLOCK = 256


def decode_block_fits(block: int, length: int) -> int | None:
    """Largest ``fit_block``-shrunk block that tiles ``length``, or None.

    Decode buffers are ``prompt + max_new`` (arbitrary), so non-tileable
    lengths (and lengths only tileable by degenerate tiny blocks) fall
    back to the XLA walk rather than constraining the CLI.
    """
    from deeplearning_mpi_tpu.ops.pallas.flash_attention import fit_block

    b = fit_block(block, length)
    # Floor scales down with an explicitly small requested block (tests use
    # 16-row blocks on tiny buffers); the dispatcher's production request
    # (1024) gets the full floor.
    if length % b or b % 8 or b < min(_MIN_DECODE_BLOCK, block):
        return None
    return b
