"""Flash attention: tiled online-softmax Pallas TPU kernel.

The memory-bound op XLA cannot rescue: dense attention materializes the
``[S, S]`` score matrix in HBM, so past ~8k tokens the HBM round-trips (not
the MXU) bound throughput and past ~32k the scores don't fit at all. This
kernel streams K/V blocks through VMEM against a resident Q block,
maintaining the flash-attention online-softmax accumulator
``(acc, m, l)`` in VMEM scratch — O(S) memory, every matmul an
MXU-shaped ``[block_q, head_dim] x [head_dim, block_k]`` tile.

Schedule: grid ``(batch, heads, q_blocks, kv_blocks)``, the first three axes
parallel (Mosaic splits them over the two TensorCores), the kv axis
sequential ("arbitrary") so scratch carries the accumulator across kv steps.
Causal masking is positional arithmetic in global coordinates; kv blocks
entirely in a q block's future skip their matmuls via ``pl.when``.

Backward is a custom VJP in blockwise pure JAX (``lax.scan`` over kv
blocks): recomputes the row logsumexp online, then accumulates
dq/dk/dv per block — O(S·block_k) live memory, never the full score
matrix. It trades one extra QKᵀ pass (~20% backward FLOPs) for not
threading the lse out of the kernel; the Pallas backward kernel is a
later optimization.

No reference analog (the reference has no attention — SURVEY.md §5.7).
Conventions follow ``ops.attention.dense_attention`` (BSHD layout, f32
softmax, zero rows for fully-masked queries), which is the oracle in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning_mpi_tpu.ops.attention import NEG_INF, dense_attention


def _swap_sh(x: jax.Array) -> jax.Array:
    """BSHD <-> BHSD (self-inverse transpose of the seq/heads axes)."""
    return x.transpose(0, 2, 1, 3)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, causal: bool, scale: float, block_q: int, block_k: int,
):
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: skip kv blocks whose every key is in every query's future.
    run = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _update():
        q = q_ref[0, 0]  # [bq, d]
        k = k_ref[0, 0]  # [bk, d]
        v = v_ref[0, 0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if causal:
            q_pos = i * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            # finite NEG_INF ⇒ exp(0)=1 on rows still at the init value; re-zero.
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        o = acc_ref[...] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = jnp.where(l > 0.0, o, 0.0).astype(o_ref.dtype)


def _fwd_pallas(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, block_q: int, block_k: int, interpret: bool,
) -> jax.Array:
    """Run the kernel on BHSD-transposed inputs; returns BSHD output."""
    batch, seq, heads, head_dim = q.shape
    bq, bk = min(block_q, seq), min(block_k, seq)
    qt, kt, vt = _swap_sh(q), _swap_sh(k), _swap_sh(v)
    grid = (batch, heads, seq // bq, seq // bk)
    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel,
            causal=causal, scale=head_dim**-0.5, block_q=bq, block_k=bk,
        ),
        out_shape=jax.ShapeDtypeStruct((batch, heads, seq, head_dim), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, bq, head_dim), lambda b, h, i, j: (b, h, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, bk, head_dim), lambda b, h, i, j: (b, h, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, bk, head_dim), lambda b, h, i, j: (b, h, j, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bq, head_dim), lambda b, h, i, j: (b, h, i, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, head_dim), jnp.float32),  # acc
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (lane-replicated)
            pltpu.VMEM((bq, 128), jnp.float32),  # running denom
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    return _swap_sh(out)


def _blockwise_lse(
    q: jax.Array, k_blocks: jax.Array, causal: bool, block_k: int, scale: float
) -> jax.Array:
    """Row logsumexp over all keys, streamed kv-block-wise. BHSD q."""
    seq = q.shape[2]

    def step(carry, inputs):
        m, l = carry
        j, k_blk = inputs
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = lax.broadcasted_iota(jnp.int32, (seq, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(jnp.int32, (seq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # Rows masked in every block seen so far self-pollute (exp(0)=1 per
        # masked entry), but the first valid block rescales l by
        # exp(NEG_INF - real_max) = 0, erasing the pollution — and causally
        # every row has a valid diagonal key, so the global lse is exact.
        p_sum = jnp.sum(jnp.exp(s - m_new[..., None]), axis=-1)
        l_new = l * jnp.exp(m - m_new) + p_sum
        return (m_new, l_new), None

    nk = k_blocks.shape[0]
    batch, heads, _, _ = q.shape
    m0 = jnp.full((batch, heads, seq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((batch, heads, seq), jnp.float32)
    (m, l), _ = lax.scan(step, (m0, l0), (jnp.arange(nk), k_blocks))
    return m + jnp.log(jnp.maximum(l, 1e-30))  # lse; fully-masked rows: ~NEG_INF


def _flash_bwd_impl(
    q: jax.Array, k: jax.Array, v: jax.Array, o: jax.Array, do: jax.Array,
    causal: bool, block_k: int, interpret: bool,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Blockwise flash backward in pure JAX (BSHD in/out)."""
    del interpret
    batch, seq, heads, head_dim = q.shape
    bk = min(block_k, seq)
    nk = seq // bk
    scale = head_dim**-0.5
    qt, kt, vt = _swap_sh(q), _swap_sh(k), _swap_sh(v)
    ot, dot_ = _swap_sh(o).astype(jnp.float32), _swap_sh(do).astype(jnp.float32)
    k_blocks = kt.reshape(batch, heads, nk, bk, head_dim).transpose(2, 0, 1, 3, 4)
    v_blocks = vt.reshape(batch, heads, nk, bk, head_dim).transpose(2, 0, 1, 3, 4)

    lse = _blockwise_lse(qt, k_blocks, causal, bk, scale)  # [B,H,S]
    delta = jnp.sum(ot * dot_, axis=-1)  # [B,H,S] row dot(o, do)

    def step(dq_acc, inputs):
        j, k_blk, v_blk = inputs
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qt, k_blk, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = lax.broadcasted_iota(jnp.int32, (seq, bk), 0)
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, (seq, bk), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [B,H,S,bk]; 0 for masked/empty rows
        if causal:
            p = jnp.where(mask, p, 0.0)
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, dot_, preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dot_, v_blk, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum(
            "bhqk,bhkd->bhqd", ds, k_blk, preferred_element_type=jnp.float32
        )
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qt, preferred_element_type=jnp.float32)
        return dq_acc, (dk_blk, dv_blk)

    dq0 = jnp.zeros((batch, heads, seq, head_dim), jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(
        step, dq0, (jnp.arange(nk), k_blocks, v_blocks)
    )
    merge = lambda blocks: _swap_sh(  # noqa: E731  [nk,B,H,bk,D] -> BSHD
        blocks.transpose(1, 2, 0, 3, 4).reshape(batch, heads, seq, head_dim)
    )
    return (
        _swap_sh(dq).astype(q.dtype),
        merge(dk_blocks).astype(k.dtype),
        merge(dv_blocks).astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _fwd_pallas(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o = _fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, o = res
    return _flash_bwd_impl(q, k, v, o, do, causal, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
) -> jax.Array:
    """Tiled flash attention over ``[B, S, H, D]`` (drop-in for
    ``dense_attention`` and valid as ``TransformerLM(attention_fn=...)``).

    ``interpret=None`` auto-selects: compiled Mosaic on TPU, the Pallas
    interpreter elsewhere (so CPU tests and the virtual-device mesh run the
    same code path). Sequences not divisible by the (clamped) block sizes
    fall back to the dense op — correctness everywhere, tiling where it
    counts.

    Default 1024×1024 blocks are from an on-chip sweep (v5e, S=4096 B4 H8
    D64): 12.40 ms (128², the flash-paper-style default) → 6.01 (256²) →
    2.79 (512²) → 1.46 ms (1024²) device time per fwd — 8.5× from block
    shape alone; small tiles leave the MXU idle between the many
    sequential-kv grid steps. VMEM cost at 1024² is ~1.8 MiB
    (q/k/v tiles + f32 accumulator + lane-replicated m/l), comfortably
    inside any TPU's VMEM, and clamping handles seq < 1024.
    """
    seq = q.shape[1]

    def fit(block: int) -> int:
        # Shrink until the block divides seq (halving preserves MXU-friendly
        # sizes): seq=1536 with the 1024 default tiles at 512 instead of
        # silently regressing to the dense O(S^2) fallback.
        b = min(block, seq)
        while b > 8 and seq % b:
            b //= 2
        return b

    bq, bk = fit(block_q), fit(block_k)
    if seq % bq or seq % bk:
        return dense_attention(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, bq, bk, interpret)
