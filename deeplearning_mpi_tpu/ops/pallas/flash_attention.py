"""Flash attention: tiled online-softmax Pallas TPU kernel.

The memory-bound op XLA cannot rescue: dense attention materializes the
``[S, S]`` score matrix in HBM, so past ~8k tokens the HBM round-trips (not
the MXU) bound throughput and past ~32k the scores don't fit at all. This
kernel streams K/V blocks through VMEM against a resident Q block,
maintaining the flash-attention online-softmax accumulator
``(acc, m, l)`` in VMEM scratch — O(S) memory, every matmul an
MXU-shaped ``[block_q, head_dim] x [head_dim, block_k]`` tile.

Schedule: grid ``(batch, heads, q_blocks, kv_blocks)``, the first three axes
parallel (Mosaic splits them over the two TensorCores), the kv axis
sequential ("arbitrary") so scratch carries the accumulator across kv steps.
Causal masking is positional arithmetic in global coordinates; kv blocks
entirely in a q block's future skip their matmuls via ``pl.when``.

Backward is a custom VJP over two more Pallas kernels (FlashAttention-2
style): the forward threads the per-row logsumexp out as a second output;
then a dq kernel streams kv blocks against each resident q block and a
dk/dv kernel streams q blocks against each resident kv block, each
recomputing its p tile from (s − lse) and ``delta = rowsum(o·do)`` in VMEM
— O(S) memory, no probability matrix ever touches HBM (the prior
blockwise-JAX backward materialized ``[B,H,S,block_k]`` p tensors per scan
step, which dominated HBM traffic at long S; an XLA-side lane-replicated
delta costs more than both backward kernels combined, hence the in-kernel
recompute).

No reference analog (the reference has no attention — SURVEY.md §5.7).
Conventions follow ``ops.attention.dense_attention`` (BSHD layout, f32
softmax, zero rows for fully-masked queries), which is the oracle in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning_mpi_tpu.runtime.compat import tpu_compiler_params
from deeplearning_mpi_tpu.telemetry.trace import annotate

from deeplearning_mpi_tpu.ops.attention import NEG_INF, dense_attention


def _swap_sh(x: jax.Array) -> jax.Array:
    """BSHD <-> BHSD (self-inverse transpose of the seq/heads axes)."""
    return x.transpose(0, 2, 1, 3)


def _blocks_interact(i, j, *, causal: bool, window: int | None,
                     block_q: int, block_k: int, shift: int = 0):
    """Whether (q block ``i``, kv block ``j``) has any unmasked pair — the
    ``pl.when`` gate that skips whole tiles. Causal skips kv blocks wholly in
    the future; ``window`` (sliding-window attention) additionally skips kv
    blocks wholly before every query's window, which is where the O(S·W)
    cost of windowed attention comes from (the per-element mask alone would
    still pay O(S²/2) matmuls).

    ``shift`` is a STATIC global offset added to every q position: the ring
    schedule calls the kernels once per rotation with the visiting K/V block
    ``t`` shards behind the resident Q shard, i.e. every query sits
    ``shift = t * s_local`` positions after the keys — the same trimmed-grid
    arithmetic then windows the off-diagonal rotations (rotation skipping's
    in-block half)."""
    q_hi = i * block_q + block_q - 1 + shift
    run = (j * block_k <= q_hi) if causal else True
    if window is not None:
        newest_key = (j + 1) * block_k - 1
        oldest_window_pos = i * block_q + shift - (window - 1)
        run = run & (newest_key >= oldest_window_pos)
    return run


def _window_span(window: int, block_stream: int, block_resident: int,
                 n_stream: int) -> int:
    """Static length of the TRIMMED streaming grid axis under a window: how
    many streamed blocks one resident block can interact with, worst
    alignment. ``pl.when`` gating alone only skips the *compute* of
    out-of-window tiles — Mosaic still DMAs every grid step's K/V blocks, so
    the measured 32k speedup capped at ~1.6× fwd (vs ~3× by tile count).
    Shrinking the grid axis itself to this span and anchoring its index map
    per resident block makes iteration count AND HBM traffic O(S·W).

    Derivation (forward: resident q block of ``block_resident`` rows,
    streaming kv in ``block_stream``-row blocks): the keys one q block can
    see span ``block_resident + window - 1`` positions, which touches at
    most ``(block_resident + window - 2) // block_stream + 2`` blocks over
    all alignments. Symmetric for the dkv kernel (resident kv, streamed q).
    """
    return min(n_stream, (block_resident + window - 2) // block_stream + 2)


def _pair_mask(s_shape, i, j, *, window: int | None,
               block_q: int, block_k: int, shift: int = 0):
    """Causal (+ window) mask for one ``[bq, bk]`` score tile, in global
    coordinates (``shift`` = static q-position offset, see
    :func:`_blocks_interact`)."""
    q_pos = i * block_q + shift + lax.broadcasted_iota(jnp.int32, s_shape, 0)
    k_pos = j * block_k + lax.broadcasted_iota(jnp.int32, s_shape, 1)
    mask = q_pos >= k_pos
    if window is not None:
        mask &= q_pos - k_pos < window
    return mask


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, *rest,
    causal: bool, scale: float, block_q: int, block_k: int, with_lse: bool,
    window: int | None = None, shift: int = 0, n_kv_blocks: int = 0,
):
    if with_lse:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        (acc_ref, m_ref, l_ref), lse_ref = rest, None
    i = pl.program_id(2)
    jj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(jj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Under a window the kv grid axis is TRIMMED (see _window_span): grid
    # step jj maps to global kv block j anchored at this q block's causal
    # frontier — clamped to the last real kv block, since a nonzero shift
    # pushes the frontier past the buffer (the span still covers the whole
    # window; over-enumerated stale blocks gate off). Without a window, the
    # axis is the full kv range and jj == j.
    if window is not None:
        anchor = jnp.minimum(
            ((i + 1) * block_q - 1 + shift) // block_k, n_kv_blocks - 1
        )
        j = anchor - (nk - 1) + jj
    else:
        j = jj
    # Causal: skip kv blocks wholly in the future; window: also wholly-stale
    # ones and the clamped-to-0 reads below the sequence start.
    run = _blocks_interact(
        i, j, causal=causal, window=window, block_q=block_q, block_k=block_k,
        shift=shift,
    )
    if window is not None:
        run = run & (j >= 0)

    @pl.when(run)
    def _update():
        q = q_ref[0, 0]  # [bq, d]
        k = k_ref[0, 0]  # [bk, d]
        v = v_ref[0, 0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if causal:
            mask = _pair_mask(
                s.shape, i, j, window=window, block_q=block_q,
                block_k=block_k, shift=shift,
            )
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            # finite NEG_INF ⇒ exp(0)=1 on rows still at the init value; re-zero.
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jj == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        o = acc_ref[...] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = jnp.where(l > 0.0, o, 0.0).astype(o_ref.dtype)
        if lse_ref is not None:
            # Row logsumexp for the backward pass, lane-replicated [bq, 128]
            # like the running stats (Mosaic block shapes need the last two
            # dims (8,128)-aligned, so a flat [bq] store is not lowerable;
            # the 128x storage is the standard TPU-flash trade — jax's own
            # kernel stores l/m the same way). Only the grad path pays the
            # write: the primal forward runs with with_lse=False. A
            # fully-masked row gets NEG_INF, which the backward treats as
            # "never happens" — see _tile_p_ds's masked-row note.
            lse_ref[0, 0] = jnp.where(
                l_ref[...] > 0.0,
                m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-37)),
                NEG_INF,
            )


def _fwd_pallas(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, block_q: int, block_k: int, interpret: bool,
    with_lse: bool,
    out_dtype: jax.typing.DTypeLike | None = None,
    native_bhsd: bool = False,
    window: int | None = None,
    shift: int = 0,
) -> tuple[jax.Array, jax.Array | None]:
    """Run the kernel on BHSD-transposed inputs; returns BSHD output plus
    (when ``with_lse``, i.e. under grad) the per-row logsumexp
    ``[B, H, S, 128]`` lane-replicated backward residual. The primal skips
    it — the lse write would be 4x the HBM bytes of the output itself at
    D=64 bf16. ``out_dtype`` overrides the output dtype (default: match q)
    — the ring schedule requests f32 partials so its cross-rotation
    logsumexp merge never rounds through bf16 (mirrors ``grad_dtype`` in
    :func:`_bwd_pallas`; the accumulator is f32 in VMEM either way, this
    only changes the final store). ``native_bhsd``: inputs and output are
    already ``[B, H, S, D]`` — no transposes at either boundary (the
    zero-copy layout path; see :func:`flash_attention_bhsd`). ``shift``:
    static global q-position offset for the ring's off-diagonal rotations
    (see :func:`_blocks_interact`; requires ``window``)."""
    if shift and window is None:
        raise ValueError("shift requires window (ring rotation use only)")
    if native_bhsd:
        batch, heads, seq, head_dim = q.shape
        qt, kt, vt = q, k, v
    else:
        batch, seq, heads, head_dim = q.shape
        qt, kt, vt = _swap_sh(q), _swap_sh(k), _swap_sh(v)
    bq, bk = min(block_q, seq), min(block_k, seq)
    nk = seq // bk
    if window is not None:
        # Trimmed kv axis: each q block streams only the blocks its window
        # can reach, anchored at its causal frontier — O(S·W) grid steps and
        # K/V DMAs, not just gated-off compute (see _window_span). The
        # anchor clamps to the last real kv block: a nonzero shift pushes
        # the causal frontier past the buffer.
        njj = _window_span(window, bk, bq, nk)

        def kv_index(b, h, i, jj):
            anchor = jnp.minimum(((i + 1) * bq - 1 + shift) // bk, nk - 1)
            j = anchor - (njj - 1) + jj
            return (b, h, jnp.maximum(j, 0), 0)
    else:
        njj = nk
        kv_index = lambda b, h, i, j: (b, h, j, 0)  # noqa: E731
    grid = (batch, heads, seq // bq, njj)
    o_shape = jax.ShapeDtypeStruct(
        (batch, heads, seq, head_dim), out_dtype or q.dtype
    )
    o_spec = pl.BlockSpec(
        (1, 1, bq, head_dim), lambda b, h, i, j: (b, h, i, 0),
        memory_space=pltpu.VMEM,
    )
    lse_shape = jax.ShapeDtypeStruct((batch, heads, seq, 128), jnp.float32)
    lse_spec = pl.BlockSpec(
        (1, 1, bq, 128), lambda b, h, i, j: (b, h, i, 0),
        memory_space=pltpu.VMEM,
    )
    result = pl.pallas_call(
        functools.partial(
            _fwd_kernel,
            causal=causal, scale=head_dim**-0.5, block_q=bq, block_k=bk,
            with_lse=with_lse, window=window, shift=shift, n_kv_blocks=nk,
        ),
        out_shape=(o_shape, lse_shape) if with_lse else o_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, bq, head_dim), lambda b, h, i, j: (b, h, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, 1, bk, head_dim), kv_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, head_dim), kv_index, memory_space=pltpu.VMEM),
        ],
        out_specs=(o_spec, lse_spec) if with_lse else o_spec,
        scratch_shapes=[
            pltpu.VMEM((bq, head_dim), jnp.float32),  # acc
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (lane-replicated)
            pltpu.VMEM((bq, 128), jnp.float32),  # running denom
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    out, lse = result if with_lse else (result, None)
    return (out if native_bhsd else _swap_sh(out)), lse


def _tile_p_ds(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
    i, j, *, causal: bool, scale: float, block_q: int, block_k: int,
    window: int | None = None, shift: int = 0,
):
    """Shared backward tile math: returns ``(p, ds, do_f32)`` for the
    (q block i, kv block j) tile.

    Both backward kernels need identical p/ds definitions — a one-sided edit
    here would silently give dq a different gradient than dk/dv, so the core
    lives in one place. delta = rowsum(o·do) is recomputed per tile in VMEM
    (bq×d VPU work); materializing it lane-replicated in HBM cost more than
    both backward kernels combined at long S.

    Note on masked rows: ``p = exp(s - lse)`` relies on every q row having a
    finite lse. In square causal/full self-attention every row attends to at
    least its diagonal key, so this always holds; a hypothetical fully-masked
    row (lse = NEG_INF) would yield exp(0) = 1 per entry, NOT zero — padding
    or segment masks must guard p explicitly before relying on this path.
    """
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, :1]  # lane-replicated [bq, 128] -> [bq, 1]
    delta = jnp.sum(o_ref[0, 0].astype(jnp.float32) * do, axis=1, keepdims=True)
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        mask = _pair_mask(
            s.shape, i, j, window=window, block_q=block_q, block_k=block_k,
            shift=shift,
        )
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse)  # [bq, bk]
    dp = lax.dot_general(
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta) * scale  # [bq, bk]
    return p, ds, do


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, dq_acc,
    *, causal: bool, scale: float, block_q: int, block_k: int,
    window: int | None = None, shift: int = 0, n_kv_blocks: int = 0,
):
    """dq for one q block, streaming kv blocks (sequential last grid axis)."""
    i = pl.program_id(2)
    jj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(jj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    # Trimmed kv axis under a window — same anchoring (and shift clamp) as
    # _fwd_kernel.
    if window is not None:
        anchor = jnp.minimum(
            ((i + 1) * block_q - 1 + shift) // block_k, n_kv_blocks - 1
        )
        j = anchor - (nk - 1) + jj
    else:
        j = jj
    run = _blocks_interact(
        i, j, causal=causal, window=window, block_q=block_q, block_k=block_k,
        shift=shift,
    )
    if window is not None:
        run = run & (j >= 0)

    @pl.when(run)
    def _update():
        _, ds, _ = _tile_p_ds(
            q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, i, j,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            window=window, shift=shift,
        )
        k = k_ref[0, 0]
        dq_acc[...] += lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, causal: bool, scale: float, block_q: int, block_k: int,
    window: int | None = None, n_q_blocks: int = 0, shift: int = 0,
):
    """dk/dv for one kv block, streaming q blocks (sequential last grid axis)."""
    j = pl.program_id(2)  # kv block
    ii = pl.program_id(3)  # q grid step (sequential)
    nq = pl.num_programs(3)

    @pl.when(ii == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # Trimmed q axis under a window, anchored at the LAST q block whose
    # window still reaches this kv block — CLAMPED to the last real q block
    # first: for windows within block_q of the sequence length the raw
    # anchor overshoots n_q - 1, and without the clamp the top of the span
    # gets gated off while the bottom never shifts down to compensate,
    # silently dropping the earliest in-window q blocks from dk/dv.
    # A nonzero shift moves every q block `shift` positions later, so the
    # last in-window q block comes `shift` positions earlier.
    if window is not None:
        i_anchor = jnp.minimum(
            ((j + 1) * block_k + window - 2 - shift) // block_q,
            n_q_blocks - 1,
        )
        i = i_anchor - (nq - 1) + ii
    else:
        i = ii
    # Same predicate as the forward, from the kv block's perspective: q
    # blocks strictly before this kv block (causal) or with every query
    # past this block's window (sliding window) contribute nothing.
    run = _blocks_interact(
        i, j, causal=causal, window=window, block_q=block_q, block_k=block_k,
        shift=shift,
    )
    if window is not None:
        run = run & (i >= 0)

    @pl.when(run)
    def _update():
        p, ds, do = _tile_p_ds(
            q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, i, j,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            window=window, shift=shift,
        )
        q = q_ref[0, 0]
        # p in the input dtype: bf16 inputs get the bf16 MXU rate (an f32 p
        # would halve throughput and double the tile's VMEM footprint).
        dv_acc[...] += lax.dot_general(
            p.astype(v_ref.dtype), do.astype(v_ref.dtype),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[...] += lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ii == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_pallas(
    q: jax.Array, k: jax.Array, v: jax.Array, o: jax.Array, do: jax.Array,
    lse: jax.Array, causal: bool, block_q: int, block_k: int, interpret: bool,
    grad_dtype: jax.typing.DTypeLike | None = None,
    native_bhsd: bool = False,
    window: int | None = None,
    shift: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused flash backward: two kernels (dq; dk+dv), O(S) memory, no HBM
    probability matrices — replaces the blockwise-JAX backward whose
    per-scan-step ``[B,H,S,bk]`` p tensors dominate HBM traffic at long S.
    ``lse`` comes from the forward kernel (one recompute of QKᵀ per kernel
    instead of the two extra passes the JAX path pays). ``grad_dtype``
    overrides the output dtype (default: match the inputs) — the ring
    schedule requests f32 so its cross-rotation accumulation never rounds a
    partial to bf16 first. ``native_bhsd``: all tensors (and the returned
    grads) are ``[B, H, S, D]`` — no boundary transposes. ``shift``: static
    global q-position offset for the ring's off-diagonal rotations
    (requires ``window``; see :func:`_blocks_interact`)."""
    if shift and window is None:
        raise ValueError("shift requires window (ring rotation use only)")
    if native_bhsd:
        batch, heads, seq, head_dim = q.shape
        qt, kt, vt, ot, dot_ = q, k, v, o, do
    else:
        batch, seq, heads, head_dim = q.shape
        qt, kt, vt = _swap_sh(q), _swap_sh(k), _swap_sh(v)
        ot, dot_ = _swap_sh(o), _swap_sh(do)
    dq_dtype = grad_dtype or q.dtype
    dk_dtype = grad_dtype or k.dtype
    dv_dtype = grad_dtype or v.dtype
    bq, bk = min(block_q, seq), min(block_k, seq)
    bq, bk = fit_bwd_blocks(bq, bk, q.dtype)
    scale = head_dim**-0.5

    # One index map per (side, grid): the dq grid is (b, h, q, kv), the dkv
    # grid is (b, h, kv, q). q-side rows (q, o, do, lse) share a map. Under
    # a window both streaming axes are TRIMMED to the window span and the
    # streamed side's map is anchored per resident block (see _window_span)
    # — the clamped out-of-range reads are gated off inside the kernels.
    n_q, n_k = seq // bq, seq // bk
    if window is not None:
        njj = _window_span(window, bk, bq, n_k)
        nii = _window_span(window, bq, bk, n_q)

        def kv_at_jj(b, h, i, jj):
            anchor = jnp.minimum(((i + 1) * bq - 1 + shift) // bk, n_k - 1)
            j = anchor - (njj - 1) + jj
            return (b, h, jnp.maximum(j, 0), 0)

        def q_at_ii(b, h, j, ii):
            # Anchor clamped BEFORE subtracting the span — must match the
            # kernel's i_anchor exactly (see _bwd_dkv_kernel's clamp note).
            i_anchor = jnp.minimum(
                ((j + 1) * bk + window - 2 - shift) // bq, n_q - 1
            )
            return (b, h, jnp.maximum(i_anchor - (nii - 1) + ii, 0), 0)
    else:
        njj, nii = n_k, n_q
        kv_at_jj = lambda b, h, i, j: (b, h, j, 0)  # noqa: E731
        q_at_ii = lambda b, h, j, i: (b, h, i, 0)  # noqa: E731
    row_specs = {
        "q@i": lambda b, h, i, j: (b, h, i, 0),
        "kv@j": kv_at_jj,
        "q@j": q_at_ii,
        "kv@i": lambda b, h, j, i: (b, h, j, 0),
    }

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, scale=scale, block_q=bq, block_k=bk,
            window=window, shift=shift, n_kv_blocks=n_k,
        ),
        out_shape=jax.ShapeDtypeStruct((batch, heads, seq, head_dim), dq_dtype),
        grid=(batch, heads, seq // bq, njj),
        in_specs=[
            pl.BlockSpec((1, 1, bq, head_dim), row_specs["q@i"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, head_dim), row_specs["kv@j"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, head_dim), row_specs["kv@j"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, head_dim), row_specs["q@i"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, head_dim), row_specs["q@i"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, 128), row_specs["q@i"], memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bq, head_dim), row_specs["q@i"], memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.VMEM((bq, head_dim), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt, ot, dot_, lse)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, scale=scale, block_q=bq, block_k=bk,
            window=window, n_q_blocks=n_q, shift=shift,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((batch, heads, seq, head_dim), dk_dtype),
            jax.ShapeDtypeStruct((batch, heads, seq, head_dim), dv_dtype),
        ),
        grid=(batch, heads, seq // bk, nii),
        in_specs=[
            pl.BlockSpec((1, 1, bq, head_dim), row_specs["q@j"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, head_dim), row_specs["kv@i"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, head_dim), row_specs["kv@i"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, head_dim), row_specs["q@j"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, head_dim), row_specs["q@j"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, 128), row_specs["q@j"], memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, bk, head_dim), row_specs["kv@i"], memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, head_dim), row_specs["kv@i"], memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, head_dim), jnp.float32),
            pltpu.VMEM((bk, head_dim), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt, ot, dot_, lse)

    if native_bhsd:
        return dq, dk, dv
    return _swap_sh(dq), _swap_sh(dk), _swap_sh(dv)


#: Module defaults, from the v5e sweep documented on
#: :func:`flash_attention` — what an untuned call resolves to. A tuning DB
#: (``compiler/autotune.py``) overrides per (shape, dtype, backend);
#: explicit kwargs override everything.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def resolve_blocks(
    block_q: int | None, block_k: int | None,
    shape: tuple[int, ...], dtype,
) -> tuple[int, int]:
    """Block-size resolution: explicit kwarg > tuning-DB entry for this
    ``[B, S, H, D]`` shape > module default. The DB consult can never
    raise or change numerics — only which (verified-equivalent) tiling
    runs."""
    if block_q is not None and block_k is not None:
        return block_q, block_k
    tuned = None
    try:
        from deeplearning_mpi_tpu.compiler.autotune import (
            tuned_attention_blocks,
        )

        tuned = tuned_attention_blocks(tuple(shape), dtype)
    except Exception:
        tuned = None
    tq, tk = tuned if tuned else (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    return (block_q if block_q is not None else tq,
            block_k if block_k is not None else tk)


def fit_block(block: int, seq: int) -> int:
    """Shrink ``block`` (by halving, preserving MXU-friendly sizes) until it
    divides ``seq``: seq=1536 with the 1024 default tiles at 512 instead of
    silently regressing to a dense O(S²) fallback. The result may be a
    non-divisor of ``seq`` or non-sublane-aligned (``% 8``) — callers must
    check both (see :func:`usable_blocks`) and fall back then."""
    b = min(block, seq)
    while b > 8 and seq % b:
        b //= 2
    return b


#: Scoped-VMEM budget for one backward tile's [bq, bk] intermediates. The
#: hardware limit is 16 MiB (v5e "scoped vmem"); Mosaic's stack for
#: _tile_p_ds measures ~17.75 MB at 1024x1024 f32 (s/p/dp/ds + the
#: input-dtype casts of p and ds — the compile error that motivated this
#: cap, hit by the 64k-seq f32 train_lm run) and ~14.7 MB at 1024x1024
#: bf16, which compiles. 10 + 2*itemsize bytes/element reproduces both
#: measurements (18 vs 14 B/elem); 15 MiB leaves margin for the row blocks.
_BWD_TILE_BYTES_BUDGET = 15 * 1024 * 1024


def fit_bwd_blocks(bq: int, bk: int, dtype) -> tuple[int, int]:
    """Shrink backward tile sizes until the per-tile scoped-VMEM estimate
    fits. The forward kernel keeps its own (larger-is-faster) blocks — only
    the backward materializes four-plus ``[bq, bk]`` intermediates at once.
    Halves the larger side first (a power-of-two divisor of ``seq`` stays a
    divisor when halved, so tileability is preserved)."""
    per_elem = 10 + 2 * jnp.dtype(dtype).itemsize
    while bq * bk * per_elem > _BWD_TILE_BYTES_BUDGET and max(bq, bk) > 8:
        if bq >= bk:
            bq //= 2
        else:
            bk //= 2
    return bq, bk


def _check_window(window: int | None, causal: bool, seq: int) -> int | None:
    """Validate / normalize the sliding-window size: windows at or beyond
    the sequence length are plain causal attention (drop them — pointless
    gating arithmetic in the kernel otherwise)."""
    if window is None:
        return None
    if not causal:
        raise ValueError("window attention is causal by definition; pass causal=True")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return None if window >= seq else int(window)


def usable_blocks(bq: int, bk: int, seq: int) -> bool:
    """Whether fitted blocks can legally tile ``seq`` on Mosaic: each must
    divide the sequence AND be a multiple of the 8-row sublane (a short
    sequence like 20 "fits" as one 20-row block but is not tileable)."""
    return seq % bq == 0 and seq % bk == 0 and bq % 8 == 0 and bk % 8 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, block_q, block_k, interpret, native_bhsd=False,
           window=None):
    return _fwd_pallas(
        q, k, v, causal, block_q, block_k, interpret, with_lse=False,
        native_bhsd=native_bhsd, window=window,
    )[0]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, native_bhsd=False,
               window=None):
    o, lse = _fwd_pallas(
        q, k, v, causal, block_q, block_k, interpret, with_lse=True,
        native_bhsd=native_bhsd, window=window,
    )
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, native_bhsd, window, res, do):
    q, k, v, o, lse = res
    return _bwd_pallas(
        q, k, v, o, do, lse, causal, block_q, block_k, interpret,
        native_bhsd=native_bhsd, window=window,
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    window: int | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Tiled flash attention over ``[B, S, H, D]`` (drop-in for
    ``dense_attention`` and valid as ``TransformerLM(attention_fn=...)``).

    ``block_q``/``block_k=None`` (the default) resolve through
    :func:`resolve_blocks`: an autotuned entry for this exact (shape,
    dtype, backend) when a tuning DB is installed
    (``compiler.autotune.set_default_db`` / ``$DMT_TUNING_DB``), else the
    1024×1024 module defaults — unchanged behavior for untuned callers.
    Explicit ints pin the blocks regardless of any DB.

    ``window``: sliding-window (local) attention — each query sees only its
    last ``window`` keys, self included. Whole kv blocks outside every
    query's window are *skipped* (same ``pl.when`` gate as causal skipping),
    so attention cost is O(S·W) instead of O(S²/2): at 64k tokens with a 4k
    window that is ~8× fewer score tiles. Requires ``causal``.

    ``interpret=None`` auto-selects: compiled Mosaic on TPU, the Pallas
    interpreter elsewhere (so CPU tests and the virtual-device mesh run the
    same code path). Sequences not divisible by the (clamped) block sizes
    fall back to the dense op — correctness everywhere, tiling where it
    counts.

    Default 1024×1024 blocks are from an on-chip sweep (v5e, S=4096 B4 H8
    D64): 12.40 ms (128², the flash-paper-style default) → 6.01 (256²) →
    2.79 (512²) → 1.46 ms (1024²) device time per fwd — 8.5× from block
    shape alone; small tiles leave the MXU idle between the many
    sequential-kv grid steps. VMEM cost at 1024² is ~1.8 MiB
    (q/k/v tiles + f32 accumulator + lane-replicated m/l), comfortably
    inside any TPU's VMEM, and clamping handles seq < 1024.
    """
    window = _check_window(window, causal, q.shape[1])
    seq = q.shape[1]
    block_q, block_k = resolve_blocks(block_q, block_k, q.shape, q.dtype)
    bq, bk = fit_block(block_q, seq), fit_block(block_k, seq)
    if not usable_blocks(bq, bk, seq):
        return dense_attention(q, k, v, causal=causal, window=window)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    with annotate("pallas/flash_attention"):
        return _flash(q, k, v, causal, bq, bk, interpret, False, window)


def flash_attention_bhsd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    window: int | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """:func:`flash_attention` over ``[B, H, S, D]`` — the kernels' native
    layout, with NO transposes at either boundary (forward or backward).
    ``window`` = sliding-window attention (see :func:`flash_attention`).
    ``block_q``/``block_k=None`` resolve through :func:`resolve_blocks`
    (tuning-DB overlay, module defaults otherwise) against the canonical
    BSHD shape — one DB entry serves both layout entry points.

    The BSHD entry pays six ``[B,S,H,D]``-sized XLA transposes per
    layer-step (q/k/v in, o out, then the mirror set in the backward) just
    to move between the model's layout and the kernel grid's — measured at
    ~5% of the 110M-LM step (``docs/PERF_ANALYSIS.md`` §8). A model that
    *projects* straight into BHSD (``models.transformer.Attention`` via
    ``jnp.einsum('bsm,mhd->bhsd', ...)`` — the transpose fuses into the
    projection matmul's output layout) and consumes BHSD context the same
    way never materializes a layout copy at all. The ``.layout`` attribute
    below is the signal :class:`~deeplearning_mpi_tpu.models.transformer.
    Attention` keys on to switch its projection layout.

    Sequences the blocks can't tile fall back to the dense op (transposing
    around it — correctness everywhere, the fallback is short-sequence).
    """
    seq = q.shape[2]
    window = _check_window(window, causal, seq)
    batch, heads, _, head_dim = q.shape
    block_q, block_k = resolve_blocks(
        block_q, block_k, (batch, seq, heads, head_dim), q.dtype
    )
    bq, bk = fit_block(block_q, seq), fit_block(block_k, seq)
    if not usable_blocks(bq, bk, seq):
        bshd = dense_attention(
            _swap_sh(q), _swap_sh(k), _swap_sh(v), causal=causal, window=window
        )
        return _swap_sh(bshd)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    with annotate("pallas/flash_attention_bhsd"):
        return _flash(q, k, v, causal, bq, bk, interpret, True, window)


#: models.transformer.Attention reads this to project q/k/v directly into
#: the kernel's layout (no BSHD round-trip).
flash_attention_bhsd.layout = "bhsd"


# Block-level entry points for the ring schedule (parallel/ring_flash.py):
# the ring owns the cross-shard online-softmax recombination and its own
# VJP, and drives the kernels once per K/V rotation.
flash_fwd_block = _fwd_pallas
flash_bwd_block = _bwd_pallas
