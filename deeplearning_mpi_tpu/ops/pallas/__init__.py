"""Pallas TPU kernels — the hand-scheduled hot ops.

XLA's fusions cover the reference workloads (conv/BN/pooling — SURVEY.md
§2b maps cuDNN onto plain XLA:TPU kernels), so Pallas is reserved for the ops
where explicit VMEM scheduling beats the compiler: flash attention's online
softmax over S² scores that must never be materialized in HBM.
"""

from deeplearning_mpi_tpu.ops.pallas.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_bhsd,
)
