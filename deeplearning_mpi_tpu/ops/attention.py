"""Scaled dot-product attention (dense reference implementation).

The reference contains no attention at all — both workloads are CNNs
(``pytorch/unet/model.py:51-81``, ``pytorch/resnet/main.py:40``; SURVEY.md
§5.7) — but long-context support is first-class in this framework, so
attention is a core op with three interchangeable implementations:

- :func:`dense_attention` (here) — the O(S²)-memory einsum reference, used
  on short sequences, on CPU, and as the numerical oracle in tests;
- ``ops.pallas.flash_attention`` — the tiled online-softmax Pallas TPU
  kernel (O(S) memory, MXU-shaped blocks);
- ``parallel.ring_attention`` — sequence-parallel blockwise attention over
  the mesh ``seq`` axis, rotating K/V shards with ``ppermute``.

All three share this op's conventions: inputs ``[batch, seq, heads, head_dim]``
("BSHD"), softmax accumulated in float32 regardless of input dtype, output in
the input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-negative mask value; -inf breaks softmax when a row is fully masked


def repeat_kv(x: jax.Array, n_rep: int, *, axis: int = -2) -> jax.Array:
    """Repeat each KV head ``n_rep`` times along the head axis (GQA → MHA).

    Grouped-query attention stores K/V at ``num_kv_heads < num_heads``; the
    full-sequence cores (dense, flash, ring) expect matching head counts, so
    the model repeats K/V immediately before calling them. That is the right
    trade for *training*: full-sequence attention is MXU-bound, and GQA's win
    there is the smaller K/V projections — while *decode* is HBM-bound, so
    :func:`decode_attention` consumes the grouped buffers natively instead
    of repeating (reads ``num_kv_heads``, not ``num_heads``, rows per
    position). ``axis=-2`` is the BSHD head axis; BHSD callers pass 1.
    """
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=axis)


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    window: int | None = None,
    q_offset: int | jax.Array = 0,
    kv_offset: int | jax.Array = 0,
) -> jax.Array:
    """Full-materialization attention over ``[B, S, H, D]`` inputs.

    ``q_offset``/``kv_offset`` are the absolute positions of the first query /
    key row — used by the blockwise/ring implementations, which call this on
    sequence *shards* and need causal masking in global coordinates.

    ``window`` (sliding-window / local attention, Mistral-style): each query
    attends only its last ``window`` keys (self included) — requires
    ``causal`` since the window is defined against the causal past. This is
    the numerical oracle for the windowed flash kernel
    (``ops.pallas.flash_attention(window=...)``).
    """
    if window is not None and not causal:
        raise ValueError("window attention is causal by definition; pass causal=True")
    *_, q_len, _, head_dim = q.shape
    kv_len = k.shape[-3]
    scale = head_dim**-0.5
    # [B, H, Sq, Skv] scores in f32: bf16 logits lose too much softmax precision.
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    weights = None
    if causal:
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 0)
        k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 1)
        valid = q_pos >= k_pos
        if window is not None:
            valid &= q_pos - k_pos < window
        scores = jnp.where(valid, scores, NEG_INF)
        # A query row with NO valid key (possible on blockwise shards that are
        # entirely in the row's future) must contribute zero, not a uniform
        # average of V — softmax alone would renormalize the all-masked row.
        weights = jnp.where(
            jnp.any(valid, axis=-1)[:, None], jax.nn.softmax(scores, axis=-1), 0.0
        )
    if weights is None:
        weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _tuned_decode_schedule(
    shape: tuple[int, ...], dtype,
) -> tuple[bool, int | None]:
    """(use_kernel, block) from the autotuner's DB for this ``[B, L, Hkv,
    D]`` buffer — ``(False, None)`` when untuned/unavailable, so
    ``use_kernel=None`` keeps today's einsum/walk behavior without a DB."""
    try:
        from deeplearning_mpi_tpu.compiler.autotune import (
            tuned_decode_schedule,
        )

        tuned = tuned_decode_schedule(tuple(shape), dtype)
    except Exception:
        return False, None
    if not tuned:
        return False, None
    return tuned["schedule"] == "kernel", tuned.get("block")


#: Buffers at or below this length take the one-shot masked path: measured
#: on a v5e (tools/bench_decode.py, device-looped timing), the single fused
#: einsum runs at the HBM roofline (~72 us/token flat at B8 H12 D64
#: max_len 2048) while the blockwise while-loop walk pays ~40 us per
#: iteration (~45% of roofline at block 512) — it only beats reading the
#: whole buffer once the unfilled tail it skips outweighs that derate,
#: i.e. on long buffers.
DECODE_DENSE_MAX = 4096


def decode_attention(
    q: jax.Array,
    k_buf: jax.Array,
    v_buf: jax.Array,
    index: jax.Array,
    *,
    block: int = 2048,
    dense_max: int = DECODE_DENSE_MAX,
    window: int | None = None,
    use_kernel: bool | None = None,
) -> jax.Array:
    """One KV-cached decode step over the filled prefix of the cache.

    ``q`` is ``[B, 1, H, D]`` (the single new token, RoPE applied);
    ``k_buf``/``v_buf`` are the ``[B, max_len, Hkv, D]`` cache buffers with
    positions ``0..index`` (inclusive) filled. ``Hkv`` may be a divisor of
    ``H`` (grouped-query attention): the grouped buffers are read as-is —
    never repeated to ``H`` — so decode HBM traffic per token scales with
    ``Hkv``, compounding GQA's cache-size saving.

    Two schedules, chosen at TRACE time on the static buffer length:

    - ``max_len <= dense_max``: ONE masked grouped einsum over the whole
      buffer. Reads unfilled rows, but as a single fused op it runs at the
      HBM roofline — measured 1.3-2.3x faster than the blockwise walk for
      fills above ~1/3 of a 2k buffer (tools/bench_decode.py).
    - longer buffers: the flash-decoding walk — ``block``-sized chunks
      under a ``lax.fori_loop`` whose trip count ``ceil((index+1)/block)``
      is *traced* (XLA lowers a while loop), so blocks past the prefix are
      neither read nor scored and per-token HBM traffic is O(index), not
      O(max_len). The flash-style ``(acc, m, l)`` accumulator keeps softmax
      exact across chunks in f32. The 2048 default block amortizes the
      measured ~40 us/iteration loop overhead.

    ``window`` (sliding-window models): the query attends only cache
    positions ``index-window+1 .. index``. The blockwise walk then *starts*
    at the window's first block instead of 0, so per-token HBM traffic is
    O(window) however long the generation has run — decode cost stops
    growing with context, the inference-side half of the sliding-window
    trade.

    ``use_kernel``: the fused Pallas decode kernel
    (``ops.pallas.flash_decode``) for long buffers — one grid instead of
    the walk's ``lax.fori_loop`` (whose ~40 µs/iteration host overhead
    caps the walk at ~45% of the HBM roofline, PERF_ANALYSIS §9), keeping
    O(index) — O(window) for sliding-window models — HBM traffic via its
    two-sided clamped index map. ``True`` selects it when the buffer tiles
    (the interpreter off-TPU); ``False`` keeps the walk; ``None`` consults
    the autotuner's tuning DB for this buffer's (shape, dtype, backend) —
    a recorded ``flash_decode`` winner selects the kernel at its measured
    block, an untuned shape keeps the walk (``compiler/autotune.py``;
    ``make tune-smoke`` exercises the loop end-to-end).

    Not differentiable (dynamic trip count) — decode is inference-only.
    """
    batch, q_len, heads, head_dim = q.shape
    if q_len != 1:
        raise ValueError(f"decode_attention takes one query token, got {q_len}")
    length, kv_heads = k_buf.shape[1], k_buf.shape[2]
    if heads % kv_heads:
        raise ValueError(
            f"query heads ({heads}) must be a multiple of KV heads ({kv_heads})"
        )
    group = heads // kv_heads
    scale = head_dim**-0.5

    if length <= dense_max:
        # Input-dtype dot with an f32 accumulator — the same formulation
        # the roofline measurement used. An astype(f32) on k_buf instead
        # would risk materializing a double-width copy of the whole cache,
        # exactly the HBM bytes this path is chosen to minimize.
        qg = q[:, 0].reshape(batch, kv_heads, group, head_dim)
        s = jnp.einsum(
            "bhgd,bkhd->bhgk", qg, k_buf,
            preferred_element_type=jnp.float32,
        ) * scale  # [B, Hkv, G, L]
        pos = jnp.arange(length, dtype=jnp.int32)
        valid = pos <= index
        if window is not None:
            valid &= pos > index - window
        s = jnp.where(valid[None, None, None, :], s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum(
            "bhgk,bkhd->bhgd", w.astype(v_buf.dtype), v_buf,
            preferred_element_type=jnp.float32,
        )
        return out.reshape(batch, heads, head_dim)[:, None].astype(q.dtype)
    if use_kernel is None:
        use_kernel, tuned_block = _tuned_decode_schedule(
            k_buf.shape, k_buf.dtype
        )
        if tuned_block:
            block = tuned_block
    if use_kernel:
        from deeplearning_mpi_tpu.ops.pallas.flash_decode import (
            decode_block_fits,
            flash_decode,
        )

        fitted = decode_block_fits(min(block, 1024), length)
        if fitted is not None:
            return flash_decode(
                q, k_buf, v_buf, index, block=fitted, window=window
            )
    # Blocks stay full-size whatever the buffer length (a CLI cache is
    # prompt+max_new — arbitrary): the final block's start is clamped back
    # so it never runs off the buffer, and rows it re-reads from the
    # previous block are masked out of the softmax. Shrinking the block to
    # a divisor instead can collapse to near-scalar slices (e.g. 2500 % 512
    # chains down to 4) and lose to the dense path it replaces.
    b = min(block, length)
    n_blocks = (index + b) // b  # ceil((index+1)/b), traced
    # [B, Hkv, G, D]: query heads grouped by the KV head they share.
    q32 = (q[:, 0].astype(jnp.float32) * scale).reshape(
        batch, kv_heads, group, head_dim
    )

    def body(j, carry):
        acc, m, l = carry
        start = jnp.minimum(j * b, length - b)
        k_blk = lax.dynamic_slice(
            k_buf, (0, start, 0, 0), (batch, b, kv_heads, head_dim)
        )
        v_blk = lax.dynamic_slice(
            v_buf, (0, start, 0, 0), (batch, b, kv_heads, head_dim)
        )
        s = jnp.einsum(
            "bhgd,bkhd->bhgk", q32, k_blk.astype(jnp.float32)
        )  # [B, Hkv, G, b]
        pos = start + jnp.arange(b, dtype=jnp.int32)
        # Lower bound deduplicates the clamped tail's overlap with block j-1.
        valid = (pos >= j * b) & (pos <= index)
        if window is not None:
            valid &= pos > index - window
        s = jnp.where(valid[None, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        pv = jnp.einsum(
            "bhgk,bkhd->bhgd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return acc * alpha[..., None] + pv, m_new, l * alpha + jnp.sum(p, axis=-1)

    # Windowed decode never reads blocks wholly before the window: start the
    # walk at the window's first block (traced, like the trip count).
    j_start = (
        jnp.maximum(index - window + 1, 0) // b if window is not None else 0
    )
    acc, _, l = lax.fori_loop(
        j_start, n_blocks, body,
        (
            jnp.zeros((batch, kv_heads, group, head_dim), jnp.float32),
            jnp.full((batch, kv_heads, group), NEG_INF, jnp.float32),
            jnp.zeros((batch, kv_heads, group), jnp.float32),
        ),
    )
    out = acc / jnp.maximum(l, 1e-37)[..., None]
    return out.reshape(batch, heads, head_dim)[:, None].astype(q.dtype)


def batched_decode_attention(
    q: jax.Array,
    k_buf: jax.Array,
    v_buf: jax.Array,
    index: jax.Array,
    *,
    window: int | None = None,
    use_kernel: bool | None = None,
    block: int = 1024,
) -> jax.Array:
    """One decode step where every row sits at its OWN fill level.

    :func:`decode_attention` serves the single-program CLI path: one scalar
    ``index`` because the whole batch decodes in lockstep. A continuous-
    batching engine breaks that assumption by design — each slot holds a
    different sequence, so ``index`` here is ``[B]`` int32 (row ``b`` attends
    cache positions ``0..index[b]``; negative = inactive row, output zeros).
    Shapes otherwise match: ``q`` ``[B, 1, H, D]``, grouped buffers
    ``[B, L, Hkv, D]``, grouped heads consumed natively.

    Two schedules, chosen STATICALLY like decode_attention's:

    - default: ONE masked grouped einsum over the whole buffer — the
      dense-roofline schedule (PERF_ANALYSIS §9) with the scalar prefix
      mask swapped for a per-row one. The serving engine's buffers are the
      gathered pages of ``serving.kv_pool`` (``max_blocks_per_seq * block``
      rows), sized by the engine's admission limit, so the read-everything
      trade is the measured-fastest one at those lengths.
    - ``use_kernel=True``: the fused Pallas kernel
      (:func:`~deeplearning_mpi_tpu.ops.pallas.flash_decode.flash_decode`),
      which takes the ``[B]`` index vector natively — per-row clamped DMAs
      keep HBM traffic O(own index) per row on long buffers. Falls back to
      the einsum when the buffer does not tile.
    - ``use_kernel=None``: consult the autotuner's tuning DB for this
      buffer's (shape, dtype, backend) — a recorded winner picks the
      schedule (and the kernel's block); untuned shapes keep the einsum.
      This is how ``serving/engine.py`` defers its dispatch decision to
      measurements (``EngineConfig(use_kernel=None)``).

    Not differentiable; decode is inference-only.
    """
    batch, q_len, heads, head_dim = q.shape
    if q_len != 1:
        raise ValueError(f"batched_decode_attention takes one query token, got {q_len}")
    length, kv_heads = k_buf.shape[1], k_buf.shape[2]
    if heads % kv_heads:
        raise ValueError(
            f"query heads ({heads}) must be a multiple of KV heads ({kv_heads})"
        )
    index = jnp.asarray(index, jnp.int32)
    if index.shape != (batch,):
        raise ValueError(
            f"index must be [{batch}] (one fill level per row), got {index.shape}"
        )
    if use_kernel is None:
        use_kernel, tuned_block = _tuned_decode_schedule(
            k_buf.shape, k_buf.dtype
        )
        if tuned_block:
            block = tuned_block
    if use_kernel:
        from deeplearning_mpi_tpu.ops.pallas.flash_decode import (
            decode_block_fits,
            flash_decode,
        )

        fitted = decode_block_fits(min(block, 1024), length)
        if fitted is not None:
            out = flash_decode(
                q, k_buf, v_buf, jnp.maximum(index, 0), block=fitted,
                window=window,
            )
            return jnp.where(index[:, None, None, None] >= 0, out, 0.0)
    group = heads // kv_heads
    scale = head_dim**-0.5
    qg = q[:, 0].reshape(batch, kv_heads, group, head_dim)
    s = jnp.einsum(
        "bhgd,bkhd->bhgk", qg, k_buf, preferred_element_type=jnp.float32
    ) * scale  # [B, Hkv, G, L]
    pos = jnp.arange(length, dtype=jnp.int32)
    valid = pos[None, :] <= index[:, None]  # [B, L] — per-row prefix
    if window is not None:
        valid &= pos[None, :] > (index[:, None] - window)
    s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    # An inactive row (index < 0) has NO valid key: zero its output rather
    # than letting softmax renormalize the all-masked row into a uniform
    # average of garbage V rows (same rule as dense_attention's empty-row
    # guard).
    w = jnp.where(
        jnp.any(valid, axis=-1)[:, None, None, None],
        jax.nn.softmax(s, axis=-1),
        0.0,
    )
    out = jnp.einsum(
        "bhgk,bkhd->bhgd", w.astype(v_buf.dtype), v_buf,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(batch, heads, head_dim)[:, None].astype(q.dtype)
