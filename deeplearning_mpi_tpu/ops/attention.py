"""Scaled dot-product attention (dense reference implementation).

The reference contains no attention at all — both workloads are CNNs
(``pytorch/unet/model.py:51-81``, ``pytorch/resnet/main.py:40``; SURVEY.md
§5.7) — but long-context support is first-class in this framework, so
attention is a core op with three interchangeable implementations:

- :func:`dense_attention` (here) — the O(S²)-memory einsum reference, used
  on short sequences, on CPU, and as the numerical oracle in tests;
- ``ops.pallas.flash_attention`` — the tiled online-softmax Pallas TPU
  kernel (O(S) memory, MXU-shaped blocks);
- ``parallel.ring_attention`` — sequence-parallel blockwise attention over
  the mesh ``seq`` axis, rotating K/V shards with ``ppermute``.

All three share this op's conventions: inputs ``[batch, seq, heads, head_dim]``
("BSHD"), softmax accumulated in float32 regardless of input dtype, output in
the input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative mask value; -inf breaks softmax when a row is fully masked


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_offset: int | jax.Array = 0,
    kv_offset: int | jax.Array = 0,
) -> jax.Array:
    """Full-materialization attention over ``[B, S, H, D]`` inputs.

    ``q_offset``/``kv_offset`` are the absolute positions of the first query /
    key row — used by the blockwise/ring implementations, which call this on
    sequence *shards* and need causal masking in global coordinates.
    """
    *_, q_len, _, head_dim = q.shape
    kv_len = k.shape[-3]
    scale = head_dim**-0.5
    # [B, H, Sq, Skv] scores in f32: bf16 logits lose too much softmax precision.
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    weights = None
    if causal:
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 0)
        k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 1)
        valid = q_pos >= k_pos
        scores = jnp.where(valid, scores, NEG_INF)
        # A query row with NO valid key (possible on blockwise shards that are
        # entirely in the row's future) must contribute zero, not a uniform
        # average of V — softmax alone would renormalize the all-masked row.
        weights = jnp.where(
            jnp.any(valid, axis=-1)[:, None], jax.nn.softmax(scores, axis=-1), 0.0
        )
    if weights is None:
        weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
