"""Loss functions.

Parity targets: ``nn.CrossEntropyLoss()`` for the ResNet trainer
(``pytorch/resnet/main.py:113``) and ``nn.BCEWithLogitsLoss()`` for the UNet
trainer (``pytorch/unet/train.py:160-162``). Both are mean-reduced over all
elements, matching the torch defaults. All losses are computed in float32
regardless of input dtype — on TPU the model runs bfloat16 through the MXU but
loss/softmax reductions need f32 accumulation for stability.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def _token_nll(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-element negative log-likelihood, f32 log-softmax over the last axis."""
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]


def masked_mean(values: jax.Array, where: jax.Array | None) -> jax.Array:
    """Mean of ``values``, optionally weighted by a broadcast-compatible
    validity mask (0 = padded element, excluded) — [B] per-example masks and
    [B, T] per-token masks both work."""
    if where is None:
        return jnp.mean(values)
    w = where.astype(jnp.float32)
    return jnp.sum(values * w) / jnp.maximum(jnp.sum(w), 1.0)


def softmax_cross_entropy(
    logits: jax.Array, labels: jax.Array, where: jax.Array | None = None
) -> jax.Array:
    """Mean softmax cross-entropy with integer labels.

    Equivalent of ``nn.CrossEntropyLoss()(outputs, labels)``
    (``pytorch/resnet/main.py:113,129``): softmax over the last axis, mean
    over the batch. ``where`` ([B], 1 = real example) excludes wrap-padded
    eval rows.
    """
    return masked_mean(_token_nll(logits, labels), where)


def bce_per_image(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-image mean binary cross-entropy on logits, shape [B].

    The pre-reduction form of :func:`sigmoid_binary_cross_entropy`; exposed so
    data-parallel schedules that need the batch mean in explicit
    sum-over-shards form (``parallel.zero``'s overlapped step) share these
    exact per-image values with the GSPMD loss path.
    """
    logits = logits.astype(jnp.float32)
    targets = targets.astype(jnp.float32)
    per_elem = (
        jnp.maximum(logits, 0.0)
        - logits * targets
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )
    return jnp.mean(per_elem, axis=tuple(range(1, per_elem.ndim)))


def sigmoid_binary_cross_entropy(
    logits: jax.Array, targets: jax.Array, where: jax.Array | None = None
) -> jax.Array:
    """Mean binary cross-entropy on logits.

    Equivalent of ``nn.BCEWithLogitsLoss()(predictions, masks)``
    (``pytorch/unet/train.py:160-162,183``): elementwise
    ``max(x,0) - x*y + log(1+exp(-|x|))``, mean over all elements — the same
    log-sum-exp-stable form torch uses. ``where`` ([B], 1 = real example)
    excludes wrap-padded eval rows (equal-sized images ⇒ the all-elements
    mean equals the mean of per-image means).
    """
    return masked_mean(bce_per_image(logits, targets), where)


def dice_per_image(
    logits: jax.Array, targets: jax.Array, *, eps: float = 1e-8
) -> jax.Array:
    """Per-image soft Dice loss (1 - soft Dice coefficient), shape [B].

    The pre-reduction form of :func:`dice_loss`, exposed for the same reason
    as :func:`bce_per_image` — Dice is per-image before the batch mean, so
    the data-parallel sum-over-shards form needs exactly these values.
    """
    probs = jax.nn.sigmoid(logits.astype(jnp.float32))
    targets = targets.astype(jnp.float32)
    reduce_axes = tuple(range(1, logits.ndim))
    intersection = jnp.sum(probs * targets, axis=reduce_axes)
    union = jnp.sum(probs, axis=reduce_axes) + jnp.sum(targets, axis=reduce_axes)
    dice = (2.0 * intersection + eps) / (union + eps)
    return 1.0 - dice


def dice_loss(
    logits: jax.Array,
    targets: jax.Array,
    where: jax.Array | None = None,
    *,
    eps: float = 1e-8,
) -> jax.Array:
    """Soft Dice loss (1 - soft Dice coefficient), averaged over the batch.

    The reference only uses Dice as an eval metric
    (``pytorch/unet/train.py:124-140``); offering it as a training loss is a
    standard segmentation extension (``dmt-train-unet --loss dice``). Uses
    the same ``eps`` smoothing as the reference's metric. ``where`` ([B],
    1 = real example) excludes wrap-padded eval rows, like the other losses.
    """
    return masked_mean(dice_per_image(logits, targets, eps=eps), where)


def lm_cross_entropy(
    logits: jax.Array, tokens: jax.Array, mask: jax.Array | None = None
) -> jax.Array:
    """Next-token LM loss: predict ``tokens[:, 1:]`` from ``logits[:, :-1]``.

    No reference analog (the reference has no sequence models — SURVEY.md
    §5.7); this is the training loss for the transformer workload. ``mask``
    (1 = real token) excludes padding from the mean.
    """
    nll = _token_nll(logits[:, :-1], tokens[:, 1:])
    return masked_mean(nll, None if mask is None else mask[:, 1:])


def chunked_lm_loss(
    x: jax.Array,
    head_kernel: jax.Array,
    tokens: jax.Array,
    *,
    chunk_size: int,
    mask: jax.Array | None = None,
    compute_dtype: Any = None,
) -> jax.Array:
    """Next-token loss from pre-head activations, never materializing the
    full logits.

    ``lm_cross_entropy(x @ head_kernel, tokens)`` needs the ``[B, S, V]``
    f32 logits resident in BOTH passes — at 32k tokens over a 32k vocab
    that is ~4.2 GB forward plus the same again for ``dlogits``, the two
    biggest tensors in the long-context step. Here the head matmul and the
    cross-entropy run chunk-by-chunk over the sequence inside a
    ``lax.scan``, with each chunk under ``jax.checkpoint`` so the backward
    recomputes its ``[B, chunk, V]`` logits tile instead of saving it:
    peak logits memory drops from O(S·V) to O(chunk·V) in both passes for
    one extra head matmul per chunk in the backward.

    Args: ``x`` — final-norm output ``[B, S, d]`` (any dtype);
    ``head_kernel`` — ``[d, V]`` (tied embeddings: ``embedding.T``);
    ``tokens`` — ``[B, S]`` int; ``mask`` (1 = real token) as in
    :func:`lm_cross_entropy`; ``compute_dtype`` — matmul dtype (default:
    ``x.dtype``, matching the model's head). Numerics: logits are cast to
    f32 before the log-softmax, exactly like the dense path.
    """
    compute_dtype = compute_dtype or x.dtype
    batch, seq, _ = x.shape
    # Next-token alignment first, then chunk the S-1 prediction positions.
    x_in = x[:, :-1].astype(compute_dtype)
    labels = tokens[:, 1:]
    weights = (
        jnp.ones(labels.shape, jnp.float32)
        if mask is None
        else mask[:, 1:].astype(jnp.float32)
    )
    n_pos = seq - 1
    chunk_size = max(1, min(chunk_size, n_pos))
    pad = (-n_pos) % chunk_size
    if pad:
        x_in = jnp.pad(x_in, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        weights = jnp.pad(weights, ((0, 0), (0, pad)))  # zero weight = excluded
    n_chunks = (n_pos + pad) // chunk_size
    split = lambda a: a.reshape(  # noqa: E731 — [B, S-1(+pad), ...] -> chunk-major
        batch, n_chunks, chunk_size, *a.shape[2:]
    ).swapaxes(0, 1)
    kernel = head_kernel.astype(compute_dtype)

    @jax.checkpoint
    def chunk_nll_sum(x_c, labels_c, w_c):
        logits = jnp.einsum(
            "btd,dv->btv", x_c, kernel
        )  # [B, chunk, V] — the only logits tile alive
        nll = _token_nll(logits, labels_c)
        return jnp.sum(nll * w_c)

    def body(acc, chunk):
        x_c, labels_c, w_c = chunk
        return acc + chunk_nll_sum(x_c, labels_c, w_c), None

    total, _ = jax.lax.scan(
        body, jnp.zeros((), jnp.float32), (split(x_in), split(labels), split(weights))
    )
    return total / jnp.maximum(jnp.sum(weights), 1.0)
