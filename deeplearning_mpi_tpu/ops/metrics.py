"""Evaluation metrics.

Parity targets: top-1 accuracy (``pytorch/resnet/main.py:57-73``) and the
per-image Dice coefficient with its empty-mask convention
(``pytorch/unet/train.py:104-140``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning_mpi_tpu.ops.loss import masked_mean


def top1_accuracy(
    logits: jax.Array, labels: jax.Array, where: jax.Array | None = None
) -> jax.Array:
    """Fraction of argmax predictions matching integer labels.

    Equivalent of the reference's ``torch.max(outputs,1)`` / correct-count
    accumulation (``pytorch/resnet/main.py:64-71``). Returns a scalar in
    [0, 1]; callers accumulating across batches weight by the number of
    *valid* examples (= batch size only when ``where`` is None).
    """
    preds = jnp.argmax(logits, axis=-1)
    return masked_mean(jnp.asarray(preds == labels, jnp.float32), where)


def dice_score(
    pred_mask: jax.Array,
    true_mask: jax.Array,
    where: jax.Array | None = None,
    *,
    eps: float = 1e-8,
) -> jax.Array:
    """Mean per-image Dice coefficient for binary masks.

    Parity with ``pytorch/unet/train.py:124-140`` including its two
    conventions: ``dice = (2·|∩| + eps) / (|pred| + |true| + eps)`` with
    ``eps = 1e-8``, and **both-empty ⇒ 1.0** (a correctly predicted empty
    mask counts as perfect, ``train.py:132-137``). Inputs are {0,1} masks of
    shape [batch, ...spatial]; thresholding (sigmoid > 0.5,
    ``train.py:119-122``) is the caller's job.
    """
    pred = pred_mask.astype(jnp.float32)
    true = true_mask.astype(jnp.float32)
    reduce_axes = tuple(range(1, pred.ndim))
    intersection = jnp.sum(pred * true, axis=reduce_axes)
    denom = jnp.sum(pred, axis=reduce_axes) + jnp.sum(true, axis=reduce_axes)
    dice = (2.0 * intersection + eps) / (denom + eps)
    both_empty = denom == 0
    dice = jnp.where(both_empty, 1.0, dice)
    return masked_mean(dice, where)
