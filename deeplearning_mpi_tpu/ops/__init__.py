"""Numerical ops: losses, metrics, normalization helpers, Pallas kernels."""

from deeplearning_mpi_tpu.ops.attention import dense_attention  # noqa: F401
from deeplearning_mpi_tpu.ops.loss import (  # noqa: F401
    dice_loss,
    chunked_lm_loss,
    lm_cross_entropy,
    masked_mean,
    sigmoid_binary_cross_entropy,
    softmax_cross_entropy,
)
from deeplearning_mpi_tpu.ops.metrics import dice_score, top1_accuracy  # noqa: F401
from deeplearning_mpi_tpu.ops.pallas import flash_attention  # noqa: F401
