"""Weight-only int8 quantization for LM inference.

No reference analog (the reference has no inference entrypoint at all —
its workflow ends at checkpoint files, ``pytorch/resnet/main.py:136-142``);
this is a TPU-first decode lever: batch-1 KV-cached decode is HBM-bound on
*parameter* reads (~220 MB/token for the 110M flagship, see
``docs/LONG_CONTEXT.md``), so storing the seven big matmul kernels per block
as int8 + one f32 scale per output channel halves the bytes the matmuls
stream versus bf16 — a bandwidth lever, like GQA, not a compute one.

Design:
- **Post-training, weight-only.** Checkpoints stay full-precision; a trained
  param tree is converted on restore (``quantize_lm_params``). Activations,
  norms, embeddings, and the tied LM head stay in the compute dtype — the
  quality-sensitive pieces — so the conversion is a pure serving-time choice.
- **Per-output-channel scales.** ``scale[o] = max|W[:, o]| / 127`` bounds
  elementwise error by ``scale/2``; a single per-tensor scale would let one
  outlier channel dominate the whole kernel's resolution.
- **Dequant after the matmul.** int8 values are exactly representable in
  bfloat16 (8 mantissa bits cover ±127), so
  ``(x @ q.astype(bf16)) * scale == x @ (q * scale)`` with the scale applied
  to the small ``[..., out]`` result instead of materializing a dequantized
  ``[in, out]`` kernel per call — XLA streams the int8 kernel and fuses the
  convert into the dot's operand read.
"""

from __future__ import annotations

from typing import Any

import flax.core
import flax.linen as nn
import jax
import jax.numpy as jnp

#: the seven big matmuls per transformer block — where the parameter bytes
#: are. Norm scales, embeddings, and router kernels stay full-precision.
DEFAULT_TARGETS = (
    "q_proj", "k_proj", "v_proj", "out_proj",
    "gate_proj", "up_proj", "down_proj",
)


class QuantDense(nn.Module):
    """Bias-free Dense over an int8 kernel with per-output-channel scales.

    Param tree: ``kernel`` (int8, ``[in, features]``) + ``scale`` (f32,
    ``[features]``) — exactly what :func:`quantize_lm_params` emits for an
    ``nn.Dense(features, use_bias=False)`` it replaces. Never trained: the
    init exists only to shape templates (zeros), real values always come
    from conversion.
    """

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel", lambda key, shape: jnp.zeros(shape, jnp.int8),
            (x.shape[-1], self.features),
        )
        scale = self.param(
            "scale", nn.initializers.ones, (self.features,), jnp.float32
        )
        y = jnp.einsum(
            "...i,io->...o", x.astype(self.dtype), kernel.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
        return (y * scale).astype(self.dtype)


def quantize_array(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Quantize ``[in, out]`` to (int8 ``[in, out]``, f32 ``[out]`` scales).

    Symmetric round-to-nearest; ``|w - q*scale| <= scale/2`` elementwise.
    """
    w32 = jnp.asarray(w, jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(w32), axis=0) / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Quantize cached K/V rows to (int8, f32 scale over the last axis).

    One symmetric absmax scale per token row per head — ``scale[...] =
    max|x[..., :]| / 127`` over ``head_dim`` — so a loud token (attention
    sink, BOS) cannot flatten the resolution of its neighbours the way a
    per-block or per-tensor scale would. Same ``|x - q*scale| <= scale/2``
    elementwise bound as :func:`quantize_array`. Input ``[..., head_dim]``
    yields ``q`` of the same shape and ``scale`` of shape ``x.shape[:-1]``.
    """
    x32 = jnp.asarray(x, jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(x32), axis=-1) / 127.0, 1e-12
    ).astype(jnp.float32)
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(
    q: jax.Array, scale: jax.Array, dtype: Any = jnp.float32
) -> jax.Array:
    """Inverse of :func:`quantize_kv`: ``q * scale`` broadcast over the
    trailing ``head_dim`` axis, in the requested compute dtype."""
    return q.astype(dtype) * scale[..., None].astype(dtype)


def quantize_lm_params(
    params: Any, *, targets: tuple[str, ...] = DEFAULT_TARGETS
) -> Any:
    """Convert a trained :class:`TransformerLM` param tree for the
    ``quantized=True`` model: every 2-D ``kernel`` under a module named in
    ``targets`` becomes ``{kernel: int8, scale: f32[out]}``; everything else
    passes through unchanged (embeddings, norms, routers).
    """
    def visit(tree: dict) -> dict:
        out = {}
        for name, sub in tree.items():
            if (
                name in targets
                and isinstance(sub, dict)
                and set(sub) == {"kernel"}
                and getattr(sub["kernel"], "ndim", 0) == 2
            ):
                q, scale = quantize_array(sub["kernel"])
                out[name] = {"kernel": q, "scale": scale}
            elif isinstance(sub, dict):
                out[name] = visit(sub)
            else:
                out[name] = sub
        return out

    return visit(flax.core.unfreeze(params))
