"""Convert a reference PyTorch ``.pth`` checkpoint into a framework
checkpoint that ``--resume`` / ``--eval_only`` restore.

The reference leaves migrating users with raw DDP state_dicts —
``torch.save(ddp_model.state_dict(), path)`` (``pytorch/resnet/main.py:139``,
``pytorch/unet/train.py:216``). This entry point reads one, converts the
layout (``utils/torch_import``), wraps it in a full train state (fresh
optimizer, step 0 — the reference never saved optimizer state to begin
with), and writes an Orbax checkpoint under ``--model_dir/--model_filename``:

    dmt-import-torch --arch unet --input unet_distributed.pth
    dmt-train-unet --resume --reference_topology ...   # continues from it

    dmt-import-torch --arch resnet18 --input resnet_distributed.pth
    dmt-train-resnet --resume --torch_padding ...      # ditto

UNet checkpoints restore into ``UNet(reference_topology=True)`` — the
reference's decoder keeps channels through the upsample (``pytorch/unet/
model.py:37-38``), a different param-shape contract than our default — so
the train/eval run must pass ``--reference_topology`` too.

The fresh optimizer state is written with the trainers' DEFAULT optimizer
shape (constant LR, bare-float hyperparams). Resuming with ``--lr_schedule
cosine`` changes the optax state tree and will fail to restore — true of
any checkpoint whose run flags disagree, not just imported ones.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", required=True, help="path to the .pth file")
    parser.add_argument("--arch", required=True,
                        choices=["unet", "resnet18", "resnet34", "resnet50",
                                 "resnet101", "resnet152"])
    parser.add_argument("--model_dir", default="saved_models")
    parser.add_argument("--model_filename", default=None,
                        help="checkpoint name (default: the matching "
                        "trainer's default, so --resume finds it)")
    parser.add_argument("--epoch", type=int, default=0,
                        help="epoch label for the checkpoint (the .pth "
                        "carries none; resume continues after this)")
    parser.add_argument("--num_classes", type=int, default=10,
                        help="resnet head width (reference: 10, main.py:41)")
    parser.add_argument("--out_classes", type=int, default=1,
                        help="unet head channels (reference default 2, "
                        "run.sh trains 1)")
    parser.add_argument("--bilinear", action="store_true",
                        help="the .pth came from up_sample_mode='bilinear'")
    parser.add_argument("--platform", default=None, choices=("cpu", "tpu"))
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp

    from deeplearning_mpi_tpu.train import Checkpointer, create_train_state
    from deeplearning_mpi_tpu.train.trainer import build_optimizer
    from deeplearning_mpi_tpu.utils import torch_import

    state_dict = torch_import.load_pth(args.input)

    # Optimizer hyperparameters come from the matching trainer's OWN parser
    # defaults — the optax state tree written here must equal the restore
    # template the trainer builds, and a hardcoded copy would silently
    # drift if a trainer default ever changes.
    if args.arch == "unet":
        from deeplearning_mpi_tpu.cli import train_unet
        from deeplearning_mpi_tpu.models import UNet

        variables = torch_import.convert_reference_unet(state_dict)
        model = UNet(
            out_classes=args.out_classes, bilinear=args.bilinear,
            reference_topology=True,
        )
        sample = jnp.zeros((1, 64, 64, 3))
        d = train_unet.build_parser().parse_args([])
        tx = build_optimizer(d.optimizer, d.learning_rate, clip_norm=d.clip_norm)
        default_name = d.model_filename
    else:
        from deeplearning_mpi_tpu.cli import train_resnet
        from deeplearning_mpi_tpu.models import get_model

        variables = torch_import.convert_torchvision_resnet(
            state_dict, args.arch
        )
        model = get_model(
            args.arch, num_classes=args.num_classes, stem="imagenet",
            torch_padding=True,
        )
        sample = jnp.zeros((1, 32, 32, 3))
        d = train_resnet.build_parser().parse_args([])
        tx = build_optimizer(
            d.optimizer, d.learning_rate, momentum=d.momentum,
            weight_decay=d.weight_decay,
        )
        default_name = d.model_filename

    template = create_train_state(
        model, jax.random.key(0), sample, tx
    )
    imported_params = jax.tree.map(jnp.asarray, variables["params"])
    imported_stats = jax.tree.map(jnp.asarray, variables["batch_stats"])

    # Shapes, not just structure: a head-width mismatch (e.g. a .pth
    # trained at the reference's default out_classes=2 imported without
    # --out_classes 2) has an identical tree structure and would otherwise
    # surface as an opaque orbax error at restore time.
    def flat_shapes(tree):
        return {
            "/".join(str(getattr(k, "key", k)) for k in path): tuple(
                int(d) for d in getattr(v, "shape", ())
            )
            for path, v in jax.tree_util.tree_leaves_with_path(tree)
        }

    want = flat_shapes(template.params)
    got = flat_shapes(imported_params)
    if want != got:
        diffs = sorted(
            {k for k in want.keys() | got.keys() if want.get(k) != got.get(k)}
        )
        raise SystemExit(
            f"imported param shapes do not match a fresh {args.arch} init —\n"
            f"model flags (--out_classes/--num_classes/--bilinear) probably "
            f"disagree with how the .pth was trained.\n"
            f"mismatched leaves: {diffs[:8]}"
        )

    state = template.replace(
        params=imported_params,
        batch_stats=imported_stats,
        opt_state=tx.init(imported_params),
    )

    name = args.model_filename or default_name
    checkpointer = Checkpointer(f"{args.model_dir}/{name}")
    try:
        checkpointer.save(state, epoch=args.epoch)
        checkpointer.manager.wait_until_finished()
    finally:
        checkpointer.close()
    n_params = sum(x.size for x in jax.tree.leaves(imported_params))
    print(
        f"imported {args.arch} ({n_params:,} params) from {args.input} -> "
        f"{args.model_dir}/{name} @ epoch {args.epoch}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
