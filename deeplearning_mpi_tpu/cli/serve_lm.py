"""Continuous-batching LM serving benchmark — trace replay + latency report.

The offline ``dmt-generate`` answers "what does this checkpoint say"; this
CLI answers "how does it SERVE": it replays a request trace (Poisson
arrivals or a JSONL file) through the ``serving`` engine — paged KV cache,
chunked prefill interleaved with decode, admission control — and reports
the latency numbers serving is judged on: TTFT (arrival → first generated
token), TPOT (decode-phase seconds per token), and aggregate generated
tokens/s, plus the engine's live counters (queue depth, slot occupancy,
shed requests, KV blocks in use) through the telemetry registry
(``--metrics_file`` appends the canonical JSONL records
``tools/metrics_report.py`` reads; see docs/OBSERVABILITY.md).

Trace file format: one JSON object per line —
``{"arrival": seconds-from-start, "prompt": "text", "max_new": N,
"deadline": seconds-after-arrival (optional)}``; only ``prompt`` is
required (``arrival`` defaults to 0 — submit immediately).

``--selftest`` needs no checkpoint: it serves a tiny random-init model
against a synthetic Poisson trace and verifies every completion against
the offline greedy decode path token-for-token — the correctness contract
of continuous batching is that co-batched strangers never change your
output. ``make serve-smoke`` runs exactly this.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import deque


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dmt-serve-lm",
        description="Replay a request trace through the continuous-batching "
        "serving engine; report TTFT/TPOT/tokens/s.",
    )
    from deeplearning_mpi_tpu.utils import config

    model = config.add_lm_model_flags(parser)
    model.title = (
        "model (MUST match the training run — the checkpoint stores arrays, "
        "not architecture)"
    )
    model.add_argument("--dtype", default="float32",
                       choices=("float32", "bfloat16"))
    parser.add_argument("--model_dir", default="saved_models")
    parser.add_argument("--model_filename", default="lm")
    parser.add_argument("--epoch", type=int, default=None)
    parser.add_argument("--ema", type=config.ema_decay, default=0.0,
                        help="nonzero = serve the EMA-averaged weights "
                        "(match the training run's --ema)")
    eng = parser.add_argument_group("engine")
    eng.add_argument("--max_slots", type=int, default=4,
                     help="concurrent decode slots (the jitted step's batch)")
    eng.add_argument("--block_size", type=int, default=16,
                     help="token positions per KV block")
    eng.add_argument("--num_blocks", type=int, default=64,
                     help="KV pool blocks per layer (one is scratch)")
    eng.add_argument("--max_blocks_per_seq", type=int, default=8,
                     help="block-table width; admission ceiling is "
                     "max_blocks_per_seq * block_size positions")
    eng.add_argument("--prefill_chunk", type=int, default=16,
                     help="prompt positions prefilled per slot per step "
                     "(chunked prefill interleaves with decode)")
    eng.add_argument("--max_queue", type=int, default=64,
                     help="bounded request queue; overflow is shed")
    eng.add_argument("--kv_dtype", default=None, choices=("int8",),
                     help="paged KV cache storage dtype (default: the "
                     "compute dtype). int8 stores per-(token,head) scales "
                     "and dequantizes in-gather — ~half the pool bytes per "
                     "position, so more resident sequences at fixed HBM; "
                     "lossy, so --selftest gates on token-level acceptance "
                     "vs the fp reference instead of bit-exact parity")
    eng.add_argument("--kv_acceptance_min", type=float, default=0.9,
                     help="minimum token-level acceptance rate vs offline "
                     "greedy the --selftest requires under a lossy "
                     "--kv_dtype (matched-prefix tokens / expected tokens)")
    eng.add_argument("--disagg", action="store_true",
                     help="disaggregated topology: a prefill-only engine "
                     "hands completed prompts (block tables over a shared "
                     "KV pool — no KV bytes move) to a decode-only engine, "
                     "so decode batches never stall behind long prefills; "
                     "with --replicas > 1 every replica runs disaggregated")
    eng.add_argument("--use_kernel", action="store_true",
                     help="dispatch decode attention to the Pallas "
                     "flash_decode kernel (per-row fill levels)")
    eng.add_argument("--tuning_db", default=None,
                     help="autotuner tuning DB (tools/autotune.py output): "
                     "decode schedule and kernel block sizes come from its "
                     "winners; without --use_kernel the kernel-vs-einsum "
                     "choice itself defers to the DB")
    eng.add_argument("--warmup", action="store_true",
                     help="AOT-compile the decode and prefill programs "
                     "before accepting traffic (compiler/aot.py): first-"
                     "request latency contains zero compiles, and "
                     "compile-cache hit/miss counters land in the registry")
    eng.add_argument("--decode_buckets", default="",
                     help="comma-separated decode batch buckets, e.g. "
                     "'8,16,32': the scheduler briefly holds the decode "
                     "phase while enough supply exists to reach a larger "
                     "bucket, so verify/decode steps run at batched widths")
    eng.add_argument("--max_hold_steps", type=int, default=4,
                     help="max consecutive engine steps the scheduler may "
                     "hold decode while forming a larger batch bucket")
    eng.add_argument("--prefix_cache", action="store_true",
                     help="radix prefix cache: completed prompt prefixes "
                     "are indexed by token span and later requests adopt "
                     "the cached KV blocks (refcounted, copy-on-write) "
                     "instead of re-prefilling the shared span — streams "
                     "stay bit-identical to offline greedy")
    eng.add_argument("--tenants", default="",
                     help="per-tenant admission policy, e.g. "
                     "'prod=4096:1,batch=1024:0' — name=budget_tokens"
                     "[:priority]. budget_tokens bounds the tenant's "
                     "committed tokens (prompt + max_new over queued + "
                     "running; 0 = unlimited), over-budget submits are "
                     "shed with reason tenant_budget; higher priority "
                     "admits first. Trace entries pick their tenant via a "
                     "'tenant' field (default 'default')")
    spec = parser.add_argument_group(
        "speculative decoding (exact-greedy-match acceptance: output "
        "streams stay bit-identical to offline greedy regardless of "
        "draft quality)"
    )
    spec.add_argument("--spec_k", type=int, default=0,
                      help="draft tokens proposed per sequence per engine "
                      "step (0 = off; -1 = consult the tuning DB's "
                      "spec_k winner for this model/draft pair)")
    spec.add_argument("--draft_layers", type=int, default=0,
                      help="self-speculative draft: truncate the target to "
                      "its first N layers (tied embeddings reuse the "
                      "target's logit projection); required when spec_k "
                      "is nonzero")
    spec.add_argument("--draft_d_model", type=int, default=None,
                      help="custom draft width (random-init draft instead "
                      "of layer truncation; parity still holds — the "
                      "draft only proposes, the target decides)")
    spec.add_argument("--draft_d_ff", type=int, default=None)
    spec.add_argument("--draft_heads", type=int, default=None)
    spec.add_argument("--draft_head_dim", type=int, default=None)
    spec.add_argument("--draft_seed", type=int, default=0,
                      help="init seed for a custom-width draft")
    trace = parser.add_argument_group("trace")
    trace.add_argument("--trace", default=None,
                       help="JSONL request trace (see module docstring); "
                       "default: synthetic Poisson trace")
    trace.add_argument("--rate", type=float, default=20.0,
                       help="Poisson arrival rate, requests/s")
    trace.add_argument("--num_requests", type=int, default=16)
    trace.add_argument("--prompt_len_min", type=int, default=4)
    trace.add_argument("--prompt_len_max", type=int, default=24)
    trace.add_argument("--max_new_tokens", type=int, default=16,
                       help="generation budget per request (trace entries "
                       "may override)")
    trace.add_argument("--deadline", type=float, default=0.0,
                       help="seconds after arrival a QUEUED request is shed "
                       "(0 = no deadline; trace entries may override)")
    trace.add_argument("--eos_id", type=int, default=-1,
                       help="byte value that finishes a sequence (-1 = off)")
    trace.add_argument("--random_seed", type=int, default=0)
    fleet = parser.add_argument_group(
        "fleet (replicated serving: supervised replica processes behind "
        "the SLO-aware router — docs/SERVING.md)"
    )
    fleet.add_argument("--replicas", type=int, default=1,
                       help="serve through N supervised replica processes "
                       "(1 = single in-process engine); fleet mode implies "
                       "--selftest semantics (random-init model, parity "
                       "check against offline greedy)")
    fleet.add_argument("--autoscale", action="store_true",
                       help="closed-loop fleet sizing: spawn/retire "
                       "replicas from measured load (queue depth + backlog "
                       "per ready replica), with hysteresis + cooldown, a "
                       "hard --min_replicas floor, and the overload "
                       "brownout ladder (docs/SERVING.md); implies fleet "
                       "mode even with --replicas 1")
    fleet.add_argument("--autoscale_predictive", action="store_true",
                       help="predictive scale-up: forecast the load signal "
                       "(EWMA level + trend over the LoadSignal history) "
                       "and arm the up-window one --forecast_horizon_s "
                       "ahead, so replicas warm BEFORE a ramp lands "
                       "(docs/SIMULATION.md); implies --autoscale")
    fleet.add_argument("--forecast_horizon_s", type=float, default=3.0,
                       help="how far ahead the predictive forecaster "
                       "projects; should cover one spawn-to-ready warmup")
    fleet.add_argument("--forecast_tau_s", type=float, default=1.0,
                       help="EWMA time constant of the forecast load level")
    fleet.add_argument("--forecast_trend_tau_s", type=float, default=1.0,
                       help="EWMA time constant of the forecast load trend")
    fleet.add_argument("--min_replicas", type=int, default=1,
                       help="autoscaler floor: scale-down is vetoed at this "
                       "ready-replica count (a concurrent replica death "
                       "can never race the fleet to zero)")
    fleet.add_argument("--max_replicas", type=int, default=4,
                       help="autoscaler ceiling: scale-up is vetoed here; "
                       "sustained overload at the ceiling climbs the "
                       "brownout ladder instead")
    fleet.add_argument("--hedge_ms", type=float, default=0.0,
                       help="hedged-retry threshold: a request outstanding "
                       "this long (with deadline budget left) is duplicated "
                       "on a second replica; first completion wins, the "
                       "loser is cancelled (0 = hedging off)")
    fleet.add_argument("--swap_at", type=int, default=None,
                       help="after N completions, hot-swap every replica's "
                       "weights (rolling drain, zero downtime, zero "
                       "recompiles) to a fresh init from --random_seed + 1")
    fleet.add_argument("--fleet_dir", default=None,
                       help="scratch directory for replica mailboxes, "
                       "heartbeats, and logs (default: a fresh temp dir)")
    fleet.add_argument("--tp", type=int, default=1,
                       help="tensor-parallel degree per replica: each "
                       "replica's params are sharded across this many "
                       "devices (virtual CPU devices under JAX_PLATFORMS="
                       "cpu) via the Megatron column/row rules; requires "
                       "--replicas > 1")
    parser.add_argument("--metrics_file", default=None,
                        help="append canonical telemetry JSONL records here "
                        "(readable by tools/metrics_report.py)")
    parser.add_argument("--chaos", default=None,
                        help="deterministic fault-injection spec, e.g. "
                        "'serve_crash@step:12' — the engine crashes mid-step "
                        "and recovers (requeue + KV reconcile); with "
                        "--disagg also 'handoff_stall@step:N' (the "
                        "prefill→decode handoff wedges, then recovers); "
                        "with --replicas N > 1: 'replica_kill@step:4,"
                        "replica_hang@step:6' (fleet faults); falls back "
                        "to $DMT_CHAOS (docs/RESILIENCE.md)")
    parser.add_argument("--selftest", action="store_true",
                        help="random-init tiny-ish model, synthetic trace, "
                        "verify every completion against offline greedy "
                        "decode; exit 0 iff all match (no checkpoint needed)")
    run = parser.add_argument_group("runtime")
    run.add_argument("--platform", default=None, choices=("cpu", "tpu"))
    return parser


def _parse_tenants(spec: str):
    """``'prod=4096:1,batch=1024:0'`` -> the scheduler's tenants dict
    (``{name: {"budget_tokens": int, "priority": float}}``), or None for
    an empty spec."""
    spec = spec.strip()
    if not spec:
        return None
    tenants = {}
    for part in spec.split(","):
        part = part.strip()
        try:
            name, policy = part.split("=", 1)
            budget, _, priority = policy.partition(":")
            tenants[name.strip()] = {
                "budget_tokens": int(budget),
                "priority": float(priority) if priority else 0.0,
            }
        except ValueError:
            raise SystemExit(
                f"bad --tenants entry {part!r}: expected "
                "name=budget_tokens[:priority]"
            )
    return tenants


def _load_trace(path: str, default_max_new: int, default_deadline: float):
    import numpy as np

    entries = []
    try:
        lines = open(path, encoding="utf-8").read().splitlines()
    except OSError as e:
        raise SystemExit(f"cannot read --trace: {e}")
    for n, line in enumerate(lines, 1):
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
            text = obj["prompt"]
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            raise SystemExit(f"{path}:{n}: bad trace entry ({e})")
        prompt = np.frombuffer(
            text.encode("utf-8") or b"\x00", np.uint8
        ).astype(np.int32)
        entries.append({
            "arrival": float(obj.get("arrival", 0.0)),
            "prompt": prompt,
            "max_new": int(obj.get("max_new", default_max_new)),
            "deadline": float(obj.get("deadline", default_deadline)),
            "tenant": str(obj.get("tenant", "default")),
        })
    if not entries:
        raise SystemExit(f"{path}: empty trace")
    return sorted(entries, key=lambda e: e["arrival"])


def _poisson_trace(args):
    import numpy as np

    rng = np.random.default_rng(args.random_seed)
    t = 0.0
    entries = []
    for _ in range(args.num_requests):
        t += float(rng.exponential(1.0 / args.rate))
        n = int(rng.integers(args.prompt_len_min, args.prompt_len_max + 1))
        entries.append({
            "arrival": t,
            "prompt": rng.integers(1, 256, size=n).astype(np.int32),
            "max_new": args.max_new_tokens,
            "deadline": args.deadline,
        })
    return entries


def replay(engine, entries, *, poll_s: float = 0.0005):
    """Submit each entry at its arrival offset (wall clock) and step the
    engine until everything drains. Returns the Request records in
    submission order."""
    from deeplearning_mpi_tpu.resilience import InjectedFault

    # DisaggregatedEngine exposes idle() directly (two schedulers + a
    # handoff queue); the colocated engine's idleness is its scheduler's.
    idle = (
        engine.idle if hasattr(engine, "idle") else engine.scheduler.idle
    )
    pending = deque(entries)
    reqs = []
    t0 = time.monotonic()
    while pending or not idle():
        now = time.monotonic() - t0
        while pending and pending[0]["arrival"] <= now:
            e = pending.popleft()
            deadline = (
                t0 + e["arrival"] + e["deadline"] if e["deadline"] > 0
                else None
            )
            reqs.append(
                engine.submit(
                    e["prompt"], e["max_new"], deadline=deadline,
                    tenant=e.get("tenant", "default"),
                )
            )
        if not idle():
            try:
                engine.step()
            except InjectedFault as fault:
                print(f"chaos: {fault} — recovering", file=sys.stderr)
                engine.recover()
        elif pending:
            time.sleep(min(poll_s, max(pending[0]["arrival"] - now, 0.0)))
    return reqs, time.monotonic() - t0


def _report(reqs, wall_s, registry, out=sys.stderr):
    from deeplearning_mpi_tpu.serving import RequestState

    done = [r for r in reqs if r.state is RequestState.FINISHED]
    shed = [r for r in reqs if r.state is RequestState.SHED]
    tokens = sum(len(r.generated) for r in done)
    print(
        f"requests: {len(reqs)} submitted, {len(done)} completed, "
        f"{len(shed)} shed"
        + (
            " (" + ", ".join(
                f"{sum(1 for r in shed if r.shed_reason == why)} {why}"
                for why in sorted({r.shed_reason for r in shed})
            ) + ")"
            if shed else ""
        ),
        file=out,
    )
    snap = registry.snapshot()
    ttft = [k for k in ("serve_ttft_s_p50", "serve_ttft_s_p95") if k in snap]
    if done:
        print(
            f"completed tokens: {tokens} in {wall_s:.3f}s wall = "
            f"{tokens / wall_s:.1f} tokens/s",
            file=out,
        )
    if ttft:
        print(
            "TTFT p50/p95: "
            + "/".join(f"{snap[k] * 1e3:.1f}" for k in ttft) + " ms"
            + (
                f" | TPOT p50: {snap['serve_tpot_s_p50'] * 1e3:.2f} ms"
                if "serve_tpot_s_p50" in snap else ""
            ),
            file=out,
        )
    print(
        f"engine: {snap.get('serve_decode_steps', 0):.0f} decode steps, "
        f"{snap.get('serve_prefill_chunks', 0):.0f} prefill chunks"
        + (
            f", {snap['serve_decode_held_steps']:.0f} held for batching"
            if snap.get("serve_decode_held_steps") else ""
        ),
        file=out,
    )
    if "serve_prefix_hits_total" in snap:
        print(
            f"prefix cache: {snap['serve_prefix_hits_total']:.0f} hits, "
            f"{snap.get('serve_prefix_tokens_reused_total', 0):.0f} prefill "
            f"tokens reused, "
            f"{snap.get('serve_prefix_cow_copies_total', 0):.0f} CoW copies, "
            f"{snap.get('serve_prefix_evictions_total', 0):.0f} evictions",
            file=out,
        )
    if snap.get("serve_handoffs_total"):
        print(
            f"disagg: {snap['serve_handoffs_total']:.0f} prefill→decode "
            f"handoffs, {snap.get('serve_handoff_stalls_total', 0):.0f} "
            "stalled step(s)",
            file=out,
        )
    prop = snap.get("spec_proposed_total", 0)
    if prop:
        acc = snap.get("spec_accepted_total", 0)
        rb = snap.get("spec_rollback_total", 0)
        print(
            f"speculative: {prop:.0f} proposed, {acc:.0f} accepted "
            f"({acc / prop:.1%}), {rb:.0f} rolled back "
            f"({snap.get('spec_blocks_rolled_back_total', 0):.0f} KV "
            f"blocks) | accepted draft tokens/s: {acc / wall_s:.1f}",
            file=out,
        )


def _run_fleet(args, eos_id) -> int:
    """--replicas N > 1: route the trace through a supervised replica
    fleet instead of one in-process engine, then hold every completion to
    the same offline-greedy parity bar as --selftest — including requests
    that failed over between replicas mid-flight."""
    import tempfile

    from deeplearning_mpi_tpu.serving import FleetFailure, FleetSupervisor
    from deeplearning_mpi_tpu.telemetry import JsonlSink, MetricsRegistry

    if args.spec_k:
        print("--replicas > 1 does not compose with --spec_k yet",
              file=sys.stderr)
        return 1
    model_spec = {
        "vocab_size": 256,
        "num_layers": args.num_layers,
        "num_heads": args.num_heads,
        "num_kv_heads": args.num_kv_heads or None,
        "head_dim": args.head_dim,
        "d_model": args.d_model,
        "d_ff": args.d_ff,
        "attention_window": args.attention_window,
    }
    engine_spec = {
        "max_slots": args.max_slots,
        "block_size": args.block_size,
        "num_blocks": args.num_blocks,
        "max_blocks_per_seq": args.max_blocks_per_seq,
        "prefill_chunk": args.prefill_chunk,
        "max_queue": args.max_queue,
        "prefix_cache": args.prefix_cache,
    }
    if args.trace:
        entries = _load_trace(args.trace, args.max_new_tokens, args.deadline)
    else:
        entries = _poisson_trace(args)
    fleet_dir = args.fleet_dir or tempfile.mkdtemp(prefix="dmt_fleet_")
    registry = MetricsRegistry()
    if args.metrics_file:
        registry.add_sink(JsonlSink(args.metrics_file))
    autoscale = None
    if args.autoscale:
        from deeplearning_mpi_tpu.serving import AutoscalerConfig

        autoscale = AutoscalerConfig(
            min_replicas=args.min_replicas, max_replicas=args.max_replicas,
            predictive=args.autoscale_predictive,
            forecast_horizon_s=args.forecast_horizon_s,
            forecast_tau_s=args.forecast_tau_s,
            forecast_trend_tau_s=args.forecast_trend_tau_s,
        )
    sup = FleetSupervisor(
        model_spec, engine_spec, args.replicas, fleet_dir,
        seed=args.random_seed, eos_id=eos_id, warmup=True,
        chaos=args.chaos, hedge_ms=args.hedge_ms, registry=registry,
        disagg=args.disagg, tp=args.tp, tenants=_parse_tenants(args.tenants),
        autoscale=autoscale,
    )
    swap_seed = args.random_seed + 1 if args.swap_at is not None else None
    try:
        result = sup.run(entries, swap_at=args.swap_at, swap_seed=swap_seed)
    except FleetFailure as e:
        print(f"fleet FAILED: {e} (logs under {fleet_dir})", file=sys.stderr)
        return 1
    shed = ", ".join(f"{n} {why}" for why, n in sorted(result.shed.items()))
    print(
        f"fleet: {result.completed} completed, "
        f"{sum(result.shed.values())} shed" + (f" ({shed})" if shed else "")
        + f", {result.dropped} dropped | {result.redispatched} re-dispatched "
        f"across {result.restarts} restart(s)",
        file=sys.stderr,
    )
    snap = result.snapshot
    if snap.get("serve_hedge_total", 0):
        parts = []
        for k in sorted(snap):
            if k.startswith("serve_hedge_total{"):
                outcome = k.split("=", 1)[1].strip('"}')
                parts.append(f"{snap[k]:.0f} {outcome}")
        print("hedges: " + ", ".join(parts), file=sys.stderr)
    if result.scale:
        print(
            f"autoscale: {result.scale['spawned']} spawned, "
            f"{result.scale['retired']} retired, "
            f"{result.scale['vetoed']} vetoed "
            f"({result.scale['events']} decisions), brownout max stage "
            f"{result.scale['brownout_stage_max']}, final fleet "
            f"{result.scale['replicas_final']}",
            file=sys.stderr,
        )
    if result.swap["requested"]:
        print(
            f"swap: performed={result.swap['performed']} "
            f"drain={result.swap['drain_s'] and round(result.swap['drain_s'], 2)}s "
            f"completions_during={result.swap['completions_during']} "
            f"compile_flat={result.swap['compile_flat']}",
            file=sys.stderr,
        )
    registry.close()

    # Fleet parity: rebuild each weight version from (config, seed) and
    # hold every winning stream to offline greedy — the failover and
    # hedging machinery must be invisible in the tokens.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
    from deeplearning_mpi_tpu.models.generate import generate

    model = TransformerLM(
        config=TransformerConfig(**model_spec), dtype=jnp.float32
    )
    params_by_version = {}

    def version_params(version):
        if version not in params_by_version:
            seed = args.random_seed if version == 0 else swap_seed
            params_by_version[version] = model.init(
                jax.random.key(seed), jnp.zeros((1, 8), jnp.int32)
            )["params"]
        return params_by_version[version]

    mismatched = 0
    for rid, rec in sorted(result.requests.items()):
        out = generate(
            model, version_params(rec["version"]),
            jnp.asarray(rec["prompt"], jnp.int32)[None],
            max_new_tokens=rec["max_new"], rng=jax.random.key(0),
            temperature=0.0, eos_id=eos_id,
        )
        expect = np.asarray(out)[0, len(rec["prompt"]):].tolist()
        if eos_id is not None and eos_id in expect:
            expect = expect[: expect.index(eos_id) + 1]
        if rec["tokens"] != expect:
            mismatched += 1
            print(
                f"fleet parity: rid {rid} (version {rec['version']}) "
                f"diverged from offline greedy:\n"
                f"  fleet  : {rec['tokens']}\n  offline: {expect}",
                file=sys.stderr,
            )
    if mismatched or not result.ok:
        print(
            f"fleet FAILED: ok={result.ok} (dropped={result.dropped}, "
            f"compile_flat={result.compile_flat}, "
            f"chaos_balanced={result.chaos_balanced}), "
            f"{mismatched} parity mismatch(es); logs under {fleet_dir}",
            file=sys.stderr,
        )
        return 1
    peak = args.replicas
    if result.scale:
        peak = max(peak, args.replicas + result.scale["spawned"])
    print(
        f"fleet OK: {result.completed} requests bit-identical to offline "
        f"greedy across {peak} replica(s)",
        file=sys.stderr,
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.autoscale_predictive:
        args.autoscale = True  # predictive is a mode OF the autoscaler
    eos_id = args.eos_id if args.eos_id >= 0 else None
    if eos_id is not None and eos_id > 255:
        print(f"--eos_id {eos_id} is outside the byte vocab (0-255)",
              file=sys.stderr)
        return 1
    # Fail loud on chaos kinds this workload has no injection hook for:
    # a kind that can never fire would silently pass every drill while
    # keeping the reconciliation invariant unfalsifiable. CONTROLPLANE_KINDS
    # (supervisor_kill/supervisor_hang) are deliberately absent from every
    # set below: this CLI process IS the supervisor and nothing restarts
    # it, so planning its own death could never close the books. Only
    # harnesses with a restart loop around the supervisor may plan them
    # (tools/controlplane_drill.py).
    import os as _os

    chaos_spec = args.chaos or _os.environ.get("DMT_CHAOS") or ""
    if chaos_spec.strip():
        from deeplearning_mpi_tpu.resilience import (
            AUTOSCALE_KINDS,
            DISAGG_KINDS,
            FLEET_KINDS,
            SERVE_KINDS,
            validate_plan_kinds,
        )

        if args.autoscale:
            supported = FLEET_KINDS | AUTOSCALE_KINDS
            workload = "autoscaled serving fleet"
        elif args.replicas > 1:
            supported, workload = FLEET_KINDS, "serving fleet"
        elif args.disagg:
            supported, workload = DISAGG_KINDS, "disaggregated serving"
        else:
            supported, workload = SERVE_KINDS, "single-replica serving"
        try:
            validate_plan_kinds(chaos_spec, supported, workload=workload)
        except ValueError as e:
            print(f"--chaos: {e}", file=sys.stderr)
            return 1
    if args.replicas > 1 or args.autoscale:
        if args.kv_dtype:
            # Fleet parity is a bit-exact bar (failover must be invisible
            # in the tokens); a lossy KV cache would make it vacuous.
            print("--kv_dtype does not compose with fleet mode: fleet "
                  "parity is bit-exact", file=sys.stderr)
            return 1
        if args.platform:
            import jax

            jax.config.update("jax_platforms", args.platform)
        return _run_fleet(args, eos_id)
    if args.tp > 1:
        print("--tp > 1 shards replica processes; it requires "
              "--replicas > 1", file=sys.stderr)
        return 1
    if args.moe_experts > 0:
        # Same fail-fast rule as dmt-generate's composition checks: the
        # engine would raise anyway, but before minutes of init/restore.
        print(
            "serving is dense-MLP only: MoE capacity routing makes a "
            "token's output depend on co-batched strangers, breaking the "
            "engine's request-independence contract",
            file=sys.stderr,
        )
        return 1
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
    from deeplearning_mpi_tpu.serving import (
        DisaggregatedEngine,
        EngineConfig,
        RequestState,
        ServingEngine,
    )
    from deeplearning_mpi_tpu.telemetry import JsonlSink, MetricsRegistry

    cfg = TransformerConfig(
        vocab_size=256,
        num_layers=args.num_layers,
        num_heads=args.num_heads,
        num_kv_heads=args.num_kv_heads or None,
        head_dim=args.head_dim,
        d_model=args.d_model,
        d_ff=args.d_ff,
        attention_window=args.attention_window,
    )
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model = TransformerLM(config=cfg, dtype=dtype)

    if args.selftest:
        params = model.init(
            jax.random.key(args.random_seed), jnp.zeros((1, 8), jnp.int32)
        )["params"]
    else:
        from pathlib import Path

        import optax

        from deeplearning_mpi_tpu.train import (
            Checkpointer,
            create_train_state,
        )
        from deeplearning_mpi_tpu.utils import config as uconfig

        ckpt_dir = Path(args.model_dir) / args.model_filename
        if not ckpt_dir.is_dir():
            print(f"no checkpoint found under {ckpt_dir} "
                  "(--selftest serves a random-init model)", file=sys.stderr)
            return 1
        err = uconfig.arch_mismatch_error(cfg, ckpt_dir)
        if err:
            print(err, file=sys.stderr)
            return 1
        template = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
            optax.identity(), ema=args.ema > 0,
        )
        ckpt = Checkpointer(ckpt_dir)
        try:
            state = ckpt.restore_params_only(template, epoch=args.epoch)
        except Exception as e:  # noqa: BLE001 — orbax raises its own types;
            # one clean line beats a multi-frame traceback for a CLI.
            print(f"failed to restore from {ckpt.directory}: {e}",
                  file=sys.stderr)
            return 1
        finally:
            ckpt.close()
        params = state.params if state.ema_params is None else state.ema_params

    registry = MetricsRegistry()
    if args.metrics_file:
        registry.add_sink(JsonlSink(args.metrics_file))
    from deeplearning_mpi_tpu.resilience import ChaosInjector

    chaos = ChaosInjector.from_spec(args.chaos, registry=registry)
    if args.tuning_db:
        from deeplearning_mpi_tpu.compiler.autotune import set_default_db

        set_default_db(args.tuning_db)
    # --use_kernel forces the Pallas path; with only a tuning DB the
    # schedule choice itself (kernel vs einsum) defers to the DB's winner
    # (use_kernel=None); otherwise the einsum default stands.
    use_kernel = True if args.use_kernel else (None if args.tuning_db else False)

    spec_k = args.spec_k
    if spec_k and args.draft_layers < 1:
        print("--spec_k needs a draft model: pass --draft_layers N "
              "(self-speculative layer truncation)", file=sys.stderr)
        return 1
    if spec_k == -1:
        from deeplearning_mpi_tpu.compiler import autotune

        tuned = autotune.tuned_spec_k(cfg, args.draft_layers, dtype)
        spec_k = tuned["spec_k"] if tuned else 0
        print(
            f"spec_k from tuning DB: {spec_k}"
            + (f" (tuned accept_rate {tuned['accept_rate']:.2f})" if tuned
               else " (no spec_k entry for this model/draft — disabled)"),
            file=sys.stderr,
        )
    draft_cfg = draft_params = None
    if spec_k > 0:
        from deeplearning_mpi_tpu.models import draft_config, truncate_lm_params

        overrides = {
            k: v for k, v in (
                ("d_model", args.draft_d_model),
                ("d_ff", args.draft_d_ff),
                ("num_heads", args.draft_heads),
                ("head_dim", args.draft_head_dim),
            ) if v is not None
        }
        draft_cfg = draft_config(cfg, args.draft_layers, **overrides)
        if overrides:
            # Width changed: target arrays can't be reused. Random init —
            # acceptance will be poor until the draft is trained, but the
            # exact-match rule keeps outputs correct regardless.
            draft_model = TransformerLM(config=draft_cfg, dtype=dtype)
            draft_params = draft_model.init(
                jax.random.key(args.draft_seed), jnp.zeros((1, 8), jnp.int32)
            )["params"]
        else:
            draft_params = truncate_lm_params(params, args.draft_layers)

    try:
        decode_buckets = tuple(
            int(b) for b in args.decode_buckets.split(",") if b.strip()
        )
    except ValueError:
        print(f"bad --decode_buckets {args.decode_buckets!r}: expected "
              "comma-separated integers like '8,16,32'", file=sys.stderr)
        return 1
    engine_cls = DisaggregatedEngine if args.disagg else ServingEngine
    engine = engine_cls(
        cfg, params,
        EngineConfig(
            max_slots=args.max_slots,
            block_size=args.block_size,
            num_blocks=args.num_blocks,
            max_blocks_per_seq=args.max_blocks_per_seq,
            prefill_chunk=args.prefill_chunk,
            max_queue=args.max_queue,
            use_kernel=use_kernel,
            spec_k=spec_k,
            decode_buckets=decode_buckets,
            max_hold_steps=args.max_hold_steps,
            kv_dtype=args.kv_dtype,
            prefix_cache=args.prefix_cache,
        ),
        dtype=dtype, eos_id=eos_id, registry=registry, chaos=chaos,
        draft_config=draft_cfg, draft_params=draft_params,
        tenants=_parse_tenants(args.tenants),
    )
    if args.warmup:
        t_warm = time.monotonic()
        engine.warmup()
        print(f"warmup: decode+prefill compiled in "
              f"{time.monotonic() - t_warm:.2f}s", file=sys.stderr)

    if args.trace:
        entries = _load_trace(args.trace, args.max_new_tokens, args.deadline)
    else:
        entries = _poisson_trace(args)
    too_long = [
        i for i, e in enumerate(entries)
        if len(e["prompt"]) + e["max_new"] > engine.engine.max_seq_len
    ]
    if too_long:
        print(
            f"warning: {len(too_long)} request(s) exceed the engine's "
            f"{engine.engine.max_seq_len}-position ceiling "
            "(max_blocks_per_seq * block_size) and will be shed at submit",
            file=sys.stderr,
        )

    reqs, wall_s = replay(engine, entries)
    _report(reqs, wall_s, registry)
    if chaos is not None:
        print(chaos.summary(), file=sys.stderr)
    registry.emit("serve_summary", registry.snapshot())
    registry.close()

    if not args.selftest:
        for r in reqs:
            if r.state is RequestState.FINISHED:
                text = np.asarray(r.generated, np.uint8).tobytes().decode(
                    "utf-8", errors="replace"
                )
                print(f"[{r.rid}] {text!r}")
        return 0

    # Selftest parity: every completed request must match the offline
    # greedy decode of the same prompt token-for-token — a completion that
    # depends on which strangers shared the batch is the one bug class a
    # continuous-batching engine must never have.
    from deeplearning_mpi_tpu.models.generate import generate

    done = [r for r in reqs if r.state is RequestState.FINISHED]
    if len(done) != len(reqs):
        bad = [(r.rid, r.state.value, r.shed_reason) for r in reqs
               if r.state is not RequestState.FINISHED]
        print(f"selftest: not all requests completed: {bad}", file=sys.stderr)
        return 1
    kv_lossy = args.kv_dtype is not None
    mismatched = 0
    tokens_expected = 0
    tokens_accepted = 0
    for r in done:
        out = generate(
            model, params, jnp.asarray(r.prompt)[None],
            max_new_tokens=r.max_new_tokens, rng=jax.random.key(0),
            temperature=0.0, eos_id=eos_id,
        )
        expect = np.asarray(out)[0, r.prompt_len :].tolist()
        if eos_id is not None and eos_id in expect:
            # offline pads with EOS to the static window; the engine stops.
            expect = expect[: expect.index(eos_id) + 1]
        # Matched-prefix length: greedy decode forks permanently at the
        # first divergent token, so the prefix is the honest agreement
        # measure for the lossy-KV acceptance gate.
        agree = 0
        for a, b in zip(r.generated, expect):
            if a != b:
                break
            agree += 1
        tokens_expected += len(expect)
        tokens_accepted += agree
        if r.generated != expect:
            mismatched += 1
            if not kv_lossy:
                print(
                    f"selftest: rid {r.rid} diverged from offline greedy:\n"
                    f"  engine : {r.generated}\n  offline: {expect}",
                    file=sys.stderr,
                )
    if kv_lossy:
        # A quantized KV cache is allowed to perturb tokens — but only so
        # far. The gate is MEASURED acceptance against the fp reference,
        # not a promise: quantization bugs (wrong scale, stale epoch)
        # crater acceptance and fail here.
        acceptance = tokens_accepted / max(tokens_expected, 1)
        if acceptance < args.kv_acceptance_min:
            print(
                f"selftest FAILED: {args.kv_dtype} KV acceptance "
                f"{acceptance:.1%} ({tokens_accepted}/{tokens_expected} "
                f"tokens match the fp reference) below the "
                f"--kv_acceptance_min {args.kv_acceptance_min:.1%} gate",
                file=sys.stderr,
            )
            return 1
        print(
            f"selftest {args.kv_dtype} KV: acceptance {acceptance:.1%} "
            f"({tokens_accepted}/{tokens_expected} tokens, "
            f"{mismatched} stream(s) diverged) >= "
            f"{args.kv_acceptance_min:.1%} gate",
            file=sys.stderr,
        )
    elif mismatched:
        print(f"selftest FAILED: {mismatched}/{len(done)} request(s) "
              "diverged", file=sys.stderr)
        return 1
    if spec_k > 0:
        snap = registry.snapshot()
        prop = snap.get("spec_proposed_total", 0)
        acc = snap.get("spec_accepted_total", 0)
        rb = snap.get("spec_rollback_total", 0)
        if prop != acc + rb:
            print(f"selftest FAILED: speculative counters do not "
                  f"reconcile: proposed {prop:.0f} != accepted {acc:.0f} "
                  f"+ rolled back {rb:.0f}", file=sys.stderr)
            return 1
        if not prop or not acc:
            print(f"selftest FAILED: speculative path inert (proposed "
                  f"{prop:.0f}, accepted {acc:.0f}) — the draft should "
                  "land at least some exact matches", file=sys.stderr)
            return 1
        print(f"selftest speculative: {prop:.0f} proposed = {acc:.0f} "
              f"accepted + {rb:.0f} rolled back (rate {acc / prop:.1%})",
              file=sys.stderr)
    bar = (
        f"within the {args.kv_acceptance_min:.1%} acceptance gate vs"
        if kv_lossy else "bit-identical to"
    )
    print(
        f"selftest OK: {len(done)} requests {bar} offline "
        f"greedy decode ({engine.pool.total_allocated} block allocations, "
        f"{engine.pool.total_freed} frees)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
