"""Transformer LM training — the long-context / multi-axis-parallel workload.

No reference analog (the reference's workloads are CNNs — ``SURVEY.md``
§5.7); this is the workload that exercises the framework's first-class
long-context and parallelism machinery:

    # dense LM on synthetic bytes, pure DP
    python -m deeplearning_mpi_tpu.cli.train_lm --num_epochs 3

    # 64k context over a seq axis with ring attention + TP, on 8 fake devices
    python -m deeplearning_mpi_tpu.cli.train_lm \
        --n_virtual_devices 8 --sp 4 --tp 2 --attention ring --seq_len 65536

    # MoE LM with experts sharded over the expert axis
    python -m deeplearning_mpi_tpu.cli.train_lm --ep 4 --moe_experts 8

Same trainer, logger, checkpoint, and flag conventions as the
resnet/unet CLIs (``pytorch/resnet/main.py:167-182`` flag contract).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    from deeplearning_mpi_tpu.utils import config

    parser = argparse.ArgumentParser(description=__doc__)
    config.add_topology_flags(parser)
    config.add_training_flags(
        parser, num_epochs=10, batch_size=32, learning_rate=3e-4, random_seed=0,
        model_filename="lm",
    )
    group = config.add_lm_model_flags(parser)
    group.add_argument("--remat", nargs="?", const="full", default="none",
                       choices=("none", "dots", "full"),
                       help="rematerialization policy per block: bare "
                       "--remat (= full) recomputes each block's forward "
                       "(max HBM savings, one extra forward of FLOPs); "
                       "'dots' saves matmul outputs and recomputes only "
                       "elementwise glue (near-free FLOPs). MFU accounting "
                       "stays honest either way: recompute lands in "
                       "mfu_issued/mfu_gap, never in mfu "
                       "(telemetry/flops.py)")
    group.add_argument("--microbatches", type=int, default=4,
                       help="GPipe microbatches when --pp > 1 (bubble fraction = (pp-1)/(M+pp-1))")
    group.add_argument("--attention", default="dense",
                       choices=["dense", "flash", "ring", "ulysses"],
                       help="attention core: flash = Pallas TPU kernel; ring/ulysses = sequence-parallel over --sp")
    group.add_argument("--moe_aux_weight", type=float, default=0.01)
    group.add_argument("--allow_acausal_routing", action="store_true",
                       help="acknowledge that --moe_routing expert_choice "
                       "lets routing see the whole sequence, leaking future "
                       "tokens into this causal LM's training (and that "
                       "KV-cached decode routes differently). Without this "
                       "flag the trainer refuses the combination")
    group.add_argument("--loss_chunk", type=int, default=0,
                       help="compute the head matmul + cross-entropy in "
                       "sequence chunks of this size so [B, S, vocab] logits "
                       "never materialize (the long-context memory lever; "
                       "tied embeddings only). 0 = standard loss")
    group.add_argument("--aot_warmup", action="store_true",
                       help="AOT-compile the train step on a sample batch "
                       "before the first epoch (compiler/aot.py): the compile "
                       "leaves the timed loop, XLA's cost analysis backfills "
                       "FLOPs/bytes telemetry, and compile-cache hit/miss "
                       "counters land in the metrics registry")
    data = parser.add_argument_group("data")
    data.add_argument("--text_file", default=None,
                      help="train on this file's bytes (vocab 256); default: synthetic motifs")
    data.add_argument("--train_sequences", type=int, default=512,
                      help="synthetic dataset size (sequences)")
    return parser


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    # Fail-loud doctrine (train/resilience.py): expert-choice routing is
    # acausal — each expert ranks ALL positions when picking its top-C
    # tokens, so position t's MLP output depends on tokens > t. On this
    # causal trainer that silently trains with future leakage and then
    # mismatches generate.py's step-by-step decode routing. Help text alone
    # proved too quiet (round-3 verdict weak #6); require the explicit ack.
    # > 0, not > 1: the model builds a routed MoE for any moe_experts >= 1
    # (models/transformer.py), and even a single expert's top-C selection
    # ranks the whole sequence.
    if (args.moe_experts > 0 and args.moe_routing == "expert_choice"
            and not args.allow_acausal_routing):
        parser.error(
            "--moe_routing expert_choice leaks future tokens into causal LM "
            "training (routing ranks the whole sequence) and routes "
            "differently under KV-cached decode. Pass "
            "--allow_acausal_routing to proceed anyway, or use "
            "--moe_routing token_choice."
        )

    from deeplearning_mpi_tpu.utils import config

    topo, mesh = config.setup_runtime(args)

    if args.tuned_step:
        # Consult BEFORE anything is built: remat is a model property and
        # grad_accum feeds preflight's divisibility checks. Never-raise —
        # a missing/corrupt DB or an untuned shape keeps the flag defaults.
        import jax.numpy as _jnp

        from deeplearning_mpi_tpu.compiler.autotune import (
            TuningDB,
            tuned_step_schedule,
        )

        tuned = tuned_step_schedule(
            "lm", (args.batch_size, args.seq_len), mesh,
            _jnp.bfloat16 if args.dtype == "bfloat16" else _jnp.float32,
            db=TuningDB.load(args.tuned_step),
        )
        if tuned:
            args.remat = tuned.get("remat", args.remat)
            if tuned.get("grad_accum"):
                args.grad_accum = int(tuned["grad_accum"])
            if "overlap" in tuned:
                args.zero_overlap = bool(tuned["overlap"])
            print(f"tuned step schedule ({args.tuned_step}): {tuned}",
                  file=sys.stderr)
        else:
            print(f"no step tuning for this shape in {args.tuned_step}; "
                  "using flag defaults", file=sys.stderr)

    from deeplearning_mpi_tpu.train.resilience import preflight

    preflight(
        model_dir=args.model_dir, log_dir=args.log_dir,
        global_batch_size=args.batch_size, mesh=mesh,
        grad_accum=args.grad_accum,
    )

    import jax
    import jax.numpy as jnp

    from deeplearning_mpi_tpu.data import ShardedLoader
    from deeplearning_mpi_tpu.data.lm_text import ByteTextDataset, SyntheticTokens
    from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
    from deeplearning_mpi_tpu.train import Checkpointer, Trainer, create_train_state
    from deeplearning_mpi_tpu.train.trainer import build_optimizer
    from deeplearning_mpi_tpu.utils.logging import RunLogger

    logger = RunLogger(args.log_dir)
    logger.log_system_information()
    logger.log_hyperparameters(vars(args))

    if args.text_file:
        dataset = ByteTextDataset(args.text_file, args.seq_len)
    else:
        dataset = SyntheticTokens(
            args.train_sequences, args.seq_len, seed=args.random_seed
        )
    n_eval = max(1, len(dataset) // 10)
    train_ds = _Slice(dataset, 0, len(dataset) - n_eval)
    eval_ds = _Slice(dataset, len(dataset) - n_eval, len(dataset))

    train_loader = ShardedLoader(
        train_ds, args.batch_size, mesh, shuffle=True, seed=args.random_seed,
        num_workers=args.num_workers,
    )
    eval_loader = ShardedLoader(
        eval_ds, args.batch_size, mesh, shuffle=False, drop_last=False,
        num_workers=args.num_workers,
    )

    attention_fn = None
    if args.attention == "flash":
        # The BHSD-native entry: Attention sees .layout == 'bhsd' and
        # projects q/k/v straight into the kernel layout — no BSHD round
        # trip in either pass (docs/PERF_ANALYSIS.md §8's transpose tax).
        from deeplearning_mpi_tpu.ops.pallas import flash_attention_bhsd

        attention_fn = flash_attention_bhsd
    elif args.attention == "ring":
        from deeplearning_mpi_tpu.parallel import make_ring_attention_fn

        attention_fn = make_ring_attention_fn(mesh)
    elif args.attention == "ulysses":
        from deeplearning_mpi_tpu.parallel import make_ulysses_attention_fn

        if jax.default_backend() == "tpu":
            # Per-shard attention on the Pallas kernel: after the all-to-all
            # each device holds full-sequence shards for a head subset, the
            # exact shape flash tiles best. Off-TPU keeps the dense inner
            # (the Pallas interpreter is slower than XLA dense on CPU).
            from deeplearning_mpi_tpu.ops.pallas import flash_attention

            attention_fn = make_ulysses_attention_fn(
                mesh, inner=flash_attention
            )
        else:
            attention_fn = make_ulysses_attention_fn(mesh)

    cfg = TransformerConfig(
        vocab_size=256,
        num_layers=args.num_layers,
        num_heads=args.num_heads,
        num_kv_heads=args.num_kv_heads or None,
        head_dim=args.head_dim,
        d_model=args.d_model,
        d_ff=args.d_ff,
        moe_experts=args.moe_experts,
        moe_top_k=args.moe_top_k,
        moe_routing=args.moe_routing,
        attention_window=args.attention_window,
    )
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if args.pp > 1:
        from deeplearning_mpi_tpu.models.pipeline_lm import PipelinedLM

        model = PipelinedLM(
            cfg, mesh, num_microbatches=args.microbatches,
            dtype=dtype, attention_fn=attention_fn, remat=args.remat,
            return_prehead=args.loss_chunk > 0,
        )
    else:
        model = TransformerLM(
            config=cfg, dtype=dtype, attention_fn=attention_fn, remat=args.remat,
            return_prehead=args.loss_chunk > 0,
        )
    tx = build_optimizer(args.optimizer, config.build_lr(args, train_loader),
                         weight_decay=args.weight_decay, clip_norm=1.0)

    def state_factory():
        return create_train_state(
            model, jax.random.key(args.random_seed),
            jnp.zeros((1, args.seq_len), jnp.int32), tx,
            mesh=mesh, zero=args.zero, ema=args.ema > 0,
        )

    state = state_factory()

    # Chaos harness (None unless --chaos/$DMT_CHAOS): one injector spans
    # checkpointer, loader, and trainer so the fault/recovery accounting
    # reconciles across layers (docs/RESILIENCE.md).
    chaos = config.build_chaos(args)

    ckpt_dir = f"{args.model_dir}/{args.model_filename}"
    checkpointer = Checkpointer(
        ckpt_dir, max_to_keep=args.keep_checkpoints, chaos=chaos
    )
    # restore_for_start can SystemExit (--eval_only with no checkpoint); it
    # must do so inside the try or the other hosts hang at their next
    # collective (bootstrap.shutdown never runs) and orbax threads leak.
    # The arch guard sits inside for the same reason.
    try:
        # Tree-invisible flags (--attention_window, --moe_routing) would
        # otherwise train/eval/resume with silently different semantics
        # than the directory's checkpoints — the array restore cannot catch
        # them (config.save_arch's rationale). Guarded on EVERY start, so a
        # fresh run into a dir holding a different architecture's epochs
        # cannot re-stamp the sidecar out from under them; --eval_only is
        # read-only (check, never write).
        err = config.arch_mismatch_error(cfg, ckpt_dir)
        if err:
            print(err, file=sys.stderr)
            return 1
        if not args.eval_only:
            config.save_arch(cfg, ckpt_dir)
        state, start_epoch = config.restore_for_start(args, checkpointer, state, logger)
        trainer = Trainer(
            state, "lm", mesh,
            logger=logger, checkpointer=checkpointer, eval_every=args.eval_every,
            aux_weight=args.moe_aux_weight if args.moe_experts else 0.0,
            grad_accum=args.grad_accum, loss_chunk=args.loss_chunk,
            zero=args.zero, overlap=args.zero_overlap,
            clip_norm=1.0,  # the optimizer chain's clip, mirrored by overlap
            ema_decay=args.ema, chaos=chaos,
            guardrails=config.build_guardrails(args),
        )
        trainer.place_state()
        if chaos is not None:
            from deeplearning_mpi_tpu.resilience import ResilientLoader

            chaos.bind_registry(trainer.metrics)
            # The stall watchdog only wraps the TRAIN loader under chaos —
            # its serialized assembly is the price of injectable deadlines
            # (watchdog.py docstring), not worth paying on clean runs.
            train_loader = ResilientLoader(
                train_loader, chaos=chaos, logger=logger
            )
        # Analytic per-step cost estimates feed the telemetry registry's
        # MFU and collective-byte epoch stats (telemetry/flops.py,
        # telemetry/comms.py): gradient sync over data, plus whichever
        # sequence/pipeline/expert collectives this run's flags engaged.
        from deeplearning_mpi_tpu.telemetry import comms
        from deeplearning_mpi_tpu.telemetry.flops import (
            transformer_issued_flops,
            transformer_train_flops,
        )

        dp = mesh.shape.get("data", 1)
        sp = mesh.shape.get("seq", 1)
        pp = mesh.shape.get("pipe", 1)
        ep = mesh.shape.get("expert", 1)
        batch_local = max(args.batch_size // max(dp, 1), 1)
        comm_bytes = comms.dp_grad_allreduce_bytes(
            comms.param_count(trainer.state.params), dp, zero=args.zero
        )
        act_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
        if args.attention == "ulysses":
            comm_bytes += comms.ulysses_attention_bytes(
                batch_local, max(args.seq_len // sp, 1), args.num_heads,
                args.head_dim, sp, kv_heads=args.num_kv_heads or None,
                num_layers=args.num_layers, dtype=act_dtype,
            )
        elif args.attention == "ring":
            comm_bytes += comms.ring_attention_bytes(
                batch_local, max(args.seq_len // sp, 1), args.num_heads,
                args.head_dim, sp, kv_heads=args.num_kv_heads or None,
                num_layers=args.num_layers, dtype=act_dtype,
            )
        if pp > 1:
            comm_bytes += comms.pipeline_bytes(
                (max(batch_local // args.microbatches, 1), args.seq_len,
                 args.d_model),
                args.microbatches, pp, dtype=act_dtype,
            )
        if args.moe_experts and ep > 1:
            comm_bytes += comms.moe_dispatch_bytes(
                batch_local * args.seq_len, args.d_model, ep,
                top_k=args.moe_top_k, num_layers=args.num_layers,
                dtype=act_dtype,
            )
        config.build_observability(
            args, trainer,
            flops_per_step=transformer_train_flops(
                cfg, args.batch_size, args.seq_len
            ),
            # Remat recompute counts in ISSUED flops only — mfu stays the
            # paper-comparable model-FLOPs number, mfu_gap shows the tax.
            issued_flops_per_step=transformer_issued_flops(
                cfg, args.batch_size, args.seq_len, remat=args.remat
            ),
            comm_bytes_per_step=comm_bytes,
        )
        if args.aot_warmup and not args.eval_only:
            # One real batch fixes the avals; the generator is closed
            # immediately so its prefetch producer never overlaps training.
            batches = train_loader.epoch(0)
            try:
                sample = next(iter(batches))
            finally:
                if hasattr(batches, "close"):
                    batches.close()
            trainer.warmup(sample)
        config.execute_training(
            trainer, checkpointer, args, train_loader, eval_loader, start_epoch,
            state_factory=state_factory,
        )
    finally:
        checkpointer.close()
        from deeplearning_mpi_tpu.runtime import bootstrap
        bootstrap.shutdown()
    return 0


class _Slice:
    """Contiguous view of a dataset — the train/eval split (the reference
    splits 80/20 with ``random_split``, ``pytorch/unet/train.py:86-88``)."""

    def __init__(self, dataset, start: int, stop: int) -> None:
        self.dataset = dataset
        self.start = start
        self.stop = stop

    def __len__(self) -> int:
        return self.stop - self.start

    def __getitem__(self, index: int):
        return self.dataset[self.start + index]


if __name__ == "__main__":
    sys.exit(main())
