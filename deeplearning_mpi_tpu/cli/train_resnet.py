"""Data-parallel ResNet image classification on CIFAR-10.

TPU-native rebuild of the reference trainer (``pytorch/resnet/main.py``):

    python -m deeplearning_mpi_tpu.cli.train_resnet \
        --num_epochs 100 --batch_size 128 --learning_rate 0.1

Reference parity: ResNet-18 head swapped to 10 classes (``main.py:40-41``),
SGD momentum 0.9 / weight decay 1e-5 + cross-entropy (``main.py:113-114``),
per-epoch mean-loss logging (``main.py:134``), every-10-epoch eval +
checkpoint (``main.py:136-142``), ``--resume`` (``main.py:48-52``). The
``--arch`` flag extends the family to ResNet-50/152 (the BASELINE.md config
ladder); ``--synthetic`` trains on the hermetic synthetic dataset when no
CIFAR-10 directory is available.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    from deeplearning_mpi_tpu.utils import config

    parser = argparse.ArgumentParser(description=__doc__)
    config.add_topology_flags(parser)
    # ResNet defaults: epochs 100, batch 128, lr 0.1, seed 0 (main.py:162-176).
    config.add_training_flags(
        parser, num_epochs=100, batch_size=128, learning_rate=0.1, random_seed=0,
        model_filename="resnet_distributed", optimizer="sgd", weight_decay=1e-5,
    )
    parser.add_argument("--arch", default="resnet18",
                        choices=["resnet18", "resnet34", "resnet50",
                                 "resnet101", "resnet152",
                                 "vit_tiny", "vit_small"],
                        help="resnet* = reference-parity CNN family; vit_* "
                        "= the attention-native classifier (models/vit.py) "
                        "on the same data/trainer stack")
    parser.add_argument("--stem", default="imagenet", choices=["imagenet", "cifar"],
                        help="imagenet = torchvision-parity 7x7/2 stem (main.py:40)")
    parser.add_argument("--torch_padding", action="store_true",
                        help="torch-exact symmetric padding on strided convs "
                        "— use when resuming a dmt-import-torch'd "
                        "torchvision checkpoint (models/resnet.py)")
    parser.add_argument("--data_dir", default="data", help="dir containing cifar-10-batches-py")
    parser.add_argument("--synthetic", action="store_true",
                        help="train on synthetic CIFAR-like data (no dataset needed)")
    parser.add_argument("--train_samples", type=int, default=2048,
                        help="synthetic dataset size")
    parser.add_argument("--momentum", type=float, default=0.9)
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.torch_padding and args.arch.startswith("vit"):
        raise SystemExit(
            "--torch_padding is a CNN numerics flag (strided-conv "
            "padding); it does not apply to --arch " + args.arch
        )

    from deeplearning_mpi_tpu.utils import config

    topo, mesh = config.setup_runtime(args)

    from deeplearning_mpi_tpu.train.resilience import preflight

    preflight(
        data_dir=None if args.synthetic else args.data_dir,
        model_dir=args.model_dir, log_dir=args.log_dir,
        global_batch_size=args.batch_size, mesh=mesh,
        grad_accum=args.grad_accum,
    )

    import jax
    import jax.numpy as jnp

    from deeplearning_mpi_tpu.data import CIFAR10, ShardedLoader, SyntheticCIFAR10
    from deeplearning_mpi_tpu.data.native import (
        eval_transform,
        native_available,
        train_transform,
    )
    from deeplearning_mpi_tpu.models import get_model
    from deeplearning_mpi_tpu.train import Checkpointer, Trainer, create_train_state
    from deeplearning_mpi_tpu.train.trainer import build_optimizer
    from deeplearning_mpi_tpu.utils.logging import RunLogger

    logger = RunLogger(args.log_dir)
    logger.log_system_information()
    logger.log_hyperparameters(vars(args))
    logger.log(
        "input pipeline: native C++ transforms"
        if native_available()
        else "input pipeline: numpy transforms (native lib unavailable; "
        "set g++ on PATH or unset DLMPI_TPU_NO_NATIVE)"
    )

    if args.synthetic:
        train_ds = SyntheticCIFAR10(args.train_samples, seed=args.random_seed)
        eval_ds = SyntheticCIFAR10(
            max(args.batch_size, args.train_samples // 8), seed=args.random_seed + 1
        )
    else:
        train_ds = CIFAR10(args.data_dir, train=True)
        eval_ds = CIFAR10(args.data_dir, train=False)

    train_loader = ShardedLoader(
        train_ds, args.batch_size, mesh,
        shuffle=True, seed=args.random_seed, transform=train_transform,
        num_workers=args.num_workers,
    )
    eval_loader = ShardedLoader(
        eval_ds, args.batch_size, mesh,
        shuffle=False, drop_last=False, transform=eval_transform,
        num_workers=args.num_workers,
    )

    model_kw = {}
    if args.torch_padding:  # vit rejected at parse time above
        model_kw["torch_padding"] = True
    model = get_model(
        args.arch, num_classes=10, stem=args.stem,
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        **model_kw,
    )
    tx = build_optimizer(
        args.optimizer, config.build_lr(args, train_loader),
        momentum=args.momentum, weight_decay=args.weight_decay,
    )
    def state_factory():
        return create_train_state(
            model, jax.random.key(args.random_seed), jnp.zeros((1, 32, 32, 3)), tx,
            mesh=mesh, zero=args.zero, ema=args.ema > 0,
        )

    state = state_factory()

    # Chaos harness (None unless --chaos/$DMT_CHAOS): one injector spans
    # checkpointer, loader, and trainer (docs/RESILIENCE.md).
    chaos = config.build_chaos(args)

    checkpointer = Checkpointer(
        f"{args.model_dir}/{args.model_filename}",
        max_to_keep=args.keep_checkpoints, chaos=chaos,
    )
    # restore_for_start can SystemExit (--eval_only with no checkpoint); it
    # must do so inside the try or the other hosts hang at their next
    # collective (bootstrap.shutdown never runs) and orbax threads leak.
    try:
        state, start_epoch = config.restore_for_start(args, checkpointer, state, logger)
        trainer = Trainer(
            state, "classification", mesh,
            logger=logger, checkpointer=checkpointer, eval_every=args.eval_every,
            grad_accum=args.grad_accum, zero=args.zero,
            ema_decay=args.ema, chaos=chaos,
            guardrails=config.build_guardrails(args),
        )
        trainer.place_state()  # replicate (dp) or TP-shard (--tp > 1)
        if chaos is not None:
            from deeplearning_mpi_tpu.resilience import ResilientLoader

            chaos.bind_registry(trainer.metrics)
            train_loader = ResilientLoader(
                train_loader, chaos=chaos, logger=logger
            )
        # Analytic train FLOPs → MFU (vit_* has no table entry yet; the DP
        # gradient-sync bytes are derived inside build_observability).
        flops_per_step = None
        if args.arch.startswith("resnet"):
            from deeplearning_mpi_tpu.telemetry.flops import resnet_train_flops

            flops_per_step = resnet_train_flops(
                args.arch, args.batch_size, 32, stem=args.stem
            )
        config.build_observability(args, trainer, flops_per_step=flops_per_step)
        config.execute_training(
            trainer, checkpointer, args, train_loader, eval_loader, start_epoch,
            state_factory=state_factory,
        )
    finally:
        checkpointer.close()
        from deeplearning_mpi_tpu.runtime import bootstrap
        bootstrap.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
