"""Elastic pod launcher — the framework's ``torchrun``.

Where the reference launches every multi-process run through ``torchrun
--nproc-per-node ... train.py`` (``pytorch/unet/run.sh:100-112``), this CLI
wraps any training command in the :class:`~..resilience.pod.PodSupervisor`:
one worker process per simulated host, pod-level liveness from aggregated
heartbeats, and on a rank loss an elastic re-form onto the survivors —
smaller world, fresh rendezvous, resume from the latest verified checkpoint.

Usage::

    dmt-launch-pod --num_processes 2 --pod_dir /tmp/pod \\
        --chaos rank_kill@step:6 -- \\
        python -m deeplearning_mpi_tpu.cli.train_lm --platform cpu --resume ...

Everything after ``--`` is the worker command, run verbatim once per rank
with the rendezvous/heartbeat/chaos env contract injected. The worker MUST
pass ``--resume`` (a re-formed world that starts from scratch defeats the
point). Exit status: 0 when every rank of the final world exits 0, 1 when
the pod fails (survivors below ``--min_world_size`` or restart budget
spent).
"""

from __future__ import annotations

import argparse
import sys

from deeplearning_mpi_tpu.resilience.pod import PodFailure, PodSupervisor


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dmt-launch-pod",
        description="Supervise an elastic multi-process (simulated pod) run.",
    )
    p.add_argument("--num_processes", type=int, required=True,
                   help="initial world size (one worker process per rank)")
    p.add_argument("--pod_dir", required=True,
                   help="supervisor state: heartbeats, per-rank logs, "
                        "pod_metrics.jsonl")
    p.add_argument("--chaos", default=None,
                   help="chaos spec forwarded to workers via $DMT_CHAOS; "
                        "rank_kill/rank_hang entries are accounted here")
    p.add_argument("--heartbeat_deadline_s", type=float, default=60.0,
                   help="progress stall past this = hung rank")
    p.add_argument("--heartbeat_interval_s", type=float, default=1.0,
                   help="worker heartbeat cadence ($DMT_HEARTBEAT_INTERVAL_S)")
    p.add_argument("--spawn_grace_s", type=float, default=120.0,
                   help="startup window (spawn+import+compile) before a "
                        "never-progressed rank counts as hung")
    p.add_argument("--poll_interval_s", type=float, default=0.5)
    p.add_argument("--min_world_size", type=int, default=1,
                   help="fail the pod rather than re-form below this")
    p.add_argument("--max_pod_restarts", type=int, default=2)
    p.add_argument("--straggler_factor", type=float, default=4.0,
                   help="flag a rank whose progress age exceeds this multiple "
                        "of the median inter-progress interval")
    p.add_argument("worker_cmd", nargs=argparse.REMAINDER,
                   help="worker command (prefix with --); must pass --resume")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.worker_cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("dmt-launch-pod: no worker command given (after --)",
              file=sys.stderr)
        return 2
    sup = PodSupervisor(
        cmd,
        args.num_processes,
        args.pod_dir,
        chaos=args.chaos,
        heartbeat_deadline_s=args.heartbeat_deadline_s,
        heartbeat_interval_s=args.heartbeat_interval_s,
        spawn_grace_s=args.spawn_grace_s,
        poll_interval_s=args.poll_interval_s,
        min_world_size=args.min_world_size,
        max_pod_restarts=args.max_pod_restarts,
        straggler_factor=args.straggler_factor,
    )
    try:
        result = sup.run()
    except PodFailure:
        return 1
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
