"""Data-parallel 2-D UNet binary semantic segmentation.

TPU-native rebuild of the reference trainer (``pytorch/unet/train.py``):

    python -m deeplearning_mpi_tpu.cli.train_unet \
        --num_epochs 100 --batch_size 16 --learning_rate 1e-4 --scale 0.2

Reference parity: UNet with 64/128/256/512 encoder + 1024 bottleneck
(``model.py:56-68``), Adam + BCEWithLogits (``train.py:160-162``), grad-clip
1.0 (``train.py:194``), non-finite-loss skip (``train.py:186-188``),
timestamped run log with hyperparams + system info (``train.py:44-57,
356-360``), every-10-epoch Dice eval + checkpoint (``train.py:213-221``),
Carvana-style image/mask folder layout with ``--scale`` resizing
(``data_loading.py:52-134``). ``--synthetic`` substitutes the hermetic
random-ellipse dataset when no data directory exists.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    from deeplearning_mpi_tpu.utils import config

    parser = argparse.ArgumentParser(description=__doc__)
    config.add_topology_flags(parser)
    # UNet defaults: epochs 100, batch 16, lr 1e-4, seed 42 (train.py:314-335).
    config.add_training_flags(
        parser, num_epochs=100, batch_size=16, learning_rate=1e-4, random_seed=42,
        model_filename="unet_distributed",
    )
    parser.add_argument("--data_dir", default="data",
                        help="dir with images/ and masks/ subdirs (train.py:83-85)")
    parser.add_argument("--scale", type=float, default=0.2,
                        help="image downscale factor (train.py:85)")
    parser.add_argument("--mask_suffix", default="", help="mask filename suffix, e.g. _mask")
    parser.add_argument("--bilinear", action="store_true",
                        help="bilinear upsampling instead of transposed conv (model.py:40-43)")
    parser.add_argument("--reference_topology", action="store_true",
                        help="the reference's decoder channel plan (upsample "
                        "keeps channels, DoubleConv reduces from 3f) — "
                        "required when resuming from a dmt-import-torch'd "
                        ".pth checkpoint")
    parser.add_argument("--val_fraction", type=float, default=0.2,
                        help="held-out fraction (80/20 split parity, train.py:86-88)")
    parser.add_argument("--clip_norm", type=float, default=1.0)
    parser.add_argument("--loss", default="bce",
                        choices=("bce", "dice", "bce_dice"),
                        help="training objective: bce = reference parity "
                        "(train.py:160-162); dice = soft form of the "
                        "reference's eval metric; bce_dice = their sum")
    parser.add_argument("--synthetic", action="store_true",
                        help="train on synthetic ellipse-segmentation data")
    parser.add_argument("--train_samples", type=int, default=256)
    parser.add_argument("--image_size", type=int, default=64, help="synthetic image size")
    parser.add_argument("--volumetric", action="store_true",
                        help="3-D UNet on [D,H,W,1] volumes (BASELINE.md config #5; "
                        "synthetic ellipsoid data — the reference is 2-D only)")
    parser.add_argument("--remat", action="store_true",
                        help="checkpoint each DoubleConv (recompute in backward) — "
                        "the 3-D-volume memory recipe with --dtype bfloat16")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    from deeplearning_mpi_tpu.utils import config

    topo, mesh = config.setup_runtime(args)

    from deeplearning_mpi_tpu.train.resilience import preflight

    preflight(
        data_dir=None if (args.synthetic or args.volumetric) else args.data_dir,
        model_dir=args.model_dir, log_dir=args.log_dir,
        global_batch_size=args.batch_size, mesh=mesh,
        grad_accum=args.grad_accum,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning_mpi_tpu.data import (
        SegmentationFolderDataset,
        ShardedLoader,
        SyntheticShapesDataset,
    )
    from deeplearning_mpi_tpu.models import UNet
    from deeplearning_mpi_tpu.train import Checkpointer, Trainer, create_train_state
    from deeplearning_mpi_tpu.train.trainer import build_optimizer
    from deeplearning_mpi_tpu.utils.logging import RunLogger

    logger = RunLogger(args.log_dir)
    logger.log_system_information()
    logger.log_hyperparameters(vars(args))

    if args.volumetric:
        from deeplearning_mpi_tpu.data.segmentation import SyntheticVolumesDataset

        full = SyntheticVolumesDataset(
            args.train_samples, size=args.image_size, seed=args.random_seed
        )
        sample_hw = (args.image_size,) * 3
    elif args.synthetic:
        full = SyntheticShapesDataset(
            args.train_samples, size=args.image_size, seed=args.random_seed
        )
        sample_hw = (args.image_size, args.image_size)
    else:
        full = SegmentationFolderDataset(
            f"{args.data_dir}/images", f"{args.data_dir}/masks",
            scale=args.scale, mask_suffix=args.mask_suffix,
        )
        sample_hw = full[0]["image"].shape[:2]

    # 80/20 split, same permutation on every process (train.py:86-88 uses
    # random_split under a shared seed for the same effect).
    order = np.random.default_rng(args.random_seed).permutation(len(full))
    n_val = max(int(len(full) * args.val_fraction), 1)
    train_idx, val_idx = order[n_val:], order[:n_val]

    class _Subset:
        def __init__(self, indices):
            self.indices = indices

        def __len__(self):
            return len(self.indices)

        def __getitem__(self, i):
            return full[int(self.indices[i])]

    train_loader = ShardedLoader(
        _Subset(train_idx), args.batch_size, mesh, shuffle=True, seed=args.random_seed,
        num_workers=args.num_workers,
    )
    # drop_last=False: small validation sets wrap-pad to one full batch, so
    # the batch stays divisible by the mesh's data-parallel degree.
    eval_loader = ShardedLoader(
        _Subset(val_idx), args.batch_size, mesh, shuffle=False, drop_last=False,
        num_workers=args.num_workers,
    )

    channels = 1 if args.volumetric else 3
    model = UNet(
        out_classes=1, bilinear=args.bilinear,
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        spatial_dims=3 if args.volumetric else 2,
        remat=args.remat,
        reference_topology=args.reference_topology,
    )
    tx = build_optimizer(args.optimizer, config.build_lr(args, train_loader),
                         weight_decay=args.weight_decay, clip_norm=args.clip_norm)

    def state_factory():
        return create_train_state(
            model, jax.random.key(args.random_seed),
            jnp.zeros((1, *sample_hw, channels)), tx,
            mesh=mesh, zero=args.zero, ema=args.ema > 0,
        )

    state = state_factory()

    # Chaos harness (None unless --chaos/$DMT_CHAOS): one injector spans
    # checkpointer, loader, and trainer (docs/RESILIENCE.md).
    chaos = config.build_chaos(args)

    checkpointer = Checkpointer(
        f"{args.model_dir}/{args.model_filename}",
        max_to_keep=args.keep_checkpoints, chaos=chaos,
    )
    # restore_for_start can SystemExit (--eval_only with no checkpoint); it
    # must do so inside the try or the other hosts hang at their next
    # collective (bootstrap.shutdown never runs) and orbax threads leak.
    try:
        state, start_epoch = config.restore_for_start(args, checkpointer, state, logger)
        trainer = Trainer(
            state, "segmentation", mesh,
            logger=logger, checkpointer=checkpointer, eval_every=args.eval_every,
            grad_accum=args.grad_accum, zero=args.zero, seg_loss=args.loss,
            ema_decay=args.ema, chaos=chaos,
            guardrails=config.build_guardrails(args),
        )
        trainer.place_state()  # replicate (dp) or TP-shard (--tp > 1)
        if chaos is not None:
            from deeplearning_mpi_tpu.resilience import ResilientLoader

            chaos.bind_registry(trainer.metrics)
            train_loader = ResilientLoader(
                train_loader, chaos=chaos, logger=logger
            )
        # Analytic train FLOPs → MFU. Non-square folder images collapse to
        # the voxel-preserving equivalent square/cube edge (conv FLOPs scale
        # with voxel count, so the estimate is exact up to boundary effects).
        from deeplearning_mpi_tpu.telemetry.flops import unet_train_flops

        dim = 3 if args.volumetric else 2
        voxels = 1.0
        for s in sample_hw:
            voxels *= float(s)
        config.build_observability(
            args, trainer,
            flops_per_step=unet_train_flops(
                args.batch_size, voxels ** (1.0 / dim),
                in_channels=channels, out_channels=1, dim=dim,
            ),
        )
        config.execute_training(
            trainer, checkpointer, args, train_loader, eval_loader, start_epoch,
            state_factory=state_factory,
        )
    finally:
        checkpointer.close()
        from deeplearning_mpi_tpu.runtime import bootstrap
        bootstrap.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
