"""CLI for the distributed smoke test.

Usage::

    python -m deeplearning_mpi_tpu.cli.hello_world [--platform cpu|tpu]
        [--n_virtual_devices N] [--coordinator ADDR --num_processes W --process_id R]

Replaces the reference's interactive launcher + driver pair
(``pytorch/hello_world/run.sh:1-19`` prompting for topology, then torchrun
spawning ``hello_world.py``). ``--platform cpu`` is the Gloo-parity path
(``pytorch/hello_world/hello_world.py:44``): with ``--n_virtual_devices N`` it
fakes an N-device mesh on CPU, the hardware-free way to exercise the full
SPMD transport stack.
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--platform",
        default=None,
        choices=("cpu", "tpu"),
        help="force JAX platform; cpu is the reference's gloo-style fallback "
        "(hello_world.py:44)",
    )
    parser.add_argument(
        "--n_virtual_devices",
        type=int,
        default=None,
        help="with --platform cpu: fake this many CPU devices "
        "(XLA_FLAGS=--xla_force_host_platform_device_count)",
    )
    parser.add_argument("--coordinator", default=None, help="coordinator addr:port (multi-host)")
    parser.add_argument("--num_processes", type=int, default=None)
    parser.add_argument("--process_id", type=int, default=None)
    args = parser.parse_args(argv)

    # Deferred: platform/XLA flags must be set before backend init.
    from deeplearning_mpi_tpu.runtime import bootstrap
    from deeplearning_mpi_tpu.runtime.hello_world import run_hello_world

    if args.n_virtual_devices:
        bootstrap.set_virtual_cpu_devices(args.n_virtual_devices)
        args.platform = "cpu"

    topo = bootstrap.init(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        platform=args.platform,
    )
    print(
        f"[process {topo.process_id}/{topo.num_processes}] platform={topo.platform} "
        f"local_devices={topo.local_device_count} global_devices={topo.global_device_count}"
    )
    try:
        result = run_hello_world()
        status = "OK" if result.ok else "FAILED"
        print(
            f"hello_world {status}: n_devices={result.n_devices} "
            f"broadcast={'ok' if result.broadcast_ok else 'FAIL'} "
            f"ring={'ok' if result.ring_ok else 'FAIL'} "
            f"psum={'ok' if result.psum_ok else 'FAIL'}"
        )
        return 0 if result.ok else 1
    finally:
        bootstrap.shutdown()


if __name__ == "__main__":
    sys.exit(main())
