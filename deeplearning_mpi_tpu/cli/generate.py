"""Text generation from a trained LM checkpoint.

Completes the LM workflow the trainer starts: ``dmt-train-lm`` writes orbax
checkpoints; this CLI restores one and decodes from it with the KV-cached
single-token decode path (``models/generate.py`` — jitted scan, no Python
token loop). Byte-level vocab (256) in and out, matching
``data/lm_text.ByteTextDataset``.

The reference has no inference entrypoint at all (its workflow ends at
checkpoint files, ``pytorch/resnet/main.py:136-142``); this is the
beyond-parity completion of the LM model family.

Model-shape flags must match the training run — the checkpoint stores
arrays, not architecture (same contract as the reference's ``--resume``,
which also rebuilds the model from flags before loading weights,
``pytorch/resnet/main.py:36-52``).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dmt-generate",
        description="Generate text from a dmt-train-lm checkpoint.",
    )
    from deeplearning_mpi_tpu.utils import config

    # Shared definition with dmt-train-lm keeps the defaults byte-identical;
    # --seq_len is accepted for flag-compatibility but unused here (params
    # are sequence-independent — RoPE, no position table).
    model = config.add_lm_model_flags(parser)
    model.title = "model (MUST match the training run — the checkpoint stores arrays, not architecture)"
    model.add_argument("--dtype", default="float32",
                       choices=("float32", "bfloat16"),
                       help="compute dtype; match the training run "
                       "(dmt-train-lm default: float32)")
    parser.add_argument("--model_dir", default="saved_models")
    parser.add_argument("--model_filename", default="lm")
    parser.add_argument("--ema", type=config.ema_decay, default=0.0,
                        help="set to the training run's --ema decay when "
                        "serving an EMA-trained checkpoint: shapes the "
                        "restore template to include the EMA subtree and "
                        "decodes from the AVERAGED weights (the decay value "
                        "itself is unused at inference; nonzero = on)")
    parser.add_argument("--optimizer", default="adam",
                        choices=("sgd", "adam", "adamw", "adafactor", "lion"),
                        help="accepted for backward compatibility and "
                        "IGNORED: restore is params-only (the optimizer "
                        "state is never read), so serving no longer depends "
                        "on the training run's optimizer family or "
                        "hyperparameters")
    parser.add_argument("--epoch", type=int, default=None,
                        help="checkpoint epoch to load (default: latest)")
    gen = parser.add_argument_group("generation")
    gen.add_argument("--prompt", default="",
                     help="UTF-8 prompt text (byte tokens); empty = BOS-free "
                     "unconditional generation from byte 0")
    gen.add_argument("--prompts_file", default=None,
                     help="file with ONE prompt per line: the whole batch "
                     "decodes in a single jitted program (prompts "
                     "right-padded to the longest; each row switches from "
                     "prompt to samples at its own length). Sampling only "
                     "(--num_beams is single-prompt); one output line per "
                     "prompt")
    gen.add_argument("--max_new_tokens", type=int, default=128)
    gen.add_argument("--temperature", type=float, default=1.0)
    gen.add_argument("--top_k", type=int, default=0,
                     help="0 = full softmax; N>0 = top-N sampling")
    gen.add_argument("--top_p", type=float, default=1.0,
                     help="nucleus sampling: restrict to the smallest token "
                     "set whose probability mass reaches P (1.0 = off; "
                     "composes with --top_k)")
    gen.add_argument("--greedy", action="store_true",
                     help="argmax decoding (temperature ignored)")
    gen.add_argument("--num_beams", type=int, default=1,
                     help="N>1 = beam search over N beams (deterministic; "
                     "sampling flags ignored). Cost: the forward runs at "
                     "batch*N and each step gathers the beam cache")
    gen.add_argument("--eos_id", type=int, default=-1,
                     help="byte value that terminates generation (e.g. 10 "
                     "= newline for line-structured text); -1 = off. Rows/"
                     "beams that emit it are EOS-padded to the full length")
    gen.add_argument("--length_penalty", type=float, default=0.0,
                     help="beam ranking: score / len^alpha, len = generated "
                     "tokens through the first EOS. Needs --eos_id (without "
                     "EOS all beams are the same length and a normalizer "
                     "cannot change the ranking — rejected, not ignored)")
    gen.add_argument("--random_seed", type=int, default=0)
    gen.add_argument("--quantize", default="none", choices=("none", "int8"),
                     help="int8 = weight-only quantized decode: the block "
                     "matmul kernels are converted to int8 + per-channel "
                     "scales after restore (checkpoints stay full-precision)"
                     " — halves parameter HBM reads per token vs bfloat16")
    gen.add_argument("--time", action="store_true",
                     help="print serving throughput to stderr (runs each "
                     "phase twice: an untimed compile pass, then a timed "
                     "pass on the cached executable). Sampling with "
                     "uniform prompts reports the honest prefill/decode "
                     "split (prefill tokens/s is the batched cache-fill "
                     "forward; decode tokens/s counts ONLY generated "
                     "tokens); beam/ragged paths report whole-program "
                     "positions/s")
    run = parser.add_argument_group("runtime")
    run.add_argument("--platform", default=None, choices=("cpu", "tpu"))
    run.add_argument("--n_virtual_devices", type=int, default=None)
    run.add_argument("--tp", type=int, default=1,
                     help="tensor-parallel degree for inference: params "
                     "(and the matmuls) shard over a model axis of this "
                     "size — serve a checkpoint too big for one device's "
                     "HBM with the same megatron rules training uses")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.quantize == "int8" and (args.tp > 1 or args.moe_experts > 0):
        # Untested compositions fail loud rather than run wrong — and BEFORE
        # the init + restore they would otherwise pay for: sharded
        # conversion (--tp) and routed-MoE kernels are future work.
        print(
            "--quantize int8 supports single-device dense models "
            "(not --tp or --moe_experts yet)",
            file=sys.stderr,
        )
        return 1
    # Pure-argv checks belong HERE, before the minutes-long init + restore
    # (same fail-fast rule as above).
    eos_id = args.eos_id if args.eos_id >= 0 else None
    if eos_id is not None and eos_id > 255:
        print(
            f"--eos_id {eos_id} is outside the byte vocab (0-255) — it "
            "could never be emitted, silently disabling stopping",
            file=sys.stderr,
        )
        return 1
    if args.length_penalty != 0.0 and eos_id is None:
        print(
            "--length_penalty requires --eos_id: without EOS every beam "
            "has the same length and the penalty cannot change the ranking",
            file=sys.stderr,
        )
        return 1
    if args.length_penalty != 0.0 and args.num_beams <= 1:
        print("--length_penalty only applies to --num_beams > 1",
              file=sys.stderr)
        return 1
    if args.prompts_file and args.prompt:
        print("--prompt and --prompts_file are mutually exclusive",
              file=sys.stderr)
        return 1
    if args.prompts_file and args.num_beams > 1:
        print("--prompts_file batches the sampling path; --num_beams is "
              "single-prompt", file=sys.stderr)
        return 1
    from pathlib import Path  # stdlib — no deferred-import rationale applies

    prompt_texts = None
    if args.prompts_file:
        try:
            raw = Path(args.prompts_file).read_text(encoding="utf-8")
        except OSError as e:
            print(f"cannot read --prompts_file: {e}", file=sys.stderr)
            return 1
        lines = raw.splitlines()
        # Reject blank interior lines instead of dropping them: output is
        # documented as one line per input line, and silently skipping a
        # blank would misalign every following completion with its prompt.
        blank = [n for n, ln in enumerate(lines, 1) if not ln.strip()]
        if blank:
            print(
                f"{args.prompts_file}: blank prompt line(s) {blank[:5]} — "
                "every line must be a prompt (one output line per input "
                "line)",
                file=sys.stderr,
            )
            return 1
        if not lines:
            print(f"{args.prompts_file} has no prompts", file=sys.stderr)
            return 1
        prompt_texts = lines

    from deeplearning_mpi_tpu.runtime import bootstrap

    if args.n_virtual_devices:
        bootstrap.set_virtual_cpu_devices(args.n_virtual_devices)
    elif args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    import optax

    from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
    from deeplearning_mpi_tpu.models.generate import generate_jit
    from deeplearning_mpi_tpu.train import Checkpointer, create_train_state

    # Fail BEFORE the (potentially minutes-long) model/optimizer init, and
    # without Checkpointer's create=True side-effect mkdir on a typo'd path.
    ckpt_dir = Path(args.model_dir) / args.model_filename
    if not ckpt_dir.is_dir():
        print(f"no checkpoint found under {ckpt_dir}", file=sys.stderr)
        return 1
    mesh = None
    if args.tp > 1:
        # Mesh + device check up front (same fail-fast rule as the ckpt_dir
        # check above): a too-large --tp must not cost the user the full
        # init + restore first.
        from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

        if len(jax.devices()) < args.tp:
            print(
                f"--tp {args.tp} needs {args.tp} devices, have "
                f"{len(jax.devices())}",
                file=sys.stderr,
            )
            return 1
        mesh = create_mesh(
            MeshSpec(data=1, model=args.tp), devices=jax.devices()[:args.tp]
        )

    cfg = TransformerConfig(
        vocab_size=256,
        num_layers=args.num_layers,
        num_heads=args.num_heads,
        num_kv_heads=args.num_kv_heads or None,
        head_dim=args.head_dim,
        d_model=args.d_model,
        d_ff=args.d_ff,
        moe_experts=args.moe_experts,
        moe_top_k=args.moe_top_k,
        moe_routing=args.moe_routing,
        attention_window=args.attention_window,
    )
    from deeplearning_mpi_tpu.utils import config

    # Shape-changing mistakes fail at restore anyway; this catches the
    # TREE-INVISIBLE ones (--attention_window, --moe_routing) that would
    # otherwise silently decode with different semantics than the
    # checkpoint was trained with.
    err = config.arch_mismatch_error(cfg, ckpt_dir)
    if err:
        print(err, file=sys.stderr)
        return 1
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model = TransformerLM(config=cfg, dtype=dtype)
    # optax.identity(): restore is params-only (the checkpoint's opt_state
    # bytes are never read), so the template needs no real optimizer — any
    # family/hyperparameter combination at training time serves unchanged,
    # and no moment memory is ever initialized. The dummy input is short on
    # purpose: params are sequence-independent (RoPE, no position table),
    # and a full --seq_len dense init would do O(S^2) work — fatal for
    # long-context checkpoints.
    template = create_train_state(
        model, jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
        optax.identity(),
        ema=args.ema > 0,
    )
    if mesh is not None:
        # Shard the TEMPLATE (training's megatron rules, via the same
        # shard_state helper): orbax restores each array directly into the
        # template's sharding, so the checkpoint is born sharded — never
        # materialized replicated on one device first, which is the whole
        # point of serving with --tp. The decode scan's cache/activations
        # pick up their shardings from GSPMD propagation.
        from deeplearning_mpi_tpu.parallel import shard_state

        template = shard_state(template, mesh)
    ckpt = Checkpointer(ckpt_dir)
    try:
        state = ckpt.restore_params_only(template, epoch=args.epoch)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001 — orbax raises its own types for
        # a bad --epoch or a template/checkpoint tree mismatch; one clean
        # line beats a multi-frame traceback for a CLI.
        print(
            f"failed to restore from {ckpt.directory}"
            + (f" epoch {args.epoch}" if args.epoch is not None else "")
            + f": {e}",
            file=sys.stderr,
        )
        return 1
    finally:
        ckpt.close()

    # The averaged weights are what EMA exists to serve (same preference as
    # the trainers' eval path, TrainState.eval_variables).
    params = state.params if state.ema_params is None else state.ema_params
    if args.quantize == "int8":
        import dataclasses

        from deeplearning_mpi_tpu.ops.quant import quantize_lm_params

        params = quantize_lm_params(params)
        model = dataclasses.replace(model, quantized=True)

    shared_prefix = 0
    if prompt_texts is not None:
        rows = [
            np.frombuffer(t.encode("utf-8") or b"\x00", np.uint8).astype(
                np.int32
            )
            for t in prompt_texts
        ]
        lens = np.array([len(r) for r in rows], np.int32)
        padded = np.zeros((len(rows), int(lens.max())), np.int32)
        for b, r in enumerate(rows):
            padded[b, : len(r)] = r
        prompt = jnp.asarray(padded)
        if int(lens.min()) == int(lens.max()):
            # Uniform batch in disguise: take the full two-phase fast path
            # (batched prefill + decode-only scan) instead of the ragged
            # per-row-switch scan.
            prompt_lens = None
        else:
            prompt_lens = jnp.asarray(lens)
            # The lengths are host-side knowledge: the shared prefix
            # prefills in one batched forward; only the ragged tail pays
            # sequential steps.
            shared_prefix = int(lens.min())
    else:
        prompt_bytes = args.prompt.encode("utf-8") or b"\x00"
        prompt = jnp.asarray(
            np.frombuffer(prompt_bytes, np.uint8).astype(np.int32)
        )[None, :]
        prompt_lens = None

    if args.num_beams > 1:
        from deeplearning_mpi_tpu.models.generate import beam_search_jit

        beam_fn = beam_search_jit(
            model,
            max_new_tokens=args.max_new_tokens,
            num_beams=args.num_beams,
            eos_id=eos_id,
            length_penalty=args.length_penalty,
        )

        def call():
            return beam_fn(params, prompt)
    else:
        fn = generate_jit(
            model,
            max_new_tokens=args.max_new_tokens,
            temperature=0.0 if args.greedy else args.temperature,
            top_k=0 if args.greedy else args.top_k,
            top_p=1.0 if args.greedy else args.top_p,
            eos_id=eos_id,
            shared_prefix=shared_prefix,
        )
        rng = jax.random.key(args.random_seed)

        def call():
            return fn(params, prompt, rng, prompt_lens)

    timed_split = (
        args.time and args.num_beams == 1 and prompt_lens is None
        and args.max_new_tokens >= 2
    )
    if (
        args.time and args.num_beams == 1 and prompt_lens is None
        and not timed_split
    ):
        # The phase-split path decodes max_new - 1 model steps after the
        # prefill sample: at 0 it would crash in decode_tokens (steps >= 1)
        # and at 1 there IS no decode phase — a "decode tokens/s" over zero
        # steps is noise, not a measurement.
        print(
            "--time needs --max_new_tokens >= 2 for the prefill/decode "
            "split (the first token comes from prefill; the decode phase "
            f"would run {max(args.max_new_tokens - 1, 0)} steps) — "
            "running untimed",
            file=sys.stderr,
        )
    if timed_split:
        # Honest split timing: phase-separate jits so prefill (one batched
        # MXU-bound forward over the prompt) and decode (the HBM-bound
        # per-token cache walk, generated tokens ONLY) each get their own
        # number — one fused program would re-conflate them into the
        # "positions/s" figure the round-4 review called flattered. The
        # rng handling mirrors generate()'s fast path exactly, so the
        # emitted text equals the untimed run's.
        import time

        from deeplearning_mpi_tpu.models.generate import (
            decode_tokens,
            first_token,
            prefill,
        )
        from deeplearning_mpi_tpu.utils.profiling import host_sync

        p_len = prompt.shape[1]
        total = p_len + args.max_new_tokens
        temperature = 0.0 if args.greedy else args.temperature
        top_k = 0 if args.greedy else args.top_k
        top_p = 1.0 if args.greedy else args.top_p

        @jax.jit
        def run_prefill(params, prompt):
            return prefill(model, params, prompt, total_len=total)

        @jax.jit
        def run_decode(params, cache, first, rng, done):
            return decode_tokens(
                model, params, cache, first,
                start=p_len, steps=args.max_new_tokens, rng=rng,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=eos_id, done=done,
            )

        def measure(thunk, sync_of):
            # host_sync, not block_until_ready: the latter can return
            # before remote execution finishes on the tunneled TPU.
            host_sync(sync_of(thunk()).ravel()[:1])  # compile + warm
            t0 = time.perf_counter()
            r = thunk()
            host_sync(sync_of(r).ravel()[:1])
            return r, time.perf_counter() - t0

        (cache, logits), dt_pre = measure(
            lambda: run_prefill(params, prompt), lambda r: r[1]
        )
        # first_token is the SHARED seed step with generate()'s fast path
        # — same rng split order, same EOS done-seed — so the timed run
        # emits exactly the untimed run's text.
        first, done, rng = first_token(
            logits, jax.random.key(args.random_seed),
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id,
        )
        new, dt_dec = measure(
            lambda: run_decode(params, cache, first, rng, done), lambda r: r
        )
        out = jnp.concatenate([prompt, new], axis=1)
        batch = prompt.shape[0]
        # The decode phase executed max_new - 1 model steps (the first
        # generated token came from the prefill logits) — the rate divides
        # by what actually ran, not the tokens returned.
        dec_steps = max(args.max_new_tokens - 1, 1)
        print(
            f"prefill: {batch * p_len} tokens in {dt_pre:.3f}s = "
            f"{batch * p_len / dt_pre:.1f} tokens/s | decode: "
            f"{batch * dec_steps} steps in {dt_dec:.3f}s = "
            f"{batch * dec_steps / dt_dec:.1f} tokens/s",
            file=sys.stderr,
        )
    else:
        out = call()
    if args.time and (args.num_beams > 1 or prompt_lens is not None):
        import time

        from deeplearning_mpi_tpu.utils.profiling import host_sync

        # host_sync, not block_until_ready: the latter can return before
        # remote execution finishes on the tunneled TPU (host_sync docs).
        host_sync(out.ravel()[:1])  # first call compiled; time the cache hit
        t0 = time.perf_counter()
        out = call()
        host_sync(out.ravel()[:1])
        dt = time.perf_counter() - t0
        # The beam/ragged program mixes one batched prefill (beam: the
        # whole prompt; ragged: the shared prefix) with sequential scan
        # steps; count ONLY the scan positions so the rate isn't prefill-
        # flattered (the round-4 verdict's complaint about the old blended
        # metric). Batch mode scans all rows in one program: count all.
        # --num_beams and --prompts_file are mutually exclusive (checked up
        # front): the beam program prefills the whole prompt, the ragged
        # program the shared prefix.
        scan_start = prompt.shape[1] if args.num_beams > 1 else shared_prefix
        positions = out.shape[0] * (
            prompt.shape[1] + args.max_new_tokens - scan_start
        )
        print(
            f"scan: {positions} sequential positions "
            f"({args.max_new_tokens} new; {scan_start} prefix positions "
            f"prefilled in one batched forward) in {dt:.3f}s = "
            f"{positions / dt:.1f} positions/s",
            file=sys.stderr,
        )
    if prompt_texts is not None:
        # One line per prompt. Short rows keep generating to the end of the
        # static window; slice each at its own len + max_new so every
        # prompt gets exactly max_new_tokens of continuation. `lens` is the
        # host-side array — prompt_lens is None on the uniform fast path.
        for b in range(out.shape[0]):
            row = np.asarray(
                out[b, : int(lens[b]) + args.max_new_tokens], np.uint8
            )
            print(row.tobytes().decode("utf-8", errors="replace"))
    else:
        tokens = np.asarray(out[0], np.uint8)
        text = tokens.tobytes().decode("utf-8", errors="replace")
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
