"""Dataset fetch tool — the out-of-band prefetch step.

TPU-native equivalent of ``pytorch/resnet/download.py:1-19``: the reference
downloads CIFAR-10 *before* launching distributed training because an in-job
download "is not multiprocess safe" (``pytorch/resnet/main.py:90``). Same
contract here: run this once per host (or once on a shared filesystem), then
launch training with ``--data_dir`` pointing at the result.

Two dataset layouts:

- ``cifar10`` — fetches ``cifar-10-python.tar.gz`` (md5-verified), extracts
  the standard ``cifar-10-batches-py`` pickle directory that
  :class:`~deeplearning_mpi_tpu.data.cifar10.CIFAR10` reads.
- ``carvana`` — Carvana-style segmentation data requires Kaggle
  authentication, so it cannot be fetched anonymously (the reference has the
  same gap: its dataset doc tells the user to place files by hand,
  ``pytorch/unet/data/README.md``). This command scaffolds the expected
  ``images/`` + ``masks/`` layout and validates any data already present
  (every image paired with exactly one mask, matching sizes — the checks
  ``data_loading.py:112-118`` makes at load time, surfaced at fetch time).

``--check`` validates an existing directory without touching the network —
the mode that works on air-gapped machines (like this build box, which has
zero egress; downloads fail fast with a clear message instead of hanging).
"""

from __future__ import annotations

import argparse
import hashlib
import sys
import tarfile
import tempfile
import urllib.error
import urllib.request
from pathlib import Path

CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
_CIFAR_MEMBERS = [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]


def _md5(path: Path) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def check_cifar10(data_dir: Path) -> bool:
    """True iff the ``cifar-10-batches-py`` pickles are all present."""
    batch_dir = data_dir / "cifar-10-batches-py"
    missing = [m for m in _CIFAR_MEMBERS if not (batch_dir / m).is_file()]
    if missing:
        print(f"{batch_dir}: missing {missing}" if batch_dir.is_dir()
              else f"{batch_dir}: not found")
        return False
    print(f"{batch_dir}: complete ({len(_CIFAR_MEMBERS)} batch files)")
    return True


def fetch_cifar10(data_dir: Path, *, timeout: float = 30.0) -> int:
    """Download + verify + extract CIFAR-10; idempotent."""
    if check_cifar10(data_dir):
        return 0
    data_dir.mkdir(parents=True, exist_ok=True)
    print(f"fetching {CIFAR10_URL} ...")
    try:
        with tempfile.NamedTemporaryFile(suffix=".tar.gz", delete=False) as tmp:
            with urllib.request.urlopen(CIFAR10_URL, timeout=timeout) as r:
                while chunk := r.read(1 << 20):
                    tmp.write(chunk)
            tmp_path = Path(tmp.name)
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        print(
            f"download failed ({e!r}). This machine may have no network "
            "egress — fetch cifar-10-python.tar.gz on a connected machine "
            f"and extract it under {data_dir}, or train with --synthetic.",
            file=sys.stderr,
        )
        return 1
    try:
        digest = _md5(tmp_path)
        if digest != CIFAR10_MD5:
            print(f"md5 mismatch: got {digest}, want {CIFAR10_MD5}",
                  file=sys.stderr)
            return 1
        with tarfile.open(tmp_path, "r:gz") as tar:
            tar.extractall(data_dir, filter="data")
    finally:
        tmp_path.unlink(missing_ok=True)
    return 0 if check_cifar10(data_dir) else 1


def check_carvana(data_dir: Path, *, mask_suffix: str = "") -> bool:
    """Validate an images/ + masks/ segmentation layout.

    Every image must have exactly one mask named ``<stem><mask_suffix>.*``
    (the invariant ``SegmentationFolderDataset`` and the reference's
    ``BasicDataset.__getitem__`` assert at train time,
    ``pytorch/unet/data_loading.py:112-118``).
    """
    images, masks = data_dir / "images", data_dir / "masks"
    for d in (images, masks):
        if not d.is_dir():
            print(f"{d}: not found")
            return False
    image_stems = sorted(p.stem for p in images.iterdir() if p.is_file())
    if not image_stems:
        print(f"{images}: empty")
        return False
    mask_stems = {p.stem for p in masks.iterdir() if p.is_file()}
    unpaired = [s for s in image_stems if s + mask_suffix not in mask_stems]
    if unpaired:
        print(f"{len(unpaired)} image(s) without a mask, e.g. {unpaired[:3]}")
        return False
    print(f"{data_dir}: {len(image_stems)} image/mask pairs, all paired")
    return True


def scaffold_carvana(data_dir: Path) -> int:
    """Create the expected layout and print where to put the data."""
    for sub in ("images", "masks"):
        (data_dir / sub).mkdir(parents=True, exist_ok=True)
    print(
        f"created {data_dir}/images and {data_dir}/masks.\n"
        "Carvana-style data needs Kaggle auth and cannot be fetched "
        "anonymously:\n"
        "  kaggle competitions download -c carvana-image-masking-challenge\n"
        "Place images in images/ and masks in masks/ with matching stems, "
        "then re-run with --check."
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dmt-download",
        description="One-shot dataset prefetch, run before distributed "
        "training (parity: pytorch/resnet/download.py).",
    )
    ap.add_argument("dataset", choices=("cifar10", "carvana"))
    ap.add_argument("--data_dir", default="data", help="destination directory")
    ap.add_argument("--check", action="store_true",
                    help="validate existing data only; never touch the network")
    ap.add_argument("--mask_suffix", default="",
                    help="carvana: mask filename suffix after the image stem")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)
    data_dir = Path(args.data_dir)

    if args.dataset == "cifar10":
        if args.check:
            return 0 if check_cifar10(data_dir) else 1
        return fetch_cifar10(data_dir, timeout=args.timeout)
    if args.check:
        return 0 if check_carvana(data_dir, mask_suffix=args.mask_suffix) else 1
    if check_carvana(data_dir, mask_suffix=args.mask_suffix):
        return 0
    return scaffold_carvana(data_dir)


if __name__ == "__main__":
    sys.exit(main())
