"""Dataset fetch tool — the out-of-band prefetch step.

TPU-native equivalent of ``pytorch/resnet/download.py:1-19``: the reference
downloads CIFAR-10 *before* launching distributed training because an in-job
download "is not multiprocess safe" (``pytorch/resnet/main.py:90``). Same
contract here: run this once per host (or once on a shared filesystem), then
launch training with ``--data_dir`` pointing at the result.

Two dataset layouts:

- ``cifar10`` — fetches ``cifar-10-python.tar.gz`` (md5-verified), extracts
  the standard ``cifar-10-batches-py`` pickle directory that
  :class:`~deeplearning_mpi_tpu.data.cifar10.CIFAR10` reads.
- ``carvana`` — Carvana-style segmentation data requires Kaggle
  authentication, so it cannot be fetched anonymously (the reference has the
  same gap: its dataset doc tells the user to place files by hand,
  ``pytorch/unet/data/README.md``). This command scaffolds the expected
  ``images/`` + ``masks/`` layout and validates any data already present
  (every image paired with exactly one mask, matching sizes — the checks
  ``data_loading.py:112-118`` makes at load time, surfaced at fetch time).

``--check`` validates an existing directory without touching the network —
the mode that works on air-gapped machines (like this build box, which has
zero egress; downloads fail fast with a clear message instead of hanging).
"""

from __future__ import annotations

import argparse
import hashlib
import sys
import tarfile
import tempfile
import urllib.error
import urllib.request
from pathlib import Path

CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
_CIFAR_MEMBERS = [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]


def _md5(path: Path) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def check_cifar10(data_dir: Path) -> bool:
    """True iff the ``cifar-10-batches-py`` pickles are all present."""
    batch_dir = data_dir / "cifar-10-batches-py"
    missing = [m for m in _CIFAR_MEMBERS if not (batch_dir / m).is_file()]
    if missing:
        print(f"{batch_dir}: missing {missing}" if batch_dir.is_dir()
              else f"{batch_dir}: not found")
        return False
    print(f"{batch_dir}: complete ({len(_CIFAR_MEMBERS)} batch files)")
    return True


def _verify_and_extract(
    tarball: Path, data_dir: Path, *, md5: str | None
) -> int:
    """Shared verify+extract tail of the download and --from_file paths."""
    if md5 is not None:
        digest = _md5(tarball)
        if digest != md5:
            print(f"md5 mismatch: got {digest}, want {md5}", file=sys.stderr)
            return 1
    data_dir.mkdir(parents=True, exist_ok=True)
    with tarfile.open(tarball, "r:*") as tar:
        try:
            tar.extractall(data_dir, filter="data")
        except TypeError:  # filter= needs py>=3.10.12/3.11.4/3.12
            # Manual tar-slip guard for the no-filter fallback: the ingest
            # path can run UNVERIFIED (--md5 none), so members must be
            # checked before a bare extractall — names for traversal, and an
            # ALLOWLIST of member types. Deny-listing symlink/hardlink was
            # not enough: a device node or FIFO member extracts too (a FIFO
            # blocks the next read; a device node is worse run as root) —
            # CIFAR tarballs contain only regular files + dirs, so only
            # those pass.
            bad = [
                m.name for m in tar.getmembers()
                if m.name.startswith(("/", ".."))
                or ".." in Path(m.name).parts
                or not (m.isfile() or m.isdir())
            ]
            if bad:
                print(f"refusing unsafe tar members: {bad[:3]}",
                      file=sys.stderr)
                return 1
            tar.extractall(data_dir)  # noqa: S202 — members validated above
    return 0 if check_cifar10(data_dir) else 1


def ingest_cifar10(
    tarball: Path, data_dir: Path, *, md5: str | None = CIFAR10_MD5
) -> int:
    """Extract a user-supplied ``cifar-10-python.tar.gz`` — the offline path.

    An air-gapped machine (like this build box — zero egress, verified in
    BASELINE.md) can't run the download, but a user can carry the tarball
    in; this makes the real-data accuracy run one file-copy away instead of
    network-blocked (round-4 missing #1). Same md5 verification and
    post-extract layout as :func:`fetch_cifar10`; ``md5=None`` skips the
    check for custom subsets (``--md5 none``).
    """
    if not tarball.is_file():
        print(f"{tarball}: not a file", file=sys.stderr)
        return 1
    return _verify_and_extract(tarball, data_dir, md5=md5)


def fetch_cifar10(data_dir: Path, *, timeout: float = 30.0) -> int:
    """Download + verify + extract CIFAR-10; idempotent."""
    if check_cifar10(data_dir):
        return 0
    data_dir.mkdir(parents=True, exist_ok=True)
    print(f"fetching {CIFAR10_URL} ...")
    with tempfile.NamedTemporaryFile(suffix=".tar.gz", delete=False) as tmp:
        tmp_path = Path(tmp.name)
    try:
        try:
            with open(tmp_path, "wb") as f, urllib.request.urlopen(
                CIFAR10_URL, timeout=timeout
            ) as r:
                while chunk := r.read(1 << 20):
                    f.write(chunk)
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            print(
                f"download failed ({e!r}). This machine may have no network "
                "egress — fetch cifar-10-python.tar.gz on a connected machine "
                f"and ingest it with --from_file, or train with --synthetic.",
                file=sys.stderr,
            )
            return 1
        return _verify_and_extract(tmp_path, data_dir, md5=CIFAR10_MD5)
    finally:
        tmp_path.unlink(missing_ok=True)


def check_carvana(data_dir: Path, *, mask_suffix: str = "") -> bool:
    """Validate an images/ + masks/ segmentation layout.

    Every image must have exactly one mask named ``<stem><mask_suffix>.*``
    with matching pixel dimensions (the invariants
    ``SegmentationFolderDataset`` and the reference's
    ``BasicDataset.__getitem__`` assert at train time,
    ``pytorch/unet/data_loading.py:112-118``) — surfaced here at fetch time
    instead of mid-epoch.
    """
    images, masks = data_dir / "images", data_dir / "masks"
    for d in (images, masks):
        if not d.is_dir():
            print(f"{d}: not found")
            return False
    image_files = sorted(p for p in images.iterdir() if p.is_file())
    if not image_files:
        print(f"{images}: empty")
        return False
    mask_by_stem = {p.stem: p for p in masks.iterdir() if p.is_file()}
    unpaired, mismatched = [], []
    for img in image_files:
        mask = mask_by_stem.get(img.stem + mask_suffix)
        if mask is None:
            unpaired.append(img.stem)
            continue
        try:
            from PIL import Image

            with Image.open(img) as im, Image.open(mask) as mk:
                if im.size != mk.size:
                    mismatched.append(f"{img.stem} {im.size} vs {mk.size}")
        except OSError as e:
            mismatched.append(f"{img.stem} unreadable: {e}")
    if unpaired:
        print(f"{len(unpaired)} image(s) without a mask, e.g. {unpaired[:3]}")
        return False
    if mismatched:
        print(f"{len(mismatched)} image/mask size mismatch(es), "
              f"e.g. {mismatched[:3]}")
        return False
    print(f"{data_dir}: {len(image_files)} image/mask pairs, all paired, "
          "sizes match")
    return True


def scaffold_carvana(data_dir: Path) -> int:
    """Create the expected layout and print where to put the data."""
    for sub in ("images", "masks"):
        (data_dir / sub).mkdir(parents=True, exist_ok=True)
    print(
        f"created {data_dir}/images and {data_dir}/masks.\n"
        "Carvana-style data needs Kaggle auth and cannot be fetched "
        "anonymously:\n"
        "  kaggle competitions download -c carvana-image-masking-challenge\n"
        "Place images in images/ and masks in masks/ with matching stems, "
        "then re-run with --check."
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dmt-download",
        description="One-shot dataset prefetch, run before distributed "
        "training (parity: pytorch/resnet/download.py).",
    )
    ap.add_argument("dataset", choices=("cifar10", "carvana"))
    ap.add_argument("--data_dir", default="data", help="destination directory")
    ap.add_argument("--check", action="store_true",
                    help="validate existing data only; never touch the network")
    ap.add_argument("--from_file", default=None,
                    help="cifar10: ingest a user-supplied "
                    "cifar-10-python.tar.gz instead of downloading — the "
                    "offline path for air-gapped machines (md5-verified, "
                    "same post-extract layout)")
    ap.add_argument("--md5", default=CIFAR10_MD5,
                    help="expected md5 of --from_file ('none' to skip, for "
                    "custom subsets; default: the official CIFAR-10 digest)")
    ap.add_argument("--mask_suffix", default="",
                    help="carvana: mask filename suffix after the image stem")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)
    data_dir = Path(args.data_dir)

    if args.from_file and args.dataset != "cifar10":
        ap.error("--from_file applies to cifar10 only")
    if args.from_file and args.check:
        ap.error("--check validates existing data; it never reads "
                 "--from_file — drop one of the two")
    if args.md5 != CIFAR10_MD5 and not args.from_file:
        ap.error("--md5 only applies to --from_file (the download path "
                 "always verifies against the official digest)")
    if args.dataset == "cifar10":
        if args.check:
            return 0 if check_cifar10(data_dir) else 1
        if args.from_file:
            # lower(): hashlib prints lowercase; tools that print uppercase
            # digests must not fail verification on case alone.
            md5 = None if args.md5.lower() == "none" else args.md5.lower()
            return ingest_cifar10(Path(args.from_file), data_dir, md5=md5)
        return fetch_cifar10(data_dir, timeout=args.timeout)
    if args.check:
        return 0 if check_carvana(data_dir, mask_suffix=args.mask_suffix) else 1
    if check_carvana(data_dir, mask_suffix=args.mask_suffix):
        return 0
    return scaffold_carvana(data_dir)


if __name__ == "__main__":
    sys.exit(main())
