"""Non-interactive CLI entrypoints.

Replace the reference's interactive ``read -p`` bash launchers
(``pytorch/hello_world/run.sh:4-10``, ``pytorch/unet/run.sh:25-79``) with
flag-driven ``python -m`` entrypoints that work under any process launcher.
"""
