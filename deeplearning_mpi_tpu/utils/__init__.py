"""Cross-cutting utilities: run logging, config/flag system."""

from deeplearning_mpi_tpu.utils.logging import RunLogger  # noqa: F401
