"""Cross-cutting utilities: run logging, profiling, config/flag system."""

from deeplearning_mpi_tpu.utils.logging import RunLogger  # noqa: F401
from deeplearning_mpi_tpu.utils.profiling import (  # noqa: F401
    Profiler,
    StepTimer,
    measure_collective_latency,
    nan_debug_mode,
)
