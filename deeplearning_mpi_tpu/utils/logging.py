"""Run logging: timestamped log files + stdout, process-0 gated.

Parity with the reference's file logger (``create_log_file`` /
``log_to_file``, ``pytorch/unet/train.py:44-57``): one
``logs/training_log_%Y%m%d_%H%M%S.log`` per run, hyperparameters and system
info recorded at startup (``train.py:356-360``), per-epoch metrics appended.
Non-coordinator processes log nothing, like the reference's rank-0 gating
(``train.py:208``).
"""

from __future__ import annotations

import datetime
import json
from pathlib import Path
from typing import Any, Mapping

import jax

from deeplearning_mpi_tpu.runtime.bootstrap import get_system_information


class RunLogger:
    """Print + append-to-file logger, active only on process 0."""

    def __init__(
        self,
        log_dir: str | Path | None = None,
        *,
        echo: bool = True,
        run_name: str | None = None,
    ) -> None:
        self.echo = echo
        self.enabled = jax.process_index() == 0
        self.path: Path | None = None
        if self.enabled and log_dir is not None:
            stamp = datetime.datetime.now().strftime("%Y%m%d_%H%M%S")
            name = run_name or f"training_log_{stamp}"
            log_dir = Path(log_dir)
            log_dir.mkdir(parents=True, exist_ok=True)
            self.path = log_dir / f"{name}.log"
            self.path.touch()

    def log(self, message: str) -> None:
        if not self.enabled:
            return
        stamp = datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S")
        line = f"[{stamp}] {message}"
        if self.echo:
            print(line, flush=True)
        if self.path is not None:
            with self.path.open("a") as f:
                f.write(line + "\n")

    def log_metrics(self, record: Mapping[str, Any]) -> None:
        """Append one structured metrics record to ``<run>.metrics.jsonl``.

        The machine-readable sidecar of the human log: one JSON object per
        line (timestamped), so plotting/analysis never parses the prose log.
        The reference has no structured metrics at all (prose log only,
        ``pytorch/unet/train.py:44-57``).
        """
        if not self.enabled or self.path is None:
            return
        # Records from telemetry.MetricsRegistry already carry a canonical
        # numeric "ts"; stamp only bare records so the two never disagree.
        line = dict(record)
        if "ts" not in line:
            line["ts"] = datetime.datetime.now().isoformat(timespec="seconds")
        with self.path.with_suffix(".metrics.jsonl").open("a") as f:
            f.write(json.dumps(line, default=float) + "\n")

    def log_hyperparameters(self, params: Mapping[str, Any]) -> None:
        """Startup block parity: hyperparams + world info (train.py:356-360)."""
        self.log("hyperparameters: " + json.dumps(dict(params), default=str))

    def log_system_information(self) -> None:
        self.log("system: " + json.dumps(get_system_information()))
