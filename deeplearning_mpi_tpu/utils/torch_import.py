"""Import the reference's PyTorch ``.pth`` checkpoints into this framework.

The reference saves raw DDP state_dicts — ``torch.save(ddp_model.
state_dict(), path)`` every 10 epochs (``pytorch/resnet/main.py:139``,
``pytorch/unet/train.py:216``) — so a user migrating from it arrives with
``.pth`` files whose keys carry DDP's ``module.`` prefix. This module
converts those serialized trees into this framework's Flax variables
(``params`` + ``batch_stats``), handling the layout differences:

- torch ``Conv2d`` weights are OIHW; Flax kernels are HWIO.
- torch ``ConvTranspose2d`` weights are (in, out, kH, kW); Flax
  ``nn.ConvTranspose`` kernels are (kH, kW, in, out).
- torch ``Linear`` weights are (out, in); Flax ``Dense`` kernels are
  (in, out).
- torch BatchNorm splits into params (weight→scale, bias→bias) and
  running stats (running_mean→mean, running_var→var).
- The reference's 3×3 convs keep torch's default ``bias=True`` even though
  BatchNorm follows (``pytorch/unet/model.py:9-13``); our convs are
  bias-free there, so the bias is *folded into the BN running mean*:
  BN(Wx + b) with stats (m, v) equals BN'(Wx) with stats (m − b, v) — an
  exact transform, not an approximation.

Only the UNet import needs the bias fold; torchvision ResNets use
bias-free convs. UNet checkpoints restore into
``UNet(reference_topology=True)`` — the reference's decoder keeps channels
through the upsample and reduces in DoubleConv (``model.py:37-38,63-66``),
which is a different param-shape contract than our default decoder.

torch is imported lazily: it is only needed when actually reading a
``.pth`` file, and the rest of the framework must not pay its import cost.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

Tree = dict[str, Any]


def strip_ddp_prefix(state_dict: Mapping[str, Any]) -> dict[str, Any]:
    """Drop the ``module.`` prefix DDP adds to every key.

    The reference saves the *wrapped* model's state_dict, so its files
    always carry the prefix (SURVEY.md §5.4); a plain model's dict passes
    through unchanged. Mixed dicts are rejected — that indicates a file
    this converter does not understand.
    """
    keys = list(state_dict)
    prefixed = [k.startswith("module.") for k in keys]
    if all(prefixed):
        return {k[len("module."):]: v for k, v in state_dict.items()}
    if any(prefixed):
        bad = [k for k, p in zip(keys, prefixed) if not p][:3]
        raise ValueError(
            f"state_dict mixes DDP-prefixed and bare keys (e.g. {bad}); "
            "refusing to guess"
        )
    return dict(state_dict)


def _np(t: Any) -> np.ndarray:
    """torch tensor (or array-like) → float32 numpy without importing torch."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _conv_kernel(w: Any) -> np.ndarray:
    """OIHW → HWIO."""
    return _np(w).transpose(2, 3, 1, 0)


def _conv_transpose_kernel(w: Any) -> np.ndarray:
    """torch ConvTranspose2d (in, out, kH, kW) → Flax (kH, kW, in, out).

    Flax's ``nn.ConvTranspose`` (``lax.conv_transpose`` with
    ``transpose_kernel=False``) correlates the *unflipped* kernel with the
    stride-dilated input, while torch's ConvTranspose2d is the gradient of a
    convolution — equivalent to correlating the spatially FLIPPED kernel.
    For the reference's 2×2 stride-2 upsample the blocks do not overlap, so
    the flip is exactly a reversal of both spatial axes (verified
    numerically against ``torch.nn.functional.conv_transpose2d`` in
    ``tests/test_torch_import.py``).
    """
    return _np(w)[:, :, ::-1, ::-1].transpose(2, 3, 0, 1)


def _set(tree: Tree, path: tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for name in path[:-1]:
        node = node.setdefault(name, {})
    if path[-1] in node:
        raise ValueError(f"duplicate assignment at {'/'.join(path)}")
    node[path[-1]] = value


def _double_conv(
    sd: Mapping[str, Any], src: str, params: Tree, stats: Tree,
    dst: tuple[str, ...],
) -> None:
    """One reference DoubleConv (``<src>.double_conv.{0,1,3,4}``) → our
    ``Conv_{0,1}`` / ``BatchNorm_{0,1}`` under ``dst``, folding each conv's
    bias into the following BN's running mean."""
    for our_idx, (conv_i, bn_i) in enumerate(((0, 1), (3, 4))):
        conv, bn = f"{src}.double_conv.{conv_i}", f"{src}.double_conv.{bn_i}"
        _set(params, dst + (f"Conv_{our_idx}", "kernel"),
             _conv_kernel(sd[f"{conv}.weight"]))
        _set(params, dst + (f"BatchNorm_{our_idx}", "scale"),
             _np(sd[f"{bn}.weight"]))
        _set(params, dst + (f"BatchNorm_{our_idx}", "bias"),
             _np(sd[f"{bn}.bias"]))
        _set(stats, dst + (f"BatchNorm_{our_idx}", "mean"),
             _np(sd[f"{bn}.running_mean"]) - _np(sd[f"{conv}.bias"]))
        _set(stats, dst + (f"BatchNorm_{our_idx}", "var"),
             _np(sd[f"{bn}.running_var"]))


def convert_reference_unet(
    state_dict: Mapping[str, Any],
) -> dict[str, Tree]:
    """Reference UNet state_dict → variables for
    ``UNet(reference_topology=True, bilinear=False)``.

    Key layout (from the reference's module attribute names,
    ``pytorch/unet/model.py:51-68``): ``down_conv{1..4}`` encoder blocks,
    ``double_conv`` bottleneck, ``up_conv{4..1}`` decoder blocks (each with
    an ``up_sample`` ConvTranspose2d in conv_transpose mode), ``conv_last``
    1×1 head. Decoder order reverses: ``up_conv4`` (deepest) is our
    ``up_0``. Returns ``{"params": ..., "batch_stats": ...}``.
    """
    sd = strip_ddp_prefix(state_dict)
    params: Tree = {}
    stats: Tree = {}
    # DownBlock/UpBlock hold a DoubleConv attribute named double_conv whose
    # inner Sequential is ALSO named double_conv, so their keys nest it
    # twice; the bottleneck is a bare DoubleConv (one level).
    for n in range(1, 5):
        _double_conv(
            sd, f"down_conv{n}.double_conv", params, stats, (f"down_{n - 1}",)
        )
    _double_conv(sd, "double_conv", params, stats, ("bottleneck",))
    for i, m in enumerate((4, 3, 2, 1)):
        up = f"up_conv{m}.up_sample"
        if f"{up}.weight" in sd:  # conv_transpose mode; bilinear has no params
            _set(params, (f"ConvTranspose_{i}", "kernel"),
                 _conv_transpose_kernel(sd[f"{up}.weight"]))
            _set(params, (f"ConvTranspose_{i}", "bias"), _np(sd[f"{up}.bias"]))
        _double_conv(
            sd, f"up_conv{m}.double_conv", params, stats, (f"up_{i}",)
        )
    # 1×1 head: bias kept (no BN follows), model.py:68.
    _set(params, ("Conv_0", "kernel"), _conv_kernel(sd["conv_last.weight"]))
    _set(params, ("Conv_0", "bias"), _np(sd["conv_last.bias"]))

    used = {k.rsplit(".", 1)[0] for k in sd}
    known = {"conv_last"}
    doubles = (
        [f"down_conv{n}.double_conv" for n in range(1, 5)]
        + ["double_conv"]
        + [f"up_conv{m}.double_conv" for m in range(1, 5)]
    )
    known |= {f"{d}.double_conv.{i}" for d in doubles for i in (0, 1, 3, 4)}
    known |= {f"up_conv{m}.up_sample" for m in range(1, 5)}
    extra = sorted(set(used) - known)
    if extra:
        raise ValueError(f"unrecognized modules in state_dict: {extra[:5]}")
    return {"params": params, "batch_stats": stats}


# torchvision ResNet naming is canonical public API: stem conv1/bn1, stages
# layer1..layer4 of numbered blocks, each block conv1/bn1/conv2/bn2
# (+conv3/bn3 for Bottleneck) and optional downsample.{0,1}, head fc. The
# reference builds exactly this via torchvision and only swaps fc
# (``pytorch/resnet/main.py:40-41``).
_RESNET_BLOCKS = {
    "resnet18": (2, 2, 2, 2),
    "resnet34": (3, 4, 6, 3),
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
    "resnet152": (3, 8, 36, 3),
}
_BOTTLENECK = {"resnet50", "resnet101", "resnet152"}


def convert_torchvision_resnet(
    state_dict: Mapping[str, Any], arch: str = "resnet18"
) -> dict[str, Tree]:
    """torchvision ResNet state_dict → variables for our ``models.resnet``
    builders (ImageNet stem — what the reference trains,
    ``pytorch/resnet/main.py:40``).

    Our blocks are flat-numbered across stages (``BasicBlock_0..7`` for
    resnet18; ``Bottleneck_*`` for 50/101/152) with convs/BNs numbered
    in declaration order and the downsample projection LAST
    (``Conv_2``/``BatchNorm_2`` for basic, ``Conv_3``/``BatchNorm_3`` for
    bottleneck).

    Numerical-exactness note: restore into a model built with
    ``torch_padding=True`` — flax 'SAME' pads strided convs asymmetrically,
    shifting the conv grid the weights were trained under
    (``models/resnet.py``).
    """
    if arch not in _RESNET_BLOCKS:
        raise ValueError(f"unknown arch {arch!r}; one of {sorted(_RESNET_BLOCKS)}")
    sd = strip_ddp_prefix(state_dict)
    bottleneck = arch in _BOTTLENECK
    n_convs = 3 if bottleneck else 2
    block_name = "Bottleneck" if bottleneck else "BasicBlock"
    params: Tree = {}
    stats: Tree = {}

    def bn(src: str, dst: tuple[str, ...]) -> None:
        _set(params, dst + ("scale",), _np(sd[f"{src}.weight"]))
        _set(params, dst + ("bias",), _np(sd[f"{src}.bias"]))
        _set(stats, dst + ("mean",), _np(sd[f"{src}.running_mean"]))
        _set(stats, dst + ("var",), _np(sd[f"{src}.running_var"]))

    _set(params, ("Conv_0", "kernel"), _conv_kernel(sd["conv1.weight"]))
    bn("bn1", ("BatchNorm_0",))

    flat = 0
    for stage, n_blocks in enumerate(_RESNET_BLOCKS[arch], start=1):
        for b in range(n_blocks):
            src = f"layer{stage}.{b}"
            ours = f"{block_name}_{flat}"
            for c in range(1, n_convs + 1):
                _set(params, (ours, f"Conv_{c - 1}", "kernel"),
                     _conv_kernel(sd[f"{src}.conv{c}.weight"]))
                bn(f"{src}.bn{c}", (ours, f"BatchNorm_{c - 1}"))
            if f"{src}.downsample.0.weight" in sd:
                _set(params, (ours, f"Conv_{n_convs}", "kernel"),
                     _conv_kernel(sd[f"{src}.downsample.0.weight"]))
                bn(f"{src}.downsample.1", (ours, f"BatchNorm_{n_convs}"))
            flat += 1

    _set(params, ("Dense_0", "kernel"), _np(sd["fc.weight"]).T)
    _set(params, ("Dense_0", "bias"), _np(sd["fc.bias"]))

    # Every module in the file must have been consumed — an arch-mismatched
    # .pth (e.g. a resnet34 imported as resnet18: all resnet18 keys exist
    # with identical shapes, 9 trained blocks silently dropped) would
    # otherwise convert cleanly into a frankenmodel.
    known = {"conv1", "bn1", "fc"}
    for stage, n_blocks in enumerate(_RESNET_BLOCKS[arch], start=1):
        for b in range(n_blocks):
            src = f"layer{stage}.{b}"
            known |= {f"{src}.conv{c}" for c in range(1, n_convs + 1)}
            known |= {f"{src}.bn{c}" for c in range(1, n_convs + 1)}
            known |= {f"{src}.downsample.0", f"{src}.downsample.1"}
    extra = sorted({k.rsplit(".", 1)[0] for k in sd} - known)
    if extra:
        raise ValueError(
            f"state_dict has modules {arch} does not ({extra[:5]}…) — "
            f"wrong --arch?"
        )
    return {"params": params, "batch_stats": stats}


def load_pth(path: str) -> dict[str, Any]:
    """Read a ``.pth`` file the way the reference wrote it (CPU map)."""
    import torch  # lazy: only the import path needs it

    return torch.load(path, map_location="cpu", weights_only=True)
