"""Profiling and step-level timing — the observability the reference lacks.

The reference's only instrumentation is per-epoch wall-clock written to a log
file (``pytorch/unet/train.py:166,206-211``); there is no profiler, no step
timer, and the DDP all-reduce latency on its hot path
(``pytorch/resnet/main.py:131``) is never measured (``SURVEY.md`` §5.1, §6).
This module supplies both halves TPU-natively:

- :class:`Profiler` wraps ``jax.profiler`` — on-demand XLA/TPU traces
  (HLO timelines, per-op HBM/MXU utilization) viewable in TensorBoard or
  Perfetto, plus a live ``start_server`` port for ``tensorboard --logdir``
  capture on a running job.
- :class:`StepTimer` measures per-step wall time **correctly under JAX's
  async dispatch** (a naive ``time.time()`` around ``train_step`` measures
  Python dispatch, not device compute — the device runs ahead), by a
  device→host fetch (:func:`host_sync`) on a sampling cadence. From it come
  images/sec/chip and step-latency percentiles — the BASELINE.md primary
  metrics.
- :func:`measure_collective_latency` times an N-byte gradient-style
  all-reduce over the mesh's ``data`` axis — the "DDP all-reduce step
  latency" number the baseline asks for, measured the same way on CPU
  meshes and real ICI.
"""

from __future__ import annotations

import statistics
import time
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def host_sync(x: Any) -> None:
    """Force device completion by fetching one leaf to the host.

    ``jax.block_until_ready`` can return before remote execution finishes on
    tunneled platforms (observed on the axon TPU tunnel: a chained-matmul
    "benchmark" reported 14 PFLOPS on one v5e until a real device→host fetch
    was inserted; with the fetch it reports a physical ~140 TFLOPS). A D2H
    copy cannot complete before the producing computation has, so fetching is
    the reliable sync. Pass a SMALL output (a scalar loss) — the fetch copies
    it.
    """
    leaves = jax.tree.leaves(x)
    if leaves:
        np.asarray(leaves[0])


class Profiler:
    """``jax.profiler`` wrapper: programmatic traces + live capture server."""

    def __init__(self, trace_dir: str | Path | None = None) -> None:
        self.trace_dir = str(trace_dir) if trace_dir else None
        self._active = False

    def start_server(self, port: int = 9999) -> None:
        """Expose the live profiling endpoint (TensorBoard 'capture profile')."""
        jax.profiler.start_server(port)

    def start(self) -> None:
        if self.trace_dir and not self._active:
            jax.profiler.start_trace(self.trace_dir)
            self._active = True

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False

    def trace_steps(self, step_fn, *args, num_steps: int = 3):
        """Trace ``num_steps`` invocations of ``step_fn`` and return the last
        result — the standard "capture a few hot steps" workflow."""
        self.start()
        try:
            out = None
            for _ in range(num_steps):
                out = step_fn(*args)
            jax.block_until_ready(out)
            return out
        finally:
            self.stop()

    def __enter__(self) -> "Profiler":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


class StepTimer:
    """Per-step timing under async dispatch, with summary percentiles.

    Call :meth:`tick` once per training step with the step's output (any
    pytree on device). Every ``sync_every`` steps it blocks on the output and
    attributes the elapsed wall time evenly to the intervening steps — cheap
    enough to leave on (one host sync per window), accurate enough for
    images/sec and latency percentiles.
    """

    def __init__(self, sync_every: int = 10) -> None:
        self.sync_every = sync_every
        self.durations_s: list[float] = []
        self._window_start: float | None = None
        self._pending = 0
        self._last_output: Any = None

    def _close_window(self) -> None:
        host_sync(self._last_output)
        now = time.perf_counter()
        per_step = (now - self._window_start) / self._pending
        self.durations_s.extend([per_step] * self._pending)
        self._window_start = now
        self._pending = 0

    def tick(self, step_output: Any) -> None:
        if self._window_start is None:
            # First call: sync so the window starts from an idle device.
            host_sync(step_output)
            self._window_start = time.perf_counter()
            return
        self._pending += 1
        self._last_output = step_output
        if self._pending >= self.sync_every:
            self._close_window()

    def summary(self, items_per_step: int | None = None) -> dict[str, float]:
        """Latency percentiles (+ throughput when ``items_per_step`` given).

        Flushes the trailing partial window first (one extra host sync), so
        short epochs — fewer steps than ``sync_every`` — still report stats.
        """
        if self._pending:
            self._close_window()
        if not self.durations_s:
            return {}
        d = sorted(self.durations_s)
        out = {
            "steps_timed": float(len(d)),
            "step_ms_p50": statistics.median(d) * 1e3,
            "step_ms_p90": d[int(0.9 * (len(d) - 1))] * 1e3,
            "step_ms_p95": d[int(0.95 * (len(d) - 1))] * 1e3,
            "step_ms_max": d[-1] * 1e3,
        }
        if items_per_step:
            mean = sum(d) / len(d)
            out["items_per_s"] = items_per_step / mean
            out["items_per_s_per_device"] = (
                out["items_per_s"] / jax.device_count()
            )
        return out


def measure_collective_latency(
    mesh: jax.sharding.Mesh,
    *,
    num_floats: int = 1 << 20,
    axis: str = "data",
    trials: int = 10,
) -> dict[str, float]:
    """Time a gradient-sized all-reduce over ``axis`` — the step-latency
    metric the reference never measures (its analog hot path: the NCCL
    all-reduce inside DDP backward, ``pytorch/resnet/main.py:131``).

    Returns mean/min milliseconds and the implied algorithmic bandwidth.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    if n == 1:
        # bus_gbps is 0.0, not inf: no bytes cross any link on a 1-device
        # axis, and inf would serialize as invalid JSON downstream (bench.py
        # prints this dict).
        return {"all_reduce_ms_mean": 0.0, "all_reduce_ms_min": 0.0,
                "axis_size": 1.0, "bus_gbps": 0.0}

    @jax.jit
    def allreduce(x):
        # Reduce to one scalar so the timing fetch is tiny. Summing the WHOLE
        # result (not a slice) keeps the full-buffer collective live — a
        # sliced dependency could let XLA shrink the psum to 8 floats.
        from deeplearning_mpi_tpu.runtime.compat import shard_map

        reduced = shard_map(
            lambda s: jax.lax.psum(s, axis),
            mesh=mesh,
            in_specs=P(axis), out_specs=P(),
            check_vma=False,
        )(x)
        return jnp.sum(reduced)

    x = jax.device_put(
        jnp.ones((n * num_floats,), jnp.float32),
        NamedSharding(mesh, P(axis)),
    )
    host_sync(allreduce(x))  # compile + warm
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        host_sync(allreduce(x))
        times.append(time.perf_counter() - t0)
    mean = sum(times) / len(times)
    # Ring all-reduce moves 2*(n-1)/n of the buffer per device.
    bytes_moved = 2 * (n - 1) / n * num_floats * 4
    return {
        "all_reduce_ms_mean": mean * 1e3,
        "all_reduce_ms_min": min(times) * 1e3,
        "axis_size": float(n),
        "bus_gbps": bytes_moved / min(times) / 1e9,
    }


def nan_debug_mode(enable: bool = True) -> None:
    """Toggle ``jax_debug_nans`` — the framework's race/NaN-detection analog
    (``SURVEY.md`` §5.2: the reference's only guard is a per-batch isfinite
    check, ``pytorch/unet/train.py:186-188``). With it on, the first NaN-
    producing op raises with a stack trace instead of poisoning the run."""
    jax.config.update("jax_debug_nans", enable)
