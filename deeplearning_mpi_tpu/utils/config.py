"""Flag system: the reference's argparse contract + TPU topology flags.

The reference configures runs through three tiers (SURVEY.md §5.6): torchrun
env vars for topology, argparse for hyperparameters
(``pytorch/resnet/main.py:167-182``, ``pytorch/unet/train.py:310-347``), and
interactive bash prompts that assemble the command (``pytorch/unet/run.sh``).
Here everything is flags (env vars still honored by ``bootstrap.init``), with
the reference's exact flag names and defaults preserved so commands port 1:1.
"""

from __future__ import annotations

import argparse


def add_topology_flags(parser: argparse.ArgumentParser) -> None:
    """Distributed/topology flags — replaces torchrun's CLI + the run.sh
    prompts (``pytorch/unet/run.sh:100-104``)."""
    group = parser.add_argument_group("topology")
    group.add_argument("--coordinator", default=None, help="coordinator addr:port (multi-host; replaces MASTER_ADDR:MASTER_PORT)")
    group.add_argument("--num_processes", type=int, default=None, help="number of host processes (replaces WORLD_SIZE)")
    group.add_argument("--process_id", type=int, default=None, help="this process's id (replaces RANK)")
    group.add_argument("--platform", default=None, choices=("cpu", "tpu"), help="force JAX platform; cpu is the gloo-parity fallback (hello_world.py:44)")
    group.add_argument("--n_virtual_devices", type=int, default=None, help="fake N CPU devices for hardware-free multi-device runs")
    group.add_argument("--dp", type=int, default=-1, help="data-parallel degree (-1: all remaining devices)")
    group.add_argument("--tp", type=int, default=1, help="tensor-parallel degree (model axis)")
    group.add_argument("--pp", type=int, default=1, help="pipeline-parallel degree (pipe axis)")
    group.add_argument("--sp", type=int, default=1, help="sequence-parallel degree (seq axis; ring/ulysses attention)")
    group.add_argument("--ep", type=int, default=1, help="expert-parallel degree (expert axis; MoE)")
    group.add_argument("--zero", action="store_true", help="ZeRO-1: shard optimizer state over the data axis (moments drop to 1/dp per device)")
    group.add_argument("--zero_overlap", action="store_true", help="with --zero: use the explicit bucketed ZeRO-1 schedule (reduce-scattered grad buckets, 1/dp optimizer update, overlapped param all-gather); bit-identical to the GSPMD step where supported, logged fallback otherwise")
    group.add_argument("--tuned_step", default=None, metavar="DB", help="tuning DB (tools/autotune.py --step) whose step|... entry, if present for this model/shape/mesh/dtype, sets remat/grad_accum/overlap; missing or corrupt DB silently keeps the flag defaults")


def ema_decay(value: str) -> float:
    """argparse type for ``--ema``: a decay in [0, 1). 1.0 would freeze the
    average at its random-init seed — training improves while every eval
    silently reports init-quality numbers — so out-of-range fails at parse."""
    f = float(value)
    if not 0.0 <= f < 1.0:
        raise argparse.ArgumentTypeError(
            f"--ema must be in [0, 1), got {f} (it is a decay; 0 disables)"
        )
    return f


def add_training_flags(
    parser: argparse.ArgumentParser,
    *,
    num_epochs: int = 100,
    batch_size: int = 128,
    learning_rate: float = 0.1,
    random_seed: int = 0,
    model_dir: str = "saved_models",
    model_filename: str = "model",
    optimizer: str = "adam",
    weight_decay: float = 0.0,
) -> None:
    """The reference's shared hyperparameter flags, names and defaults intact.

    ResNet defaults: epochs 100, batch 128, lr 0.1, seed 0
    (``pytorch/resnet/main.py:162-176``). UNet callers override to batch 16,
    lr 1e-4, seed 42 (``pytorch/unet/train.py:314-335``). ``--batch_size``
    here is the **global** batch (the reference's is per-process — documented
    difference; one process per host changes the natural unit).
    """
    group = parser.add_argument_group("training")
    group.add_argument("--num_epochs", type=int, default=num_epochs)
    group.add_argument("--batch_size", type=int, default=batch_size, help="GLOBAL batch size")
    group.add_argument("--learning_rate", type=float, default=learning_rate)
    group.add_argument("--optimizer", default=optimizer,
                       choices=("sgd", "adam", "adamw", "adafactor", "lion"),
                       help="default = the reference's choice for this "
                       "trainer (resnet: sgd, unet/lm: adam). adamw/lion use "
                       "decoupled weight decay; adafactor's factored moments "
                       "cut optimizer HBM to ~half of Adam's (composes with "
                       "--zero). --resume requires the same optimizer the "
                       "run started with (opt-state tree mismatch otherwise "
                       "— fail-loud, like --ema)")
    group.add_argument("--weight_decay", type=float, default=weight_decay,
                       help="sgd: coupled L2 (torch semantics, reference "
                       "parity); adamw/adafactor/lion: decoupled decay. "
                       "Ignored by plain adam. Default = the reference's "
                       "value for this trainer (resnet: 1e-5, unet/lm: 0)")
    group.add_argument("--lr_schedule", default="constant",
                       choices=("constant", "cosine", "linear"),
                       help="LR over steps: constant (reference parity), "
                       "warmup+cosine decay, or warmup+linear decay")
    group.add_argument("--warmup_steps", type=int, default=0,
                       help="linear LR warmup from 0 (any --lr_schedule)")
    group.add_argument("--grad_accum", type=int, default=1,
                       help="gradient-accumulation chunks per optimizer step "
                       "(global batch is split evenly; loss-mean semantics "
                       "preserved)")
    group.add_argument("--random_seed", type=int, default=random_seed)
    group.add_argument("--ema", type=ema_decay, default=0.0,
                       help="decay for an exponential moving average of "
                       "params (e.g. 0.999; 0 = off; must be < 1 — at 1.0 "
                       "the average would stay frozen at init). Eval and "
                       "--eval_only then use the averaged weights. The EMA "
                       "rides the checkpoint, so resume/eval/generate runs "
                       "must pass the flag too (tree mismatch otherwise — "
                       "fail-loud)")
    group.add_argument("--model_dir", default=model_dir)
    group.add_argument("--model_filename", default=model_filename)
    group.add_argument("--resume", action="store_true", help="resume from the latest checkpoint in --model_dir (full state: step + optimizer too, unlike the reference's weights-only resume, train.py:342-345)")
    group.add_argument("--eval_only", action="store_true",
                       help="restore the latest checkpoint and run one "
                       "evaluation pass over the eval split, then exit — "
                       "no training (the reference has no standalone eval). "
                       "The train split is still opened (the CLIs build both "
                       "loaders up front); accepted cost for a rare mode")
    group.add_argument("--log_dir", default="logs")
    group.add_argument("--eval_every", type=int, default=10, help="epochs between evals/checkpoints (reference cadence: resnet/main.py:136)")
    group.add_argument("--dtype", default="float32", choices=("float32", "bfloat16"), help="compute dtype (params stay float32)")
    group.add_argument("--profile_dir", default=None, help="write a jax.profiler trace of a few hot steps here (TensorBoard/Perfetto)")
    group.add_argument("--metrics_dir", "--metrics-dir", default=None,
                       help="write telemetry records (per-step scalars, epoch "
                       "stats, MFU/HBM/collective-bytes) as JSONL under this "
                       "directory; render with tools/metrics_report.py")
    group.add_argument("--metrics_every", "--metrics-every", type=int, default=1,
                       help="record every Nth step's scalars to the metrics "
                       "sinks (0 = per-step records off; epoch records always "
                       "flow)")
    group.add_argument("--max_restarts", type=int, default=0, help="auto-resume from the latest checkpoint this many times on failure (0 = fail immediately; the reference's analog is manual restart with --resume)")
    group.add_argument("--restart_delay_s", type=float, default=5.0,
                       help="seconds to wait between auto-resume restarts "
                       "(backoff before re-restoring)")
    group.add_argument("--keep_checkpoints", type=int, default=3,
                       help="retention: keep the last N checkpoints (orbax "
                       "max_to_keep) — bounded history instead of unbounded "
                       "growth; also how far back corrupted-checkpoint "
                       "rollback can reach")
    group.add_argument("--chaos", default=None,
                       help="deterministic fault-injection plan, e.g. "
                       "'nan_grad@step:7,loader_stall@batch:3,kill@step:12,"
                       "corrupt_ckpt@epoch:1' (kinds: nan_grad/kill@step, "
                       "loader_stall/loader_die@batch, corrupt_ckpt@epoch). "
                       "Every fault fires exactly once; recovery is recorded "
                       "in fault_injected_total / recovery_total / "
                       "rollback_total. $DMT_CHAOS is the env fallback. See "
                       "docs/RESILIENCE.md")
    group.add_argument("--guardrails", action="store_true",
                       help="numerics guardrails: judge every step's loss/"
                       "grad-norm/finite scalars through EWMA robust-z "
                       "detectors; tolerated spikes are logged, a poisoned "
                       "verdict rolls back to the pinned last-known-good "
                       "checkpoint and replays (pair with --max_restarts). "
                       "Costs one host sync per step; off (default) adds "
                       "zero syncs and zero allocations. docs/RESILIENCE.md")
    group.add_argument("--digest_every", type=int, default=0,
                       help="with --guardrails: every N steps, sha256 a "
                       "fixed sample of param leaves and publish it on the "
                       "heartbeat for the pod supervisor's cross-rank digest "
                       "vote (a bit-flipped replica is blamed directly; "
                       "minority digest loses). 0 = off")
    group.add_argument("--debug_nans", action="store_true", help="jax_debug_nans: raise at the first NaN-producing op (SURVEY.md §5.2)")
    group.add_argument("--num_workers", type=int, default=None,
                       help="loader fetch threads per host (default: half the "
                       "cores, capped at 16; 0 = synchronous). The reference's "
                       "DataLoader num_workers knob (resnet/main.py:100)")


def add_lm_model_flags(parser: argparse.ArgumentParser) -> "argparse._ArgumentGroup":
    """LM architecture flags shared by ``dmt-train-lm`` and ``dmt-generate``.

    One definition keeps the two entrypoints' defaults byte-identical — the
    checkpoint stores arrays, not architecture, so a silent default drift
    between train and generate would surface as an opaque orbax tree/shape
    mismatch at restore time. Returns the group so callers can append their
    own entrypoint-specific flags (remat, attention, sampling, ...).
    """
    group = parser.add_argument_group("model")
    group.add_argument("--seq_len", type=int, default=512,
                       help="training sequence length (params are RoPE/"
                       "sequence-independent, so inference entrypoints "
                       "accept but ignore it)")
    group.add_argument("--num_layers", type=int, default=4)
    group.add_argument("--num_heads", type=int, default=8)
    group.add_argument("--num_kv_heads", type=int, default=0,
                       help="grouped-query attention: K/V heads shared by "
                       "groups of query heads (0 = num_heads, plain MHA); "
                       "must divide --num_heads. Shrinks the KV cache and "
                       "decode HBM reads by num_heads/num_kv_heads")
    group.add_argument("--head_dim", type=int, default=32)
    group.add_argument("--d_model", type=int, default=256)
    group.add_argument("--d_ff", type=int, default=1024)
    group.add_argument("--moe_experts", type=int, default=0,
                       help="0 = dense SwiGLU MLP; N>1 swaps in a routed MoE "
                       "MLP per block (shard with --ep when training)")
    group.add_argument("--moe_top_k", type=int, default=2)
    group.add_argument("--attention_window", type=int, default=0,
                       help="sliding-window (local) attention: each token "
                       "attends its last N tokens only (0 = full causal). "
                       "A model property — training, prefill, and KV-cached "
                       "decode all honor it (decode then reads O(N) cache "
                       "rows per token). Flash kernels skip out-of-window "
                       "blocks: attention cost becomes O(S*N). Composes "
                       "with --attention ulysses (full-sequence inner) AND "
                       "--attention ring (rotation skipping: each device "
                       "rotates only the O(N/shard) neighbor K/V blocks "
                       "its queries' windows reach)")
    group.add_argument("--moe_routing", default="token_choice",
                       choices=("token_choice", "expert_choice"),
                       help="token_choice = GShard top-k + balance aux loss; "
                       "expert_choice = each expert takes its top-C tokens "
                       "(balanced by construction, but routing sees the "
                       "whole sequence — leaks future context in causal LMs)")
    return group


def save_arch(cfg, ckpt_dir) -> None:
    """Persist the model architecture next to the checkpoint (process 0).

    The checkpoint stores arrays, not architecture; most wrong-flag serving
    mistakes fail loudly anyway (a wrong ``--d_model`` is a shape mismatch,
    a wrong ``--optimizer`` an opt-state tree mismatch). But two knobs are
    TREE-INVISIBLE: ``--attention_window`` and ``--moe_routing`` change
    semantics without changing a single array shape, so serving a
    window-trained checkpoint without the flag would silently decode with
    full attention. ``arch.json`` closes that hole:
    ``arch_mismatch_error`` refuses the mismatch at every start (train,
    resume, eval_only, generate).
    """
    import dataclasses
    from pathlib import Path

    import jax

    if jax.process_index() != 0:
        return
    from deeplearning_mpi_tpu.resilience.integrity import atomic_write_json

    path = Path(ckpt_dir)
    path.mkdir(parents=True, exist_ok=True)
    # Atomic: a kill during the write must not leave a truncated arch.json
    # that poisons every later start with a JSON parse error.
    atomic_write_json(path / "arch.json", dataclasses.asdict(cfg))


def arch_mismatch_error(cfg, ckpt_dir) -> str | None:
    """Formatted refusal message if ``cfg`` differs from the checkpoint
    directory's saved ``arch.json`` — ``None`` if they match or the
    checkpoint predates arch sidecars (old checkpoints keep working; only
    fields present in the file are compared, so new config fields stay
    forward-compatible). One formatter for every caller (train resume,
    eval_only, fresh-train-into-existing-dir, generate), so the message
    and its remedy hint cannot drift between CLIs.

    Multi-host note: all processes read the same file — the checkpoint
    directory is on a shared filesystem by requirement (orbax multi-host
    save/restore already assumes it), so every host reaches the same
    verdict and exits together rather than diverging into a hung
    collective.
    """
    import dataclasses
    import json
    from pathlib import Path

    path = Path(ckpt_dir) / "arch.json"
    if not path.is_file():
        return None
    saved = json.loads(path.read_text())
    current = dataclasses.asdict(cfg)
    lines = [
        f"{key}: checkpoint={saved[key]!r}, flags={current[key]!r}"
        for key in saved
        if key in current and saved[key] != current[key]
    ]
    if not lines:
        return None
    return (
        "checkpoint architecture does not match the flags:\n  "
        + "\n  ".join(lines)
        + f"\n(sidecar: {path}; pass matching flags, or use a fresh "
        "--model_dir to train a different architecture)"
    )


def build_lr(args: argparse.Namespace, train_loader) -> object:
    """Resolve the shared LR flags into what ``build_optimizer`` takes.

    ``--lr_schedule constant`` with no warmup stays a bare float (reference
    parity); the decaying schedules span the planned optimizer steps
    (``loader.steps_per_epoch() * --num_epochs``).
    """
    from deeplearning_mpi_tpu.train.trainer import build_lr_schedule

    # --eval_only must build the SAME schedule shape as training: a callable
    # lr gives optax a ScaleByScheduleState(count) opt_state leaf where a
    # bare float gives EmptyState, and the restore template must match the
    # checkpoint's tree structure exactly (the schedule's values are
    # irrelevant to eval — its *state shape* is not).
    return build_lr_schedule(
        args.learning_rate, args.lr_schedule,
        warmup_steps=args.warmup_steps,
        decay_steps=train_loader.steps_per_epoch() * args.num_epochs,
    )


def restore_for_start(args, checkpointer, state, logger):
    """Shared --resume / --eval_only restore; returns (state, start_epoch).

    ``--eval_only`` is resume-or-die: evaluating a fresh random init would
    silently report garbage metrics, so a missing checkpoint is an error.
    ``--resume`` keeps the reference's lenient start-fresh behavior.

    Both paths restore VERIFIED: the newest checkpoint whose integrity
    manifest re-hashes clean, rolling back past corrupted steps
    (``Checkpointer.restore_verified``; ``docs/RESILIENCE.md``).
    """
    from deeplearning_mpi_tpu.resilience.integrity import CheckpointCorruption

    latest = checkpointer.latest_epoch()
    if getattr(args, "eval_only", False):
        if latest is None:
            raise SystemExit(
                f"--eval_only: no checkpoint under {checkpointer.directory}"
            )
        state, epoch = checkpointer.restore_verified(state)
        logger.log(
            f"eval-only: restored verified epoch {epoch} (step {int(state.step)})"
        )
        return state, epoch + 1
    if args.resume:
        if latest is None:
            logger.log(f"--resume: no checkpoint under {checkpointer.directory}; starting fresh")
        else:
            try:
                # Elastic path: the template's shardings describe THIS run's
                # mesh, which need not match the world that saved — a pod
                # re-formed on survivors restores a dp=4/ZeRO checkpoint
                # onto a dp=2 (or dp=1) mesh, orbax re-sharding against the
                # template and the assertion confirming placement landed.
                state, epoch = checkpointer.restore_elastic(state)
            except CheckpointCorruption as err:
                # --resume is lenient about a MISSING checkpoint; stay
                # consistent for an all-corrupt history: warn and start
                # fresh rather than dying on a recoverable situation.
                logger.log(f"--resume: {err}; starting fresh")
                return state, 0
            logger.log(f"resumed from verified epoch {epoch} (step {int(state.step)})")
            return state, epoch + 1
    return state, 0


def build_chaos(args: argparse.Namespace):
    """Resolve ``--chaos`` (or ``$DMT_CHAOS``) into a ChaosInjector, or
    ``None`` when no plan is set — the common case pays one None check.

    The plan is validated against :data:`~..resilience.faults.TRAIN_KINDS`:
    a kind the training workload has no injection hook for (e.g.
    ``serve_crash``) fails loud at parse time instead of silently never
    firing and leaving the reconciliation invariant unbalanced.
    """
    from deeplearning_mpi_tpu.resilience.faults import (
        TRAIN_KINDS,
        ChaosInjector,
        validate_plan_kinds,
    )

    injector = ChaosInjector.from_spec(getattr(args, "chaos", None))
    if injector is not None:
        validate_plan_kinds(
            ",".join(f"{s.kind}@{s.unit}:{s.at}" for s in injector.plan.specs),
            TRAIN_KINDS, workload="training",
        )
    return injector


def build_guardrails(args: argparse.Namespace):
    """Resolve ``--guardrails``/``--digest_every`` into a GuardrailPolicy,
    or ``None`` (the costless-when-off default: no policy object means the
    trainer allocates nothing and adds no host syncs)."""
    if not getattr(args, "guardrails", False):
        return None
    from deeplearning_mpi_tpu.resilience.guardrails import (
        GuardrailConfig,
        GuardrailPolicy,
    )

    return GuardrailPolicy(
        GuardrailConfig(digest_every=getattr(args, "digest_every", 0) or 0)
    )


def setup_runtime(args: argparse.Namespace):
    """Apply topology flags and initialize the runtime. Returns (topology, mesh).

    Import-deferred so flag parsing (--help) never initializes a backend.
    """
    from deeplearning_mpi_tpu.runtime import bootstrap
    from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

    if args.n_virtual_devices:
        bootstrap.set_virtual_cpu_devices(args.n_virtual_devices)
        args.platform = "cpu"
    topo = bootstrap.init(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        platform=args.platform,
    )
    mesh = create_mesh(
        MeshSpec(
            data=args.dp,
            pipe=getattr(args, "pp", 1),
            expert=getattr(args, "ep", 1),
            seq=getattr(args, "sp", 1),
            model=args.tp,
        )
    )
    if getattr(args, "debug_nans", False):
        from deeplearning_mpi_tpu.utils.profiling import nan_debug_mode

        nan_debug_mode(True)
    return topo, mesh


def build_observability(
    args: argparse.Namespace,
    trainer,
    *,
    flops_per_step: float | None = None,
    issued_flops_per_step: float | None = None,
    comm_bytes_per_step: float | None = None,
) -> None:
    """Attach profiler + heartbeat + telemetry from the shared flags.

    ``--metrics_dir`` adds a JSONL sink to the trainer's registry (every
    record — per-step scalars, epoch stats, evals — lands in
    ``metrics.jsonl`` there; ``tools/metrics_report.py`` renders it).
    ``flops_per_step`` / ``comm_bytes_per_step`` are the CLI's analytic
    estimates (``telemetry.flops`` / ``telemetry.comms``) feeding the
    trainer's MFU and collective-byte epoch stats. When the caller passes no
    comm estimate, the pure-DP gradient all-reduce is derived from the
    trainer's own state + mesh — every data-parallel run gets collective
    accounting for free.
    """
    import os
    import pathlib

    import jax

    from deeplearning_mpi_tpu.resilience.pod import (
        ENV_HEARTBEAT_DIR,
        ENV_HEARTBEAT_INTERVAL,
    )
    from deeplearning_mpi_tpu.train.resilience import Heartbeat
    from deeplearning_mpi_tpu.utils.profiling import Profiler

    if getattr(args, "profile_dir", None):
        trainer.profiler = Profiler(args.profile_dir)
    if getattr(args, "log_dir", None):
        # Under a pod supervisor ($DMT_HEARTBEAT_DIR), each rank beats into
        # its own file in the shared heartbeat dir — the supervisor's
        # pod-level liveness view aggregates them. Standalone runs keep the
        # single heartbeat.json beside the logs.
        hb_dir = os.environ.get(ENV_HEARTBEAT_DIR)
        hb_path = (
            pathlib.Path(hb_dir) / f"heartbeat-{jax.process_index()}.json"
            if hb_dir
            else pathlib.Path(args.log_dir) / "heartbeat.json"
        )
        interval_s = float(os.environ.get(ENV_HEARTBEAT_INTERVAL, "10.0"))
        trainer.heartbeat = Heartbeat(hb_path, interval_s=interval_s).start()
    metrics_dir = getattr(args, "metrics_dir", None)
    if metrics_dir and jax.process_index() == 0:
        # Process 0 only: every rank computes identical global scalars (the
        # records are collective results), so N ranks appending to one
        # metrics.jsonl would duplicate each record N times — and an
        # elastically resumed world would change the duplication factor
        # mid-file, breaking the per-step loss series the parity drills
        # compare.
        from deeplearning_mpi_tpu.telemetry.registry import JsonlSink

        trainer.metrics.add_sink(
            JsonlSink(pathlib.Path(metrics_dir) / "metrics.jsonl")
        )
    trainer.metrics_every = getattr(args, "metrics_every", trainer.metrics_every)
    if flops_per_step is not None:
        trainer.flops_per_step = flops_per_step
    if issued_flops_per_step is not None:
        # Model FLOPs + remat recompute: feeds mfu_issued/mfu_gap (and the
        # overlap-fraction estimate) in the epoch stats. MFU itself stays
        # defined over model FLOPs only (telemetry/flops.py docstring).
        trainer.issued_flops_per_step = issued_flops_per_step
    if comm_bytes_per_step is None and trainer.comm_bytes_per_step is None:
        from deeplearning_mpi_tpu.telemetry import comms

        dp = trainer.mesh.shape.get("data", 1)
        comm_bytes_per_step = comms.dp_grad_allreduce_bytes(
            comms.param_count(trainer.state.params), dp,
            zero=getattr(trainer, "zero", False),
        )
    if comm_bytes_per_step is not None:
        trainer.comm_bytes_per_step = comm_bytes_per_step


def execute_training(
    trainer,
    checkpointer,
    args: argparse.Namespace,
    train_loader,
    eval_loader,
    start_epoch: int,
    state_factory=None,
):
    """Shared CLI tail: fit with optional auto-resume, then clean teardown.

    ``--max_restarts N`` turns crashes into restore-latest-checkpoint-and-
    continue (see ``train.resilience.run_with_auto_resume``); the reference's
    only recovery is a manual re-launch with ``--resume``
    (``pytorch/unet/train.py:342-345``). ``state_factory`` rebuilds a fresh
    initial TrainState for restarts that happen before the first checkpoint —
    required because the jitted step donates the state's buffers, so a crash
    mid-step leaves ``trainer.state`` deleted and unusable.

    Resilience integration (``docs/RESILIENCE.md``): restart restores go
    through ``restore_verified`` (corrupted checkpoints roll back; an
    all-corrupt history restarts from init rather than dying), a SIGTERM
    handler is installed so preemption exits via a graceful final
    checkpoint (``Preempted`` — clean, never retried), and teardown emits
    one ``run_summary`` record carrying every counter — including the
    chaos reconciliation triple — before the sinks close.
    """
    from deeplearning_mpi_tpu.resilience import (
        CheckpointCorruption,
        GracefulShutdown,
        Preempted,
        run_with_auto_resume,
    )

    if getattr(args, "eval_only", False):
        # The CLI upgraded --eval_only to a restore (resume-or-die): by here
        # trainer.state holds checkpoint weights. One collective eval pass.
        try:
            if trainer.profiler is not None:
                trainer.report_eval(
                    {}, note="--profile_dir is a no-op with --eval_only "
                    "(tracing hooks live in the train loop)"
                )
            stats = trainer.evaluate(eval_loader)
            trainer.report_eval(stats)
            return [stats]
        finally:
            if trainer.heartbeat is not None:
                trainer.heartbeat.stop()
            if getattr(trainer, "metrics", None) is not None:
                trainer.metrics.close()

    if args.max_restarts > 0 and state_factory is None:
        # Without a factory, a pre-checkpoint crash would retry on the
        # donated/deleted state and burn every restart on buffer errors.
        raise ValueError("--max_restarts requires a state_factory")

    chaos = getattr(trainer, "chaos", None)
    own_shutdown = trainer.shutdown is None
    if own_shutdown:
        # install() is a no-op off the main thread (degrades to manual
        # request()); every training CLI gets SIGTERM grace for free.
        trainer.shutdown = GracefulShutdown().install()

    attempts = 0

    def fit(restart_epoch: int):
        nonlocal attempts
        attempts += 1
        if attempts > 1:
            pending = getattr(trainer, "pending_rollback", None)
            if pending is not None:
                # Guardrail rollback (docs/RESILIENCE.md): the poisoned
                # steps never happened. Restore the PINNED last-known-good
                # (not merely the newest bytes-clean step, which may carry
                # the poisoned updates), discard younger checkpoints, and
                # replay — the loader order is (seed, epoch)-deterministic,
                # so the replay rejoins the unfaulted trajectory.
                trainer.pending_rollback = None
                template = state_factory() if state_factory else trainer.state
                if checkpointer.latest_epoch() is not None:
                    trainer.state, epoch = checkpointer.rollback_to_last_good(
                        template
                    )
                    restart_epoch = epoch + 1
                else:
                    # Poisoned before the first save: a fresh init IS the
                    # last-known-good.
                    trainer.state = template
                    restart_epoch = 0
                # Rejoin the restored state's step count, so the replayed
                # steps' records/triggers line up with a clean run's.
                trainer._global_step = int(trainer.state.step)
                trainer.place_state()
                trainer.metrics.counter("guard_rollback_total").inc()
                trainer._log(
                    f"guardrail rollback: restored last-good state (step "
                    f"{trainer._global_step}); replaying from epoch "
                    f"{restart_epoch} (poison region {pending.region})"
                )
                return trainer.fit(
                    train_loader, args.num_epochs,
                    eval_loader=eval_loader,
                    start_epoch=max(start_epoch, restart_epoch),
                )
            # Crash restart: the previous state's buffers may be donated/
            # deleted — ALWAYS rebuild, from the newest checkpoint that
            # passes integrity verification when one exists, else from a
            # fresh init (an all-corrupt history restarts from scratch —
            # losing progress beats dying with checkpoints on disk).
            if checkpointer.latest_epoch() is not None:
                template = state_factory() if state_factory else trainer.state
                try:
                    trainer.state, epoch = checkpointer.restore_verified(template)
                    # The VERIFIED epoch wins over the supervisor's
                    # latest+1: a rollback past a corrupted newest step
                    # must re-train the rolled-back epochs, not skip them.
                    restart_epoch = epoch + 1
                except CheckpointCorruption as err:
                    trainer._log(f"restart: {err}; restarting from a fresh init")
                    trainer.state = template  # already a fresh init
                    restart_epoch = 0
            elif state_factory is not None:
                trainer.state = state_factory()
            trainer.place_state()
            if chaos is not None:
                # Surviving the restart IS the kill's recovery (no-op when
                # the crash wasn't an injected kill).
                chaos.record_recovery("kill")
        return trainer.fit(
            train_loader, args.num_epochs,
            eval_loader=eval_loader, start_epoch=max(start_epoch, restart_epoch),
        )

    try:
        if args.max_restarts > 0 and checkpointer is not None:
            return run_with_auto_resume(
                fit, checkpointer,
                max_restarts=args.max_restarts, logger=trainer.logger,
                restart_delay_s=getattr(args, "restart_delay_s", 5.0),
                registry=getattr(trainer, "metrics", None),
            )
        return fit(start_epoch)
    except Preempted as p:
        # Clean preemption: the final checkpoint is on disk; exit 0 so
        # orchestrators reschedule instead of alerting on a crash.
        trainer._log(f"exiting after preemption ({p})")
        return trainer.history
    finally:
        if trainer.heartbeat is not None:
            trainer.heartbeat.stop()
        if trainer.profiler is not None:
            trainer.profiler.stop()  # finalize a trace left open by a crash
        if own_shutdown and trainer.shutdown is not None:
            trainer.shutdown.uninstall()
        if getattr(trainer, "metrics", None) is not None:
            # One run_summary record with every counter/gauge/histogram —
            # where the chaos triple (fault_injected_total == recovery_total
            # + rollback_total) reconciles in the metrics report.
            trainer.metrics.emit("run_summary", trainer.metrics.snapshot())
            if chaos is not None:
                trainer._log(chaos.summary())
            trainer.metrics.close()  # flush + close every telemetry sink
