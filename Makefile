# Developer entry points. `make verify` is the tier-1 gate (the exact
# ROADMAP.md command, byte-for-byte); `make check` adds the telemetry
# report selftest.

SHELL := /bin/bash

.PHONY: verify selftest check smoke lint sanitize-smoke serve-smoke spec-smoke chaos-smoke tune-smoke pod-smoke overlap-smoke fleet-smoke disagg-smoke prefix-smoke autoscale-smoke trace-smoke guard-smoke sim-smoke controlplane-smoke

# Tier-1 tests — verbatim from ROADMAP.md ("Tier-1 verify"). The lint,
# sanitize-smoke, serve-smoke, spec-smoke, chaos-smoke, tune-smoke,
# pod-smoke, overlap-smoke, fleet-smoke, disagg-smoke, and prefix-smoke
# prerequisites gate the tier-1 run on the static analyzer, the
# runtime-sanitizer injection drill, the serving engine's end-to-end
# parity selftest, the speculative-decode parity/reconciliation drill,
# the fault-injection recovery drill, the autotune loop, the elastic-pod
# rank-failure drill, the overlapped-ZeRO-1 bit-equality drill, the
# serving-fleet replica-failure drill, the disaggregated prefill/decode
# drill, the radix prefix-cache drill, the fleet-autoscaler surge drill,
# and the numerics-guardrail drill without touching the ROADMAP command
# itself.
verify: lint sanitize-smoke serve-smoke spec-smoke chaos-smoke tune-smoke pod-smoke overlap-smoke fleet-smoke disagg-smoke prefix-smoke autoscale-smoke trace-smoke guard-smoke sim-smoke controlplane-smoke
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# Static analysis gate (docs/ANALYSIS.md): dmt-lint enforces the repo's
# JAX contracts (donation safety, zero-retrace, atomic IO, single-writer
# JSONL, supervisor ordering, telemetry schema) with AST passes; ruff
# (pinned in pyproject.toml [tool.ruff]) runs alongside when installed —
# the container image does not ship it, so it is gated, not required.
lint:
	env JAX_PLATFORMS=cpu python tools/lint.py
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check .; \
	else \
		echo "lint: ruff not installed — skipping (CI runs it; config pinned in pyproject.toml)"; \
	fi

# Runtime-sanitizer injection drill (docs/ANALYSIS.md "Runtime
# sanitizer"): under DMT_SANITIZE=1, an injected KV-pool double-free,
# use-after-free, post-warmup retrace, and donation-canary flip must each
# be caught and classified — and the clean paths must trip nothing.
sanitize-smoke:
	env JAX_PLATFORMS=cpu DMT_SANITIZE=1 python tools/sanitize_drill.py

# Telemetry pipeline smoke: registry -> JSONL -> report, no training needed.
selftest:
	env JAX_PLATFORMS=cpu python tools/metrics_report.py --selftest

check: verify selftest

# Continuous-batching serving engine end-to-end: random-init model, Poisson
# trace, every completion verified token-for-token against offline greedy
# decode (docs/SERVING.md).
serve-smoke:
	env JAX_PLATFORMS=cpu python -m deeplearning_mpi_tpu.cli.serve_lm \
		--selftest --num_layers 2 --num_heads 2 --head_dim 16 \
		--d_model 64 --d_ff 128 --num_requests 8 --rate 100 \
		--max_new_tokens 8 --prompt_len_min 3 --prompt_len_max 20 \
		--max_slots 3 --block_size 8 --num_blocks 32 \
		--max_blocks_per_seq 6 --prefill_chunk 8

# Speculative decoding end-to-end: same trace as serve-smoke but with a
# 1-layer self-draft proposing 3 tokens/step and bucketed decode-batch
# formation. The selftest asserts bit-identical greedy parity (the
# exact-match acceptance rule means the draft can never change output),
# counter reconciliation (proposed == accepted + rolled back), and a
# nonzero acceptance rate (docs/SERVING.md "Speculative decoding").
spec-smoke:
	env JAX_PLATFORMS=cpu python -m deeplearning_mpi_tpu.cli.serve_lm \
		--selftest --num_layers 2 --num_heads 2 --head_dim 16 \
		--d_model 64 --d_ff 128 --num_requests 8 --rate 100 \
		--max_new_tokens 8 --prompt_len_min 3 --prompt_len_max 20 \
		--max_slots 3 --block_size 8 --num_blocks 32 \
		--max_blocks_per_seq 6 --prefill_chunk 8 \
		--spec_k 3 --draft_layers 1 --decode_buckets 2,3

# Overlapped-ZeRO-1 bit-equality drill (docs/PERF_ANALYSIS.md): 5 training
# steps at dp=2 (two virtual CPU devices) through the explicit bucketed
# reduce-scatter/all-gather schedule vs the GSPMD ZeRO-1 path — losses,
# optimizer state, and params must be BIT-identical (no tolerance).
overlap-smoke:
	env JAX_PLATFORMS=cpu python tools/overlap_drill.py

# Compilation-service acceptance loop (docs/COMPILATION.md): autotune tiny
# kernels into a tuning DB, round-trip it, verify tuned == default
# numerics, and prove a warm-started serving engine hits the persistent
# compile cache and performs zero compiles on its first request.
tune-smoke:
	env JAX_PLATFORMS=cpu python tools/autotune.py --selftest

# 30-second observability demo: tiny CPU-mesh LM run with telemetry on,
# rendered by the report tool (docs/OBSERVABILITY.md walks through it).
smoke:
	rm -rf /tmp/dmt_smoke
	env JAX_PLATFORMS=cpu python -m deeplearning_mpi_tpu.cli.train_lm \
		--n_virtual_devices 8 --num_epochs 1 --batch_size 16 \
		--train_sequences 64 --seq_len 64 --num_layers 2 --d_model 64 \
		--d_ff 128 --num_heads 4 --head_dim 16 --eval_every 1 \
		--metrics_dir /tmp/dmt_smoke/metrics --log_dir /tmp/dmt_smoke/logs \
		--model_dir /tmp/dmt_smoke/models
	python tools/metrics_report.py /tmp/dmt_smoke/metrics/metrics.jsonl

# Fault-injection recovery drill (<60s, docs/RESILIENCE.md): a tiny LM run
# where the epoch-1 checkpoint is corrupted on disk and the process "dies"
# mid-epoch-2; auto-resume must roll back past the corruption to the
# verified epoch-0 checkpoint, re-train, and finish all 3 epochs. The
# follow-up assert reads the run's own metrics.jsonl and requires the
# reconciliation invariant: fault_injected_total == recovery_total +
# rollback_total.
chaos-smoke:
	rm -rf /tmp/dmt_chaos
	env JAX_PLATFORMS=cpu python -m deeplearning_mpi_tpu.cli.train_lm \
		--n_virtual_devices 8 --num_epochs 3 --batch_size 8 \
		--train_sequences 40 --seq_len 32 --num_layers 1 --d_model 32 \
		--d_ff 64 --num_heads 2 --head_dim 16 --eval_every 1 \
		--max_restarts 2 --restart_delay_s 0.1 \
		--chaos "corrupt_ckpt@epoch:1,kill@step:11" \
		--metrics_dir /tmp/dmt_chaos/metrics \
		--model_dir /tmp/dmt_chaos/models --log_dir /tmp/dmt_chaos/logs
	env JAX_PLATFORMS=cpu python -c 'import json; recs = [json.loads(l) for l in open("/tmp/dmt_chaos/metrics/metrics.jsonl")]; s = [r for r in recs if r["kind"] == "run_summary"][-1]; f, r, b = (s.get(k, 0) for k in ("fault_injected_total", "recovery_total", "rollback_total")); assert f >= 2 and f == r + b, (f, r, b); print("chaos-smoke OK: injected=%d recovered=%d rolled_back=%d" % (f, r, b))'

# Elastic-pod rank-failure drill (docs/RESILIENCE.md "Elastic pods",
# docs/TPU_POD_RUNBOOK.md): a 2-process CPU pod loses rank 1 to a planned
# rank_kill mid-epoch-1; the supervisor must detect it, re-form a world of
# one, resume from the epoch-0 checkpoint, and land on a loss trajectory
# bit-identical to a clean single-process from-checkpoint run — with the
# pod-level chaos books reconciling in pod_metrics.jsonl.
pod-smoke:
	env JAX_PLATFORMS=cpu python tools/pod_drill.py --fault rank_kill \
		--root /tmp/dmt_pod_smoke

# Numerics-guardrail drill (docs/RESILIENCE.md "Numerics guardrails"):
# both arms of tools/guardrail_drill.py. loss_spike — a planned x1000
# loss scale must draw a poisoned verdict, roll back to the pinned
# last-known-good checkpoint, and replay onto a trajectory bit-identical
# to an unfaulted run. bitflip — a 2-process pod's rank 1 flips one
# param bit mid-run; the supervisor's cross-rank digest vote must convict
# it, quarantine the host, prune poisoned checkpoints, and re-form a
# world of one whose resumed losses are bit-identical to a clean resume.
# Chaos books must reconcile in both arms.
guard-smoke:
	env JAX_PLATFORMS=cpu python tools/guardrail_drill.py --arm both \
		--root /tmp/dmt_guard_smoke

# Disaggregated prefill/decode drill (docs/SERVING.md "Disaggregated
# topology"): the serve-smoke trace through the split topology — a
# prefill-only engine handing completed prompts to a decode-only engine
# over one shared KV pool — under a handoff_stall + serve_crash chaos
# plan. The selftest asserts every stream is still bit-identical to
# offline greedy (the handoff and both recoveries must be invisible in
# the tokens); the second run gates the opt-in int8 paged KV cache on
# measured token-level acceptance vs the fp reference.
disagg-smoke:
	env JAX_PLATFORMS=cpu python -m deeplearning_mpi_tpu.cli.serve_lm \
		--selftest --disagg --warmup \
		--chaos "handoff_stall@step:6,serve_crash@step:14" \
		--num_layers 2 --num_heads 2 --head_dim 16 \
		--d_model 64 --d_ff 128 --num_requests 8 --rate 100 \
		--max_new_tokens 8 --prompt_len_min 3 --prompt_len_max 20 \
		--max_slots 3 --block_size 8 --num_blocks 32 \
		--max_blocks_per_seq 6 --prefill_chunk 8
	env JAX_PLATFORMS=cpu python -m deeplearning_mpi_tpu.cli.serve_lm \
		--selftest --disagg --kv_dtype int8 \
		--num_layers 2 --num_heads 2 --head_dim 16 \
		--d_model 64 --d_ff 128 --num_requests 8 --rate 100 \
		--max_new_tokens 8 --prompt_len_min 3 --prompt_len_max 20 \
		--max_slots 3 --block_size 8 --num_blocks 32 \
		--max_blocks_per_seq 6 --prefill_chunk 8

# Radix prefix-cache drill (docs/SERVING.md "Prefix cache &
# multi-tenancy"): a two-tenant trace whose prompts share a long,
# non-block-aligned preamble through a colocated engine with the radix
# cache on and per-tenant budgets. Asserts prefix hits and CoW copies
# fire, every stream stays bit-identical to offline greedy, the
# over-budget tenant is shed with reason tenant_budget, and the pool's
# refcount books balance at drain (flush() returns every block).
prefix-smoke:
	env JAX_PLATFORMS=cpu python tools/prefix_drill.py

# Serving-fleet replica-failure drill (docs/SERVING.md "Fault-tolerant
# fleet", docs/TPU_POD_RUNBOOK.md §8): a 2-replica CPU fleet under a
# trace-replay burst loses replica 0 to a planned replica_kill and
# replica 1 to a replica_hang; the supervisor must re-dispatch every
# in-flight request to a survivor (original arrival/deadline preserved),
# respawn both, and roll a zero-downtime weight swap through the fleet —
# with every completed stream bit-identical to offline greedy, zero
# dropped requests, zero post-warmup compiles, and the chaos books
# reconciled in fleet_metrics.jsonl.
fleet-smoke:
	env JAX_PLATFORMS=cpu python tools/fleet_drill.py --fault kill_hang \
		--root /tmp/dmt_fleet_smoke

# Fleet-autoscaler surge drill (docs/SERVING.md "Load-adaptive
# autoscaling", docs/TPU_POD_RUNBOOK.md §9): a 1-replica fleet under a
# burst+spike trace must scale up (supervised spawn, warmed and
# ready-acked before the router sees it) while a planned SIGKILL races the
# first scale-up, then drain-retire back toward the floor on the trickle
# tail — zero drops, every completed stream bit-identical to offline
# greedy, and the scale books reconciling
# (scale_events == spawned + retired + vetoed). The brownout ladder has
# its own drill mode (--fault brownout); the smoke runs surge only to
# keep the verify gate fast.
autoscale-smoke:
	env JAX_PLATFORMS=cpu python tools/autoscale_drill.py --fault surge \
		--root /tmp/dmt_autoscale_smoke

# Control-plane crash drill (docs/RESILIENCE.md "Control-plane crash
# safety", docs/TPU_POD_RUNBOOK.md §12): the fleet SUPERVISOR is
# SIGKILLed mid-surge (load_spike live, a scale-up warming), its
# orphaned replicas keep decoding headless, one orphan is killed, and a
# restarted supervisor must replay the write-ahead journal, re-adopt
# every live replica without respawning it (serve_compile_total flat —
# zero retraces), respawn the corpse, re-dispatch its orphaned requests
# with their original arrival/deadline, and drain with zero drops —
# every stream bit-identical to offline greedy and the chaos + scale
# books reconciling across both incarnations in fleet_metrics.jsonl.
controlplane-smoke:
	env JAX_PLATFORMS=cpu python tools/controlplane_drill.py \
		--root /tmp/dmt_controlplane_smoke

# Load-simulator drill (docs/SIMULATION.md): three phases. scale — a
# >=100k-request multi-tenant compressed day (diurnal + bursts + flash
# crowd + an adversarial tenant) simulated against the REAL
# router/scheduler/autoscaler objects under the fake clock in <60s on
# CPU, books balanced (completed + shed == requests), byte-deterministic
# twice. sweep — a seeded policy-parameter search scored on SLO-attained
# completions per replica-second; the winner must round-trip through the
# autotune TuningDB under its simpolicy|<digest> key. predictive — a
# REAL-process fleet with the predictive autoscaler replays a
# flash-crowd trace; the forecaster must fire the first scale-up BEFORE
# the crowd's peak, with zero dropped requests and reconciled scale
# books.
sim-smoke:
	env JAX_PLATFORMS=cpu python tools/sim_drill.py --phase all \
		--root /tmp/dmt_sim_smoke

# Distributed-tracing drill (docs/OBSERVABILITY.md "Distributed request
# tracing"): a 2-replica disaggregated fleet replays a trace with the
# flight recorder armed while chaos kills replica 0 mid-decode. The
# merged per-process JSONL (tools/trace_report.py) must cover every
# completed request — queue+prefill+handoff+decode+stream spans within
# 5% of measured TTLT — with zero orphan spans, and the killed replica
# must leave its flight dump behind. A short traced training run then
# proves the per-phase step attribution tiles the epoch wall-clock and
# mfu_gap decomposes into named phase shares.
trace-smoke:
	env JAX_PLATFORMS=cpu python tools/trace_drill.py \
		--root /tmp/dmt_trace_smoke
