# Developer entry points. `make verify` is the tier-1 gate (the exact
# ROADMAP.md command, byte-for-byte); `make check` adds the telemetry
# report selftest.

SHELL := /bin/bash

.PHONY: verify selftest check smoke

# Tier-1 tests — verbatim from ROADMAP.md ("Tier-1 verify").
verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# Telemetry pipeline smoke: registry -> JSONL -> report, no training needed.
selftest:
	env JAX_PLATFORMS=cpu python tools/metrics_report.py --selftest

check: verify selftest

# 30-second observability demo: tiny CPU-mesh LM run with telemetry on,
# rendered by the report tool (docs/OBSERVABILITY.md walks through it).
smoke:
	rm -rf /tmp/dmt_smoke
	env JAX_PLATFORMS=cpu python -m deeplearning_mpi_tpu.cli.train_lm \
		--n_virtual_devices 8 --num_epochs 1 --batch_size 16 \
		--train_sequences 64 --seq_len 64 --num_layers 2 --d_model 64 \
		--d_ff 128 --num_heads 4 --head_dim 16 --eval_every 1 \
		--metrics_dir /tmp/dmt_smoke/metrics --log_dir /tmp/dmt_smoke/logs \
		--model_dir /tmp/dmt_smoke/models
	python tools/metrics_report.py /tmp/dmt_smoke/metrics/metrics.jsonl
