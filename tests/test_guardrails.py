"""Numerics-guardrail tests: SDC detection, digest voting, rollback-and-
replay, quarantine, and the costless-when-off contract.

Structured bottom-up, like the subsystem (``docs/RESILIENCE.md`` "Numerics
guardrails"):

- :class:`GuardrailPolicy` — the pure per-step verdict machine (warmup
  grace, EWMA bands, spike/poison thresholds, patience escalation,
  hysteresis, replay attribution).
- :class:`DigestVote` / :func:`param_digest` / ``maybe_bitflip`` — the
  cross-rank SDC detector and the chaos hook it detects.
- :class:`QuarantineLedger` — the persistent blame book.
- :class:`Checkpointer` hardening — the pinned last-known-good surviving
  retention with every younger save corrupt, ``rollback_to_last_good``
  discarding poisoned steps, and the anti-rollback generation fence.
- the fault-kind audit — every kind in every ``*_KINDS`` set is
  grammar-parseable, workload-validated, and has a live injection hook.
- trainer integration — the all-non-finite epoch path, spike-kind
  accounting (``nan_grads``), the loss-spike rollback-and-replay e2e
  rejoining the unfaulted trajectory, and the costless-when-off
  regression (no policy => no guardrail objects, no extra metrics, no
  guardrail code reachable from the hot loop).

The two-process bitflip drill (digest vote -> quarantine -> re-form) needs
real subprocess ranks and lives in ``tools/guardrail_drill.py``
(``make guard-smoke``); everything in-process is covered here.
"""

import json
import math
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import pytest

from deeplearning_mpi_tpu.data import ShardedLoader, SyntheticTokens
from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.resilience import (
    ChaosInjector,
    CheckpointCorruption,
    FaultPlan,
    ResilientLoader,
    atomic_write_json,
    tree_digests,
)
from deeplearning_mpi_tpu.resilience.faults import (
    AUTOSCALE_KINDS,
    CONTROLPLANE_KINDS,
    DISAGG_KINDS,
    FAULT_INJECTED,
    FAULT_UNITS,
    FLEET_KINDS,
    GUARD_KINDS,
    POD_KINDS,
    RECOVERY,
    ROLLBACK,
    SERVE_KINDS,
    TRAIN_KINDS,
    validate_plan_kinds,
)
from deeplearning_mpi_tpu.resilience.guardrails import (
    DigestVote,
    GuardrailConfig,
    GuardrailPolicy,
    QuarantineLedger,
    VoteResult,
    attach_digest_ring,
    param_digest,
)
from deeplearning_mpi_tpu.train import Checkpointer, Trainer, create_train_state
from deeplearning_mpi_tpu.train.trainer import build_optimizer, make_train_step


# -- shared tiny-LM plumbing --------------------------------------------------

def _lm_factory(mesh=None, seed=0, ema=False):
    model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
    tx = build_optimizer("sgd", 1e-2, momentum=0.0)

    def factory():
        return create_train_state(
            model, jax.random.key(seed), jnp.zeros((1, 16), jnp.int32), tx,
            mesh=mesh, ema=ema,
        )

    return factory


def _warm(policy, n, value=1.0):
    """Feed ``n`` calm steps; returns the next step index."""
    for step in range(n):
        verdict = policy.observe(step, loss=value)
        assert verdict.ok, verdict
    return n


def _denom(policy, signal="loss"):
    """The robust-z denominator the policy will use for ``signal`` now."""
    band = policy._bands[signal]
    return max(band.dev, 1e-8, abs(band.mean) * 1e-3)


# -- GuardrailPolicy ----------------------------------------------------------

class TestGuardrailPolicy:
    CFG = GuardrailConfig(warmup_steps=4, spike_patience=2, hysteresis_steps=3)

    def test_warmup_grace_judges_nothing(self):
        pol = GuardrailPolicy(self.CFG)
        # Wildly bimodal losses: any z-test would scream, but the first
        # warmup_steps observations only build bands.
        for step, loss in enumerate([1.0, 500.0, 1.0, 500.0]):
            assert pol.observe(step, loss=loss).ok

    def test_calm_steps_stay_ok_and_update_bands(self):
        pol = GuardrailPolicy(self.CFG)
        step = _warm(pol, 8)
        assert pol.observe(step, loss=1.0).ok
        band = pol._bands["loss"]
        assert band.n == 9 and band.mean == pytest.approx(1.0)

    def test_spike_verdict_between_thresholds(self):
        pol = GuardrailPolicy(self.CFG)
        step = _warm(pol, 8)
        x = 1.0 + 9.0 * _denom(pol)  # z ~ 9: >= z_spike 6, < z_poison 12
        v = pol.observe(step, loss=x)
        assert v.status == "spike" and v.signal == "loss"
        assert 6.0 <= v.z < 12.0
        assert v.region == (step, step)

    def test_instant_poison_above_z_poison(self):
        pol = GuardrailPolicy(self.CFG)
        step = _warm(pol, 8)
        v = pol.observe(step, loss=1.0 + 50.0 * _denom(pol))
        assert v.status == "poisoned" and v.region == (step, step)
        # A poisoned verdict resets the policy: the caller rolls back to a
        # state where this band history never happened.
        assert pol._seen == 0 and not pol._bands

    def test_spike_run_escalates_past_patience(self):
        pol = GuardrailPolicy(self.CFG)
        step = _warm(pol, 8)
        x = 1.0 + 9.0 * _denom(pol)
        assert pol.observe(step, loss=x).status == "spike"
        assert pol.observe(step + 1, loss=x).status == "spike"
        v = pol.observe(step + 2, loss=x)  # 3 consecutive > patience 2
        assert v.status == "poisoned"
        assert v.region == (step, step + 2)  # whole episode attributed

    def test_hysteresis_freezes_bands_until_calm(self):
        pol = GuardrailPolicy(self.CFG)
        step = _warm(pol, 8)
        dev_before = pol._bands["loss"].dev
        assert pol.observe(step, loss=1.0 + 9.0 * _denom(pol)).status == "spike"
        # Calm steps inside the episode: verdict ok, bands still frozen.
        for i in range(1, self.CFG.hysteresis_steps):
            v = pol.observe(step + i, loss=1.0)
            assert v.ok and v.reason == "episode cooling"
            assert pol._bands["loss"].dev == dev_before
        # The closing calm step thaws the bands and updates them again.
        v = pol.observe(step + self.CFG.hysteresis_steps, loss=1.0)
        assert v.ok and v.reason == ""
        assert pol._episode_start is None
        assert pol._bands["loss"].dev != dev_before

    def test_non_finite_is_spike_even_during_warmup(self):
        pol = GuardrailPolicy(self.CFG)
        v = pol.observe(0, loss=float("nan"), finite=False)
        assert v.status == "spike" and v.signal == "finite"
        assert v.z == float("inf")

    def test_grad_norm_signal_judged_independently(self):
        pol = GuardrailPolicy(self.CFG)
        for step in range(8):
            assert pol.observe(step, loss=1.0, grad_norm=2.0).ok
        x = 2.0 + 50.0 * _denom(pol, "grad_norm")
        v = pol.observe(8, loss=1.0, grad_norm=x)  # loss calm, grads explode
        assert v.status == "poisoned" and v.signal == "grad_norm"

    def test_replay_scale_regions(self):
        for replay, inside in (("none", 1.0), ("skip", 0.0), ("clip", 0.1)):
            pol = GuardrailPolicy(GuardrailConfig(replay=replay))
            assert pol.replay_scale(5, (4, 6)) == inside
            assert pol.replay_scale(7, (4, 6)) == 1.0
            assert pol.replay_scale(5, None) == 1.0


# -- DigestVote ---------------------------------------------------------------

class TestDigestVote:
    def test_majority_blames_minority(self):
        vote = DigestVote()
        vote.observe(0, {"4": "a"})  # str keys: heartbeat JSON round-trip
        vote.observe(1, {4: "a"})
        vote.observe(2, {4: "b"})
        assert vote.tally() == VoteResult(4, (2,), {0: "a", 1: "a", 2: "b"})

    def test_two_rank_tie_is_unattributed(self):
        vote = DigestVote()
        vote.observe(0, {3: "a"})
        vote.observe(1, {3: "b"})
        result = vote.tally()
        assert result is not None and result.minority == ()

    def test_single_ring_has_no_quorum(self):
        vote = DigestVote()
        vote.observe(0, {1: "a", 2: "b"})
        assert vote.tally() is None

    def test_agreement_advances_watermark(self):
        vote = DigestVote()
        vote.observe(0, {1: "x", 2: "y"})
        vote.observe(1, {1: "x", 2: "y"})
        assert vote.tally() is None
        assert vote.last_agreed_step == 2
        # A late rewrite of an already-agreed step is never re-judged —
        # the watermark bounds how far back blame (and the checkpoint
        # prune) can reach.
        vote.observe(1, {2: "z"})
        assert vote.tally() is None

    def test_earliest_divergence_wins(self):
        vote = DigestVote()
        vote.observe(0, {5: "a", 7: "a"})
        vote.observe(1, {5: "b", 7: "b"})
        result = vote.tally()
        assert result is not None and result.step == 5

    def test_drop_rank_forgets_stale_digests(self):
        vote = DigestVote()
        vote.observe(0, {4: "a"})
        vote.observe(1, {4: "a"})
        vote.observe(2, {4: "b"})
        assert vote.tally().minority == (2,)
        vote.drop_rank(2)
        # Survivors agree; the departed rank's ring can't out-vote them.
        assert vote.tally() is None


# -- param_digest + bitflip chaos hook ---------------------------------------

class TestParamDigest:
    def _params(self):
        return {
            "w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
            "b": jnp.ones((4,), jnp.float32),
        }

    def test_deterministic_and_value_sensitive(self):
        params = self._params()
        d1 = param_digest(params)
        assert d1 == param_digest(self._params())
        tweaked = dict(params, b=params["b"].at[0].set(2.0))
        assert param_digest(tweaked) != d1

    def test_sample_leaves_bounds_coverage(self):
        params = self._params()
        assert param_digest(params, sample_leaves=1) != param_digest(
            params, sample_leaves=2
        )

    def test_maybe_bitflip_changes_the_digest(self, monkeypatch):
        monkeypatch.delenv("DMT_CHAOS_RANK", raising=False)
        params = self._params()
        clean = param_digest(params)
        chaos = ChaosInjector(FaultPlan.parse("bitflip@step:2"))
        assert chaos.maybe_bitflip(params, step=1) is None  # not yet
        flipped = chaos.maybe_bitflip(params, step=2)
        assert flipped is not None
        # Silent corruption: one mantissa bit, still finite, new digest.
        assert param_digest(flipped) != clean
        assert all(
            bool(jnp.isfinite(leaf).all()) for leaf in jax.tree_util.tree_leaves(flipped)
        )
        assert param_digest(params) == clean  # original tree untouched
        assert chaos.maybe_bitflip(params, step=2) is None  # fire-once

    def test_attach_digest_ring_caps_and_evicts_oldest(self):
        ring: dict[int, str] = {}
        for step in range(20):
            attach_digest_ring(ring, step, f"d{step}", cap=4)
        assert sorted(ring) == [16, 17, 18, 19]


# -- QuarantineLedger ---------------------------------------------------------

class TestQuarantineLedger:
    def test_roundtrip_idempotence_and_persistence(self, tmp_path):
        ledger = QuarantineLedger(tmp_path / "quarantine.json")
        assert 1 not in ledger
        entry = ledger.quarantine(
            1, reason="digest vote minority", step=6, digest="abc123"
        )
        assert entry == {
            "host": "1", "reason": "digest vote minority",
            "step": 6, "digest": "abc123",
        }
        assert 1 in ledger and "1" in ledger and 0 not in ledger
        # Re-blame updates nothing.
        ledger.quarantine(1, reason="again", step=9)
        assert len(ledger.entries) == 1
        # The ledger outlives the supervisor that wrote it.
        reloaded = QuarantineLedger(tmp_path / "quarantine.json")
        assert reloaded.hosts() == {"1"}
        assert reloaded.entries[0]["reason"] == "digest vote minority"

    def test_unreadable_ledger_fails_open(self, tmp_path):
        path = tmp_path / "quarantine.json"
        path.write_text("{not json")
        ledger = QuarantineLedger(path)
        assert ledger.hosts() == set()
        # and it is still writable after the bad read
        ledger.quarantine(3, reason="x")
        assert 3 in QuarantineLedger(path)


# -- Checkpointer: pin retention, rollback, generation fence ------------------

def _corrupting_chaos(from_epoch=1):
    """Stub chaos that corrupts every save from ``from_epoch``; the restore
    path books rollbacks against it, which the stub just swallows."""
    return SimpleNamespace(
        should_corrupt=lambda *, epoch: epoch >= from_epoch,
        record_rollback=lambda *a, **k: True,
        record_recovery=lambda *a, **k: True,
    )


class TestCheckpointRetentionPin:
    def test_pin_survives_retention_with_all_younger_saves_corrupt(
        self, mesh, tmp_path
    ):
        # Regression (PR 18 satellite): max_to_keep used to be allowed to
        # delete the pinned last-known-good once it aged out of the count
        # window — a run where every younger save is corrupt then had
        # nothing verified left to roll back to.
        factory = _lm_factory(mesh)
        state = factory()
        ck = Checkpointer(
            tmp_path / "ck", max_to_keep=2, chaos=_corrupting_chaos()
        )
        try:
            for epoch in range(4):
                ck.save(state, epoch=epoch)
            ck.manager.wait_until_finished()
            # Epoch 0 is the only verified save, pinned OUTSIDE the window
            # of 2; epoch 1 aged out normally.
            assert ck.last_good_epoch() == 0
            assert set(ck.manager.all_steps()) == {0, 2, 3}
            restored, epoch = ck.restore_verified(factory())
            assert epoch == 0
            assert tree_digests({"p": restored.params}) == tree_digests(
                {"p": state.params}
            )
        finally:
            ck.close()

    def test_rollback_to_last_good_discards_younger_steps(self, mesh, tmp_path):
        factory = _lm_factory(mesh)
        ck = Checkpointer(
            tmp_path / "ck", max_to_keep=5, chaos=_corrupting_chaos()
        )
        try:
            for epoch in range(3):
                ck.save(factory(), epoch=epoch)
            restored, epoch = ck.rollback_to_last_good(factory())
            assert epoch == 0
            # Younger (possibly poisoned) checkpoints are GONE — unlike
            # restore_verified's walk, which merely skips them.
            assert ck.manager.all_steps() == [0]
            assert ck._generation == 1  # rollback bumped the fence
        finally:
            ck.close()

    def test_generation_fence_rejects_stale_pin(self, mesh, tmp_path):
        factory = _lm_factory(mesh)
        ck = Checkpointer(tmp_path / "ck", max_to_keep=3)
        try:
            ck.save(factory(), epoch=0)
            ck.rollback_to_last_good(factory())  # generation 0 -> 1
            # The classic anti-rollback attack: swap the pin file for an
            # older copy, hoping to resurrect discarded checkpoints.
            atomic_write_json(
                ck.directory / "last_good.json",
                {"epoch": 0, "generation": 0},
            )
            with pytest.raises(CheckpointCorruption, match="anti-rollback"):
                ck.last_good_epoch()
        finally:
            ck.close()


# -- fault-kind audit (satellite): every kind is wired end to end -------------

class TestFaultKindAudit:
    #: kind -> the ChaosInjector hook that detonates (or books) it. The
    #: supervisor-observed kinds fire through fire_observed: load_spike /
    #: scale_during_failure detonate in serving/fleet.py's autoscale loop,
    #: bitflip's accounting lives in resilience/pod.py's digest vote.
    HOOKS = {
        "nan_grad": "maybe_poison",
        "kill": "check_kill",
        "corrupt_ckpt": "should_corrupt",
        "loader_stall": "loader_fault",
        "loader_die": "loader_fault",
        "loss_spike": "maybe_guard_fault",
        "grad_spike": "maybe_guard_fault",
        "nan_grads": "maybe_guard_fault",
        "bitflip": "maybe_bitflip",
        "rank_kill": "check_rank_fault",
        "rank_hang": "check_rank_fault",
        "serve_crash": "check_serve_crash",
        "handoff_stall": "check_handoff_stall",
        "replica_kill": "check_replica_fault",
        "replica_hang": "check_replica_fault",
        "replica_slow": "check_replica_fault",
        "load_spike": "fire_observed",
        "scale_during_failure": "fire_observed",
        "supervisor_kill": "check_supervisor_fault",
        "supervisor_hang": "check_supervisor_fault",
    }

    ALL_SETS = (
        TRAIN_KINDS, POD_KINDS, GUARD_KINDS, FLEET_KINDS,
        SERVE_KINDS, DISAGG_KINDS, AUTOSCALE_KINDS, CONTROLPLANE_KINDS,
    )

    def test_every_kind_set_is_grammar_parseable(self):
        for kinds in self.ALL_SETS:
            assert kinds <= set(FAULT_UNITS), kinds - set(FAULT_UNITS)

    def test_workload_sets_cover_the_grammar_exactly(self):
        # No orphan kind that parses but no workload would ever validate —
        # such a kind could never fire and its books could never balance.
        covered = (TRAIN_KINDS | FLEET_KINDS | DISAGG_KINDS
                   | AUTOSCALE_KINDS | CONTROLPLANE_KINDS)
        assert covered == set(FAULT_UNITS)

    def test_validate_accepts_each_kind_in_its_workload(self):
        for kinds, workload in (
            (TRAIN_KINDS, "training"),
            (FLEET_KINDS, "fleet"),
            (SERVE_KINDS, "serving"),
            (DISAGG_KINDS, "serving-disagg"),
            (AUTOSCALE_KINDS, "autoscaler"),
            (CONTROLPLANE_KINDS, "controlplane"),
        ):
            spec = ",".join(f"{k}@{FAULT_UNITS[k]}:1" for k in sorted(kinds))
            validate_plan_kinds(spec, kinds, workload=workload)  # no raise
            plan = FaultPlan.parse(spec)  # and the grammar agrees
            assert len(plan) == len(kinds)

    def test_validate_rejects_cross_workload_kind(self):
        with pytest.raises(ValueError, match="no injection hook"):
            validate_plan_kinds(
                "loader_stall@batch:1", SERVE_KINDS, workload="serving"
            )

    def test_every_kind_has_a_live_hook(self):
        assert set(self.HOOKS) == set(FAULT_UNITS)
        for kind, hook in self.HOOKS.items():
            assert callable(getattr(ChaosInjector, hook)), (kind, hook)

    def test_guard_kinds_refuse_a_trainer_without_a_policy(self, mesh):
        chaos = ChaosInjector(FaultPlan.parse("loss_spike@step:1"))
        with pytest.raises(ValueError, match="guardrail"):
            Trainer(
                _lm_factory(mesh)(), "lm", mesh,
                eval_every=1, time_steps=False, chaos=chaos,
            )


# -- trainer integration ------------------------------------------------------

class TestTrainerEpochStats:
    def test_all_nonfinite_epoch_reports_nan_and_leaves_ema_alone(self, mesh):
        # Satellite regression: an epoch where EVERY step trips the finite
        # guard must report NaN (not a perfect-looking 0.0), and the EMA —
        # advanced only on accepted updates — must be byte-identical.
        factory = _lm_factory(mesh, ema=True)
        state = factory()
        state = state.replace(
            params=jax.tree_util.tree_map(
                lambda x: jnp.full_like(x, jnp.nan), state.params
            )
        )
        trainer = Trainer(
            state, "lm", mesh, eval_every=1, time_steps=False, ema_decay=0.5,
        )
        trainer.place_state()
        ema_before = tree_digests({"e": trainer.state.ema_params})
        loader = ShardedLoader(
            SyntheticTokens(16, 32), 8, mesh, shuffle=True, seed=0
        )
        stats = trainer.run_epoch(loader, 0)
        assert math.isnan(stats["loss"])
        assert tree_digests({"e": trainer.state.ema_params}) == ema_before

    def test_partial_nonfinite_epoch_excludes_skipped_steps(self, mesh):
        # One poisoned batch out of two: the mean is over the finite step
        # only, and the reconciliation hook books the skip as the recovery.
        chaos = ChaosInjector(FaultPlan.parse("nan_grad@step:0"))
        trainer = Trainer(
            _lm_factory(mesh)(), "lm", mesh,
            eval_every=1, time_steps=False, chaos=chaos,
        )
        trainer.place_state()
        chaos.bind_registry(trainer.metrics)
        loader = ShardedLoader(
            SyntheticTokens(16, 32), 8, mesh, shuffle=True, seed=0
        )
        stats = trainer.run_epoch(loader, 0)
        assert math.isfinite(stats["loss"])
        assert chaos.balanced() and not chaos.unrecovered()


class TestGuardSpikeAccounting:
    def test_nan_grads_is_tolerated_and_booked_as_recovery(self, mesh, tmp_path):
        from deeplearning_mpi_tpu.utils import config

        factory = _lm_factory(mesh)
        chaos = ChaosInjector(FaultPlan.parse("nan_grads@step:10"))
        ck = Checkpointer(tmp_path / "ck", max_to_keep=5, chaos=chaos)
        trainer = Trainer(
            factory(), "lm", mesh, checkpointer=ck, eval_every=1,
            time_steps=False, chaos=chaos, guardrails=GuardrailPolicy(),
        )
        trainer.place_state()
        chaos.bind_registry(trainer.metrics)
        loader = ResilientLoader(
            ShardedLoader(SyntheticTokens(48, 32), 8, mesh, shuffle=True, seed=0),
            chaos=chaos, batch_timeout_s=10.0, backoff_s=0.01,
        )
        args = SimpleNamespace(
            num_epochs=3, max_restarts=2, eval_only=False, resume=False,
            restart_delay_s=0.01,
        )
        try:
            history = config.execute_training(
                trainer, ck, args, loader, None, 0, state_factory=factory
            )
        finally:
            ck.close()
        # The extended finite guard (grads half) skipped the update; the
        # spike verdict contained it in place — no rollback, no restart.
        assert [h["epoch"] for h in history] == [0, 1, 2]
        assert all(math.isfinite(h["loss"]) for h in history)
        snap = trainer.metrics.snapshot()
        assert snap[FAULT_INJECTED] == 1
        assert snap[RECOVERY] == 1 and snap.get(ROLLBACK, 0) == 0
        assert snap["guard_spike_total"] == 1
        assert snap.get("guard_poisoned_total", 0) == 0
        assert chaos.balanced(), chaos.summary()


class TestLossSpikeRollbackE2E:
    """The tentpole's in-process half: a loss_spike draws a poisoned
    verdict, the run rolls back to the pinned last-known-good and replays
    onto the exact unfaulted trajectory (bit-identical final params)."""

    EPOCHS = 3
    BATCH = 8
    SEQS = 48  # 6 steps/epoch -> 18 total; spike at step 10 = mid-epoch 1

    def _run(self, mesh, tmp_path, chaos_spec=None):
        from deeplearning_mpi_tpu.utils import config

        factory = _lm_factory(mesh)
        loader = ShardedLoader(
            SyntheticTokens(self.SEQS, 32), self.BATCH, mesh,
            shuffle=True, seed=0,
        )
        chaos = ChaosInjector(FaultPlan.parse(chaos_spec)) if chaos_spec else None
        ck = Checkpointer(tmp_path / "ck", max_to_keep=5, chaos=chaos)
        trainer = Trainer(
            factory(), "lm", mesh, checkpointer=ck, eval_every=1,
            time_steps=False, chaos=chaos, guardrails=GuardrailPolicy(),
        )
        trainer.place_state()
        if chaos is not None:
            chaos.bind_registry(trainer.metrics)
            loader = ResilientLoader(
                loader, chaos=chaos, batch_timeout_s=10.0, backoff_s=0.01
            )
        args = SimpleNamespace(
            num_epochs=self.EPOCHS, max_restarts=2, eval_only=False,
            resume=False, restart_delay_s=0.01,
        )
        try:
            history = config.execute_training(
                trainer, ck, args, loader, None, 0, state_factory=factory
            )
        finally:
            ck.close()
        return trainer, chaos, history

    @pytest.fixture(scope="class")
    def spiked_and_clean(self, tmp_path_factory):
        from deeplearning_mpi_tpu.runtime.mesh import create_mesh

        mesh = create_mesh()
        tmp = tmp_path_factory.mktemp("guard_e2e")
        # x1000 loss at step 10 (epoch 1, past the 8-step warmup): robust-z
        # blows through z_poison, the trainer raises RollbackRequested, and
        # the auto-resume closure restores the pinned epoch-0 checkpoint.
        spiked = self._run(mesh, tmp / "spiked", "loss_spike@step:10")
        clean = self._run(mesh, tmp / "clean")
        return spiked, clean

    def test_rollback_replays_onto_unfaulted_trajectory(self, spiked_and_clean):
        (st, _, sh), (ct, _, ch) = spiked_and_clean
        assert int(st.state.step) == self.EPOCHS * (self.SEQS // self.BATCH)
        # Epoch 1 aborted mid-flight at the poisoned verdict, then replayed
        # from the epoch-0 pin — the fired spec stays fired, so the replay
        # eats clean data and rejoins the clean run bit-for-bit.
        assert [h["epoch"] for h in sh] == [0, 1, 2]
        assert tree_digests({"p": st.state.params}) == tree_digests(
            {"p": ct.state.params}
        )
        clean_loss = {h["epoch"]: h["loss"] for h in ch}
        for h in sh:
            assert h["loss"] == clean_loss[h["epoch"]], (
                f"epoch {h['epoch']} diverged after rollback"
            )

    def test_books_reconcile_as_one_rollback(self, spiked_and_clean):
        (trainer, chaos, _), _ = spiked_and_clean
        assert chaos.balanced(), chaos.summary()
        assert not chaos.unrecovered()
        snap = trainer.metrics.snapshot()
        assert snap[FAULT_INJECTED] == 1
        assert snap[ROLLBACK] == 1 and snap.get(RECOVERY, 0) == 0
        assert snap["guard_poisoned_total"] == 1
        assert snap["guard_rollback_total"] == 1
        assert snap["guard_checks_total"] > 0

    def test_clean_run_draws_no_verdicts(self, spiked_and_clean):
        _, (trainer, _, _) = spiked_and_clean
        snap = trainer.metrics.snapshot()
        assert snap["guard_checks_total"] == self.EPOCHS * (self.SEQS // self.BATCH)
        assert snap.get("guard_spike_total", 0) == 0
        assert snap.get("guard_poisoned_total", 0) == 0


# -- costless when off --------------------------------------------------------

class TestCostlessWhenOff:
    def test_step_metrics_carry_no_grad_norm_without_guardrails(self, mesh):
        # guard_metrics=True adds optax.global_norm(grads) to the jitted
        # step — extra FLOPs and an extra device scalar. The default step
        # must not compute it.
        factory = _lm_factory(mesh)
        loader = ShardedLoader(
            SyntheticTokens(16, 32), 8, mesh, shuffle=True, seed=0
        )
        batch = next(iter(loader.epoch(0)))
        _, metrics = make_train_step("lm", donate=False)(factory(), batch)
        assert "grad_norm" not in metrics
        _, metrics = make_train_step("lm", donate=False, guard_metrics=True)(
            factory(), batch
        )
        assert "grad_norm" in metrics

    def test_off_run_never_touches_guardrail_machinery(self, mesh, monkeypatch):
        # Regression lock for the costless-when-off contract: with no
        # policy attached, zero guardrail objects are allocated and no
        # guardrail code runs — every entry point is booby-trapped and a
        # full epoch must still pass. The env pacing knob must also never
        # be read (it lives inside _guard_observe).
        from deeplearning_mpi_tpu.resilience import guardrails as G

        def boom(*args, **kwargs):
            raise AssertionError("guardrail machinery touched in off mode")

        monkeypatch.setattr(G.GuardrailPolicy, "__init__", boom)
        monkeypatch.setattr(G.DigestVote, "__init__", boom)
        monkeypatch.setattr(G, "param_digest", boom)
        monkeypatch.setattr(Trainer, "_guard_observe", boom)
        monkeypatch.setenv("DMT_GUARD_STEP_DELAY_S", "60")
        trainer = Trainer(
            _lm_factory(mesh)(), "lm", mesh, eval_every=1, time_steps=False,
        )
        trainer.place_state()
        loader = ShardedLoader(
            SyntheticTokens(16, 32), 8, mesh, shuffle=True, seed=0
        )
        stats = trainer.run_epoch(loader, 0)
        assert math.isfinite(stats["loss"])
        snapshot = trainer.metrics.snapshot()
        assert not any(k.startswith("guard_") for k in snapshot)
        assert not trainer._digest_ring and not trainer._guard_metrics
