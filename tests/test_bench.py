"""bench.py survives a wedged device probe (ROADMAP item 4 first-fix).

Rounds r03 and r05 of the bench board died WHOLE: a single 120 s
device-probe hang at startup zeroed every number in the round (see
BENCH_r05.json — ``"details": {}``). The fix under test is per-workload
isolation: the parent orchestrator never imports JAX, every workload runs
in its own killable process group behind its own probe, and a wedged
probe records a ``failed`` entry for THAT workload only while the rest of
the round still reports. ``DMT_BENCH_WEDGE_PROBE`` substitutes a
sleep-forever probe child so the drill runs without a TPU or a tunnel.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

# Everything slow is skipped: the drill exercises the orchestration
# (probe -> isolate -> salvage), not the workloads. What remains is
# cifar_32px (whose probe gets wedged) and allreduce (~0 s on one CPU).
FAST_FLAGS = [
    "--platform", "cpu", "--skip_224", "--skip_lm", "--skip_unet",
    "--skip_decode", "--skip_spec", "--probe_timeout", "3",
]


def _run_bench(wedge: str, *extra: str):
    env = dict(os.environ, JAX_PLATFORMS="cpu", DMT_BENCH_WEDGE_PROBE=wedge)
    return subprocess.run(
        [sys.executable, BENCH, *FAST_FLAGS, *extra],
        capture_output=True, text=True, timeout=540, env=env,
    )


class TestWedgedProbe:
    def test_wedged_probe_fails_one_workload_not_the_round(self):
        proc = _run_bench("cifar_32px")
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = proc.stdout.strip().splitlines()
        combined = json.loads(lines[-1])
        details = combined["details"]

        # The wedged workload is marked failed — without ever running its
        # (expensive) child — and the probe budget is named in the entry.
        cifar = details["cifar_32px"]
        assert "probe hung for 3s" in cifar["failed"]
        assert "images_per_s_per_chip" not in cifar

        # Blast radius stops there: the other workload still reports a
        # real number into the SAME combined line the driver parses.
        allreduce = details["allreduce"]
        assert "failed" not in allreduce
        assert combined["allreduce_latency_ms"] is not None

        # The per-workload progress line carried the error too.
        probe_lines = [
            json.loads(l) for l in lines
            if l.startswith("{") and "error" in json.loads(l)
        ]
        assert any("probe hung" in p["error"] for p in probe_lines)

    def test_all_probes_wedged_still_emits_combined_line(self):
        """Even the r05 catastrophe — every probe wedged — must produce
        the final combined line (all values null) with exit 0, so the
        driver records a failed round instead of a missing one. Serving
        workloads are skipped here: with a dead probe they now degrade to
        the CPU harness instead of failing (covered below), and this test
        pins the fail-fast path for the accelerator-bound entries."""
        proc = _run_bench(
            "all", "--skip_fleet", "--skip_disagg", "--skip_prefix"
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        combined = json.loads(proc.stdout.strip().splitlines()[-1])
        assert combined["value"] is None
        assert combined["allreduce_latency_ms"] is None
        for entry in combined["details"].values():
            if isinstance(entry, dict) and "failed" in entry:
                assert "probe hung" in entry["failed"]

    def test_wedged_probe_inside_jax_degrades_serving_to_cpu_harness(self):
        """ROADMAP item 4 second fix: the probe child hangs INSIDE jax
        (``:inside`` — import succeeds, the device query blocks: the shape
        a wedged tunnel actually takes) and the round must still emit
        serving metrics. Control-plane serving workloads rerun on the CPU
        harness, explicitly flagged ``degraded``; accelerator-bound
        workloads keep failing fast."""
        proc = _run_bench("all:inside", "--skip_disagg", "--skip_prefix")
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = proc.stdout.strip().splitlines()
        combined = json.loads(lines[-1])
        details = combined["details"]

        # The serving workload degraded instead of dying: a real recovery
        # number from the CPU harness, with the probe error preserved in
        # the degraded flag so nobody mistakes it for a TPU measurement.
        fleet = details["serving_fleet"]
        assert "failed" not in fleet
        assert fleet["degraded"].startswith("cpu harness fallback:")
        assert "probe hung" in fleet["degraded"]
        assert fleet["failover_recovery_s_p50"] is not None
        assert combined["fleet_failover_recovery_s"] is not None

        # Accelerator-bound entries still fail fast — degradation is for
        # host-side control-plane metrics only.
        assert "probe hung" in details["cifar_32px"]["failed"]
        assert "probe hung" in details["allreduce"]["failed"]

        # The per-workload progress line carries the degraded flag.
        flagged = [
            json.loads(ln) for ln in lines
            if ln.startswith("{") and '"degraded"' in ln
        ]
        assert any(
            p.get("degraded") is True and p.get("value") is not None
            for p in flagged
        )
