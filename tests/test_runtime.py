"""Tests for the runtime layer: bootstrap, mesh, collectives, hello_world."""

import dataclasses
import os
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning_mpi_tpu.runtime import bootstrap, collectives
from deeplearning_mpi_tpu.runtime.compat import shard_map
from deeplearning_mpi_tpu.runtime.hello_world import run_hello_world
from deeplearning_mpi_tpu.runtime.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    MESH_AXES,
    MeshSpec,
    batch_sharding,
    create_mesh,
    local_batch_size,
    replicated_sharding,
)



@dataclasses.dataclass(frozen=True)
class FakeDev:
    """Fake TPU device for mesh-placement tests: the attributes
    order_devices_for_mesh and mesh_utils.create_hybrid_device_mesh read."""

    id: int
    slice_index: int
    coords: tuple = (0, 0, 0)
    core_on_chip: int = 0
    process_index: int = 0
    platform: str = "tpu"
    device_kind: str = "TPU v5e"

class TestBootstrap:
    def test_single_process_init(self):
        topo = bootstrap.init()
        assert topo.process_id == 0
        assert topo.num_processes == 1
        assert topo.global_device_count == 8
        assert topo.is_coordinator

    def test_is_coordinator(self):
        assert bootstrap.is_coordinator()

    def test_system_information(self):
        info = bootstrap.get_system_information()
        assert info["global_device_count"] == 8
        assert info["platform"] == "cpu"
        assert "jax_version" in info

    def test_shutdown_noop_single_process(self):
        bootstrap.shutdown()  # must not raise

    def test_init_reenterable_after_shutdown(self):
        # The elastic re-form path: init -> shutdown -> init must
        # re-rendezvous cleanly (fresh coordinator port the second time).
        # ``jax.distributed.initialize`` refuses to run once the backend is
        # up, and this pytest process initialized its backend long ago — so
        # the round-trip runs in a pristine subprocess.
        import socket
        import subprocess
        import sys
        import textwrap

        def port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        script = textwrap.dedent(
            f"""
            from jax._src import distributed

            from deeplearning_mpi_tpu.runtime import bootstrap

            topo = bootstrap.init(
                coordinator_address="127.0.0.1:{port()}",
                num_processes=1, process_id=0, platform="cpu",
            )
            assert topo.num_processes == 1
            assert distributed.global_state.client is not None
            bootstrap.shutdown()
            assert distributed.global_state.client is None
            bootstrap.shutdown()  # idempotent
            # Second life: a NEW rendezvous on a NEW port must succeed.
            bootstrap.init(
                coordinator_address="127.0.0.1:{port()}",
                num_processes=1, process_id=0, platform="cpu",
            )
            assert distributed.global_state.client is not None
            bootstrap.shutdown()
            print("REENTRY_OK")
            """
        )
        env = dict(os.environ)
        repo = str(Path(__file__).parent.parent)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (repo, env.get("PYTHONPATH", "")) if p
        )
        env.pop("COORDINATOR_ADDRESS", None)
        env.pop("NUM_PROCESSES", None)
        env.pop("PROCESS_ID", None)
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "REENTRY_OK" in proc.stdout


class TestMesh:
    def test_default_mesh_all_data(self):
        mesh = create_mesh()
        assert mesh.axis_names == MESH_AXES
        assert mesh.shape[AXIS_DATA] == 8
        assert all(mesh.shape[a] == 1 for a in MESH_AXES if a != AXIS_DATA)

    def test_data_by_model_mesh(self):
        mesh = create_mesh(MeshSpec(data=4, model=2))
        assert mesh.shape[AXIS_DATA] == 4
        assert mesh.shape[AXIS_MODEL] == 2

    def test_infer_data_degree(self):
        mesh = create_mesh(MeshSpec(model=2))
        assert mesh.shape[AXIS_DATA] == 4

    def test_multislice_order_puts_data_across_slices(self):
        """DCN-aware placement: the data axis advances across slices; the
        inner (ICI) axes never leave a slice."""
        from deeplearning_mpi_tpu.runtime.mesh import order_devices_for_mesh

        # 2 slices x 4 devices, interleaved in the input to prove grouping.
        devs = [FakeDev(i, i % 2) for i in range(8)]
        arr = order_devices_for_mesh(devs, (4, 1, 1, 1, 2))  # dp4 x tp2
        assert arr.shape == (4, 1, 1, 1, 2)
        # Each tp pair lives inside one slice...
        flat_rows = arr.reshape(4, 2)
        for row in flat_rows:
            assert row[0].slice_index == row[1].slice_index
        # ...and data rows 0-1 are slice 0, rows 2-3 slice 1.
        assert [row[0].slice_index for row in flat_rows] == [0, 0, 1, 1]

    def test_multislice_rejects_bad_layouts(self):
        from deeplearning_mpi_tpu.runtime.mesh import order_devices_for_mesh

        devs = [FakeDev(i, i % 3) for i in range(9)]  # 3 slices x 3
        with pytest.raises(ValueError, match="only the data/pipe axes"):
            order_devices_for_mesh(devs, (1, 1, 1, 1, 9))  # tp across slices
        lopsided = [FakeDev(0, 0), FakeDev(1, 0), FakeDev(2, 1)]
        with pytest.raises(ValueError, match="unequal"):
            order_devices_for_mesh(lopsided, (3, 1, 1, 1, 1))

    def test_multislice_pipe_may_span_slices(self):
        """pipe is a DCN-friendly axis (MESH_AXES contract): stages split
        across slices with each slice holding a contiguous stage range."""
        from deeplearning_mpi_tpu.runtime.mesh import order_devices_for_mesh

        devs = [FakeDev(i, i // 4) for i in range(8)]  # 2 slices x 4
        arr = order_devices_for_mesh(devs, (1, 8, 1, 1, 1))  # pp8
        stages = arr.reshape(8)
        assert [d.slice_index for d in stages] == [0, 0, 0, 0, 1, 1, 1, 1]

    def test_single_slice_is_plain_reshape(self):
        from deeplearning_mpi_tpu.runtime.mesh import order_devices_for_mesh

        devs = jax.devices()
        arr = order_devices_for_mesh(devs, (8, 1, 1, 1, 1))
        assert list(arr.ravel()) == list(devs)

    def test_bad_shape_raises(self):
        with pytest.raises(ValueError):
            create_mesh(MeshSpec(data=3, model=2))
        with pytest.raises(ValueError):
            create_mesh(MeshSpec(model=3))

    def test_batch_sharding_places_shards(self, mesh):
        x = jnp.zeros((16, 4, 4, 3))
        sharded = jax.device_put(x, batch_sharding(mesh))
        assert sharded.sharding.is_equivalent_to(batch_sharding(mesh), 4)
        # each device holds 16/8 = 2 rows of the batch
        assert sharded.addressable_shards[0].data.shape == (2, 4, 4, 3)

    def test_replicated_sharding(self, mesh):
        x = jnp.zeros((5, 5))
        sharded = jax.device_put(x, replicated_sharding(mesh))
        assert sharded.addressable_shards[0].data.shape == (5, 5)

    def test_local_batch_size(self, mesh):
        assert local_batch_size(64, mesh) == 64  # single process: all local
        with pytest.raises(ValueError):
            local_batch_size(12, mesh)  # not divisible by dp=8

    def test_local_batch_size_model_parallel_mesh(self):
        # dp=4, tp=2: batch of 4 is valid (one row per data coordinate) and the
        # single process supplies all 4 distinct rows, not 4/len(devices).
        mesh = create_mesh(MeshSpec(data=4, model=2))
        assert local_batch_size(4, mesh) == 4
        assert local_batch_size(8, mesh) == 8


class TestCollectives:
    def _run(self, fn, out_specs, mesh):
        wrapped = shard_map(fn, mesh=mesh, in_specs=P(AXIS_DATA), out_specs=out_specs)
        return jax.jit(wrapped)(jnp.arange(8, dtype=jnp.float32))

    def test_all_reduce_sum(self, mesh):
        out = self._run(lambda x: collectives.all_reduce_sum(x), P(), mesh)
        assert out == pytest.approx(28.0)

    def test_all_reduce_mean(self, mesh):
        out = self._run(lambda x: collectives.all_reduce_mean(x), P(), mesh)
        assert out == pytest.approx(3.5)

    def test_all_reduce_tree(self, mesh):
        tree = {"a": jnp.ones((8,)), "b": jnp.arange(8, dtype=jnp.float32)}
        fn = shard_map(
            collectives.all_reduce_sum,
            mesh=mesh,
            in_specs=({"a": P(AXIS_DATA), "b": P(AXIS_DATA)},),
            out_specs={"a": P(), "b": P()},
        )
        out = jax.jit(fn)(tree)
        assert out["a"] == pytest.approx(8.0)
        assert out["b"] == pytest.approx(28.0)

    def test_ring_shift(self, mesh):
        out = self._run(lambda x: collectives.ring_shift(x), P(AXIS_DATA), mesh)
        # value i moves to slot (i+1) % 8
        np.testing.assert_array_equal(np.asarray(out), np.roll(np.arange(8.0), 1))

    def test_ring_shift_negative_offset(self, mesh):
        out = self._run(
            lambda x: collectives.ring_shift(x, offset=-1), P(AXIS_DATA), mesh
        )
        np.testing.assert_array_equal(np.asarray(out), np.roll(np.arange(8.0), -1))

    def test_broadcast_from(self, mesh):
        out = self._run(lambda x: collectives.broadcast_from(x, src=3), P(AXIS_DATA), mesh)
        np.testing.assert_array_equal(np.asarray(out), np.full(8, 3.0))

    def test_all_gather(self, mesh):
        out = self._run(lambda x: collectives.all_gather(x), P(AXIS_DATA), mesh)
        # every shard gathers the full vector; global result tiles it 8x
        assert out.shape == (64,)
        np.testing.assert_array_equal(np.asarray(out)[:8], np.arange(8.0))

    def test_reduce_scatter(self, mesh):
        # each shard contributes the full 8-vector of ones; scatter-sum gives 8s
        fn = shard_map(
            lambda x: collectives.reduce_scatter(jnp.ones((8,))),
            mesh=mesh,
            in_specs=P(AXIS_DATA),
            out_specs=P(AXIS_DATA),
        )
        out = jax.jit(fn)(jnp.arange(8.0))
        np.testing.assert_array_equal(np.asarray(out), np.full(8, 8.0))


class TestHelloWorld:
    def test_hello_world_passes(self, mesh):
        result = run_hello_world(mesh)
        assert result.n_devices == 8
        assert result.broadcast_ok
        assert result.ring_ok
        assert result.psum_ok
        assert result.ok


class TestMultisliceEquivalence:
    """round-3 verdict weak #5: the claimed equivalence of
    order_devices_for_mesh to jax's own mesh_utils.create_hybrid_device_mesh
    tested against mesh_utils ITSELF (fake devices carrying the slice_index
    + coords attributes it reads), not only hand-built expectations."""

    def _fake_slices(self, n_slices, per_slice):
        # 2x(per_slice//2) physical grid per slice so mesh_utils can factor
        # per-slice logical shapes out of the physical axes.
        return [
            FakeDev(i, i // per_slice, (i % 2, (i % per_slice) // 2, 0))
            for i in range(n_slices * per_slice)
        ]

    def test_dp_x_tp_over_two_slices_matches_mesh_utils(self):
        from jax.experimental import mesh_utils

        from deeplearning_mpi_tpu.runtime.mesh import order_devices_for_mesh

        devs = self._fake_slices(n_slices=2, per_slice=4)
        ours = order_devices_for_mesh(devs, (4, 1, 1, 1, 2)).reshape(4, 2)
        theirs = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(2, 2),      # per-slice (data_in_slice, model)
            dcn_mesh_shape=(2, 1),  # data across slices, model intra-slice
            devices=devs,
        )
        assert [[d.id for d in row] for row in ours] == [
            [d.id for d in row] for row in theirs
        ]

    def test_pure_dp_over_four_slices_matches_mesh_utils(self):
        from jax.experimental import mesh_utils

        from deeplearning_mpi_tpu.runtime.mesh import order_devices_for_mesh

        devs = self._fake_slices(n_slices=4, per_slice=2)
        ours = order_devices_for_mesh(devs, (8, 1, 1, 1, 1)).reshape(8)
        theirs = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(2,), dcn_mesh_shape=(4,), devices=devs
        )
        assert [d.id for d in ours] == [d.id for d in theirs]
