"""Dataset fetch tool tests — all offline (this box has zero egress).

The network path is exercised up to the failure message (parity with the
reference's one-shot prefetch contract, ``pytorch/resnet/download.py:17-18``);
layout validation and scaffolding are tested for real.
"""

import numpy as np
import pytest
from PIL import Image

from deeplearning_mpi_tpu.cli import download


class TestCifar10:
    def test_check_missing(self, tmp_path, capsys):
        assert not download.check_cifar10(tmp_path)
        assert "not found" in capsys.readouterr().out

    def test_check_complete(self, tmp_path):
        batch_dir = tmp_path / "cifar-10-batches-py"
        batch_dir.mkdir()
        for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
            (batch_dir / name).write_bytes(b"x")
        assert download.check_cifar10(tmp_path)

    def test_fetch_failure_cleans_up(self, tmp_path, monkeypatch, capsys):
        """Failed download ⇒ clear error + exit 1, no temp-file litter.
        Hermetic: urlopen is patched to fail, so the test is identical on
        connected and air-gapped machines."""
        import tempfile
        import urllib.error
        import urllib.request

        tmpdir = tmp_path / "tmp"
        tmpdir.mkdir()
        monkeypatch.setattr(tempfile, "tempdir", str(tmpdir))

        def refuse(*a, **kw):
            raise urllib.error.URLError("no route to host")

        monkeypatch.setattr(urllib.request, "urlopen", refuse)
        rc = download.fetch_cifar10(tmp_path / "data", timeout=2.0)
        assert rc == 1
        assert "download failed" in capsys.readouterr().err
        assert list(tmpdir.iterdir()) == []  # partial tarball cleaned up

    def test_cli_check_mode(self, tmp_path):
        assert download.main(["cifar10", "--check", "--data_dir", str(tmp_path)]) == 1


def _mini_cifar_tarball(tmp_path, n=4):
    """Synthesize a loadable cifar-10-python.tar.gz: real pickle batches
    (the format data.cifar10.CIFAR10 reads) with n tiny examples each."""
    import pickle
    import tarfile

    src = tmp_path / "src" / "cifar-10-batches-py"
    src.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        entry = {
            "data": rng.integers(0, 256, (n, 3 * 32 * 32), np.uint8),
            "labels": rng.integers(0, 10, n).tolist(),
        }
        with open(src / name, "wb") as f:
            pickle.dump(entry, f)
    tarball = tmp_path / "cifar-10-python.tar.gz"
    with tarfile.open(tarball, "w:gz") as tar:
        tar.add(src, arcname="cifar-10-batches-py")
    return tarball


class TestFromFile:
    """--from_file: the offline ingest path (round-4 missing #1 — the real
    accuracy run becomes one file-copy away on an air-gapped box)."""

    def test_ingest_verifies_md5_and_extracts_loadable_layout(self, tmp_path):
        tarball = _mini_cifar_tarball(tmp_path)
        digest = download._md5(tarball)
        data_dir = tmp_path / "data"
        rc = download.main([
            "cifar10", "--from_file", str(tarball),
            "--md5", digest, "--data_dir", str(data_dir),
        ])
        assert rc == 0
        assert download.check_cifar10(data_dir)
        # The extracted layout must actually LOAD through the training
        # dataset — same post-extract contract as the download path.
        from deeplearning_mpi_tpu.data.cifar10 import CIFAR10

        ds = CIFAR10(data_dir, train=True)
        assert len(ds) == 20  # 5 batches x 4 examples
        ex = ds[0]
        assert ex["image"].shape == (32, 32, 3)

    def test_ingest_rejects_bad_md5(self, tmp_path, capsys):
        tarball = _mini_cifar_tarball(tmp_path)
        rc = download.main([
            "cifar10", "--from_file", str(tarball),
            "--data_dir", str(tmp_path / "data"),  # default md5 = official
        ])
        assert rc == 1
        assert "md5 mismatch" in capsys.readouterr().err
        assert not (tmp_path / "data" / "cifar-10-batches-py").exists()

    def test_ingest_md5_none_skips_check(self, tmp_path):
        tarball = _mini_cifar_tarball(tmp_path)
        rc = download.main([
            "cifar10", "--from_file", str(tarball),
            "--md5", "none", "--data_dir", str(tmp_path / "data"),
        ])
        assert rc == 0

    def test_ingest_missing_file_fails_cleanly(self, tmp_path, capsys):
        rc = download.main([
            "cifar10", "--from_file", str(tmp_path / "nope.tar.gz"),
            "--md5", "none", "--data_dir", str(tmp_path / "data"),
        ])
        assert rc == 1
        assert "not a file" in capsys.readouterr().err

    def test_from_file_rejected_for_carvana(self, tmp_path):
        with pytest.raises(SystemExit):
            download.main([
                "carvana", "--from_file", str(tmp_path / "x.tar.gz"),
            ])


class TestNoFilterFallback:
    """The pre-filter-API extractall fallback must allowlist member types:
    only regular files and directories extract (a FIFO blocks the next
    directory read; a device node is worse; links redirect later writes)."""

    @pytest.fixture
    def no_filter_api(self, monkeypatch):
        """Force the TypeError fallback path regardless of the running
        python's tarfile version."""
        import tarfile

        orig = tarfile.TarFile.extractall

        def fake(self, *args, **kwargs):
            if "filter" in kwargs:
                raise TypeError(
                    "extractall() got an unexpected keyword argument 'filter'"
                )
            return orig(self, *args, **kwargs)

        monkeypatch.setattr(tarfile.TarFile, "extractall", fake)

    @staticmethod
    def _tarball_with(tmp_path, special):
        import io
        import tarfile

        tarball = tmp_path / "evil.tar.gz"
        with tarfile.open(tarball, "w:gz") as tar:
            ti = tarfile.TarInfo("cifar-10-batches-py/data_batch_1")
            ti.size = 1
            tar.addfile(ti, io.BytesIO(b"x"))
            tar.addfile(special)
        return tarball

    def _special(self, kind):
        import tarfile

        ti = tarfile.TarInfo(f"cifar-10-batches-py/{kind}")
        if kind == "fifo":
            ti.type = tarfile.FIFOTYPE
        elif kind == "chardev":
            ti.type = tarfile.CHRTYPE
            ti.devmajor, ti.devminor = 1, 3  # /dev/null's numbers
        elif kind == "blockdev":
            ti.type = tarfile.BLKTYPE
            ti.devmajor, ti.devminor = 8, 0
        elif kind == "symlink":
            ti.type = tarfile.SYMTYPE
            ti.linkname = "/etc/passwd"
        elif kind == "hardlink":
            ti.type = tarfile.LNKTYPE
            ti.linkname = "../outside"
        return ti

    @pytest.mark.parametrize(
        "kind", ["fifo", "chardev", "blockdev", "symlink", "hardlink"]
    )
    def test_fallback_rejects_non_regular_members(
        self, tmp_path, capsys, no_filter_api, kind
    ):
        tarball = self._tarball_with(tmp_path, self._special(kind))
        data_dir = tmp_path / "data"
        rc = download.ingest_cifar10(tarball, data_dir, md5=None)
        assert rc == 1
        assert "unsafe tar members" in capsys.readouterr().err
        # Refusal is all-or-nothing: nothing extracted, special member least
        # of all.
        assert not (data_dir / "cifar-10-batches-py" / kind).exists()

    def test_fallback_extracts_regular_layout(self, tmp_path, no_filter_api):
        """The allowlist must not over-reject: a normal files+dirs tarball
        still ingests through the fallback."""
        tarball = _mini_cifar_tarball(tmp_path)
        rc = download.ingest_cifar10(tarball, tmp_path / "data", md5=None)
        assert rc == 0
        assert download.check_cifar10(tmp_path / "data")


def _write_pair(root, stem, img_hw=(8, 8), mask_hw=None):
    img = np.zeros((*img_hw, 3), np.uint8)
    mask = np.zeros(mask_hw or img_hw, np.uint8)
    Image.fromarray(img).save(root / "images" / f"{stem}.png")
    Image.fromarray(mask).save(root / "masks" / f"{stem}.png")


class TestCarvana:
    @pytest.fixture()
    def layout(self, tmp_path):
        (tmp_path / "images").mkdir()
        (tmp_path / "masks").mkdir()
        return tmp_path

    def test_scaffold_then_check(self, tmp_path, capsys):
        assert download.main(["carvana", "--data_dir", str(tmp_path)]) == 0
        assert (tmp_path / "images").is_dir() and (tmp_path / "masks").is_dir()
        # Empty scaffold does not validate.
        assert download.main(["carvana", "--check", "--data_dir", str(tmp_path)]) == 1

    def test_valid_pairs(self, layout):
        for stem in ("a", "b"):
            _write_pair(layout, stem)
        assert download.check_carvana(layout)

    def test_unpaired_image(self, layout, capsys):
        _write_pair(layout, "a")
        (layout / "images" / "orphan.png").write_bytes(
            (layout / "images" / "a.png").read_bytes()
        )
        assert not download.check_carvana(layout)
        assert "without a mask" in capsys.readouterr().out

    def test_size_mismatch(self, layout, capsys):
        """The data_loading.py:112-118 invariant, surfaced at fetch time."""
        _write_pair(layout, "a", img_hw=(8, 8), mask_hw=(4, 4))
        assert not download.check_carvana(layout)
        assert "size mismatch" in capsys.readouterr().out

    def test_mask_suffix(self, tmp_path):
        (tmp_path / "images").mkdir()
        (tmp_path / "masks").mkdir()
        img = np.zeros((8, 8, 3), np.uint8)
        Image.fromarray(img).save(tmp_path / "images" / "car1.png")
        Image.fromarray(img[..., 0]).save(tmp_path / "masks" / "car1_mask.png")
        assert download.check_carvana(tmp_path, mask_suffix="_mask")
        assert not download.check_carvana(tmp_path)
