"""Seeded DMT005: raw write-mode open of a JSONL stream outside JsonlSink."""


def start_stream(path):
    return open(path / "events.jsonl", "a")  # seeded: DMT005 — second writer
