"""Seeded DMT002: per-call host state (wall clock) inside a jitted body."""
import time

import jax


@jax.jit
def step(x):
    t = time.time()  # seeded: DMT002 — traced-in wall clock, varies per call
    return x + t
