"""Seeded DMT006: survivors computed AFTER the teardown kill (PR 5 bug)."""


def teardown(procs):
    for p in procs:
        p.kill()
    return [p for p in procs if p.is_alive()]  # seeded: DMT006 — empty world
