"""Clean fixture: near-miss patterns every rule must NOT flag."""
import json

import jax


def run(params, kv):
    step = jax.jit(lambda p, k: (k, p), donate_argnums=(1,))
    kv, out = step(params, kv)  # donated arg rebound by this assignment
    return kv, out


def cold_path(out):
    return jax.device_get(out)  # not a hot scope: no marker, no hot path


def write_report(path, payload):
    path.write_text(json.dumps(payload))  # not IO-critical: no scope marker


def record(registry):
    registry.counter("serve_decode_steps")  # canonical schema name


def stop(procs):
    alive = [p for p in procs if p.poll() is None]
    for p in procs:
        p.kill()  # liveness was snapshotted BEFORE the kill
    return alive
