"""Seeded DMT008: direct wall-clock read in a clock-pure policy scope."""
# dmt-lint: scope=policy
import time


def decide(load_per_replica, threshold):
    now = time.monotonic()  # seeded: DMT008 — breaks fake-clock replay
    return ("up", now) if load_per_replica > threshold else None
