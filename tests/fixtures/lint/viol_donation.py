"""Seeded DMT001: a donated buffer is read after the jitted call (the
PR 3 aliasing bug class, in miniature)."""
import jax


def run(params, kv):
    step = jax.jit(lambda p, k: (k, p), donate_argnums=(1,))
    new_kv, out = step(params, kv)
    return kv.sum()  # seeded: DMT001 — kv was donated at the call above
