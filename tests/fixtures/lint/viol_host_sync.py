"""Seeded DMT003: an unaudited host-device sync inside a marked hot loop."""
import jax


def decode_loop(fn, kv, tokens):  # dmt-lint: hot-loop
    val = None
    for tok in tokens:
        kv, out = fn(kv, tok)
        val = jax.device_get(out)  # seeded: DMT003 — per-step device fetch
    return val
