"""Seeded DMT005: a rogue second writer appending to the supervisor's
write-ahead journal stream. The journal is single-writer by construction
(``resilience/cluster.py::SupervisorJournal`` — incarnation-fenced, one
live append handle); any other ``open(.. "journal.jsonl" ..)`` is a
torn-line hazard the replay discipline cannot defend against."""


def shadow_journal(run_dir):
    return open(run_dir / "journal.jsonl", "a")  # seeded: DMT005 — second journal writer
