"""Seeded DMT007: a metric name missing from the canonical schema."""


def record(registry):
    registry.counter("serve_tokens_genrated")  # seeded: DMT007 — typo'd name
