"""Seeded DMT004: non-atomic JSON write in an IO-critical scope."""
# dmt-lint: scope=resilience
import json


def write_state(path, payload):
    path.write_text(json.dumps(payload))  # seeded: DMT004 — torn-file hazard
