"""Flash-attention kernel tests vs the dense oracle.

Runs the Pallas interpreter on CPU (``interpret`` auto-selects off-TPU) —
same kernel code path the TPU compiles, minus Mosaic lowering, which the
real-chip benchmark exercises.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.ops import dense_attention, flash_attention


def qkv(B=2, S=64, H=2, D=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_forward_matches_dense(causal):
    q, k, v = qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
@pytest.mark.slow
def test_grads_match_dense(causal):
    q, k, v = qkv(S=32)

    def loss(attn, q, k, v):
        return jnp.sum(attn(q, k, v, causal=causal) ** 2)

    flash = lambda q, k, v, causal=causal: flash_attention(  # noqa: E731
        q, k, v, causal=causal, block_q=16, block_k=16
    )
    g_ref = jax.grad(loss, argnums=(1, 2, 3))(dense_attention, q, k, v)
    g_out = jax.grad(loss, argnums=(1, 2, 3))(flash, q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_rectangular_blocks():
    """block_q != block_k exercises the off-diagonal causal skip logic."""
    q, k, v = qkv(S=64)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=16)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("bq,bk", [(32, 16), (16, 32)], ids=["wide_q", "wide_k"])
def test_rectangular_block_grads(bq, bk):
    """Gradients with block_q != block_k: locks in the two backward kernels'
    asymmetric causal skip predicates (dq streams kv blocks, dkv streams q
    blocks with swapped grid axes — an off-by-one near the diagonal would
    silently zero tiles in one of them but not the other)."""
    q, k, v = qkv(S=64)

    def loss(attn, q, k, v):
        return jnp.sum(attn(q, k, v) ** 2)

    flash = lambda q, k, v: flash_attention(  # noqa: E731
        q, k, v, causal=True, block_q=bq, block_k=bk
    )
    dense = lambda q, k, v: dense_attention(q, k, v, causal=True)  # noqa: E731
    g_ref = jax.grad(loss, argnums=(1, 2, 3))(dense, q, k, v)
    g_out = jax.grad(loss, argnums=(1, 2, 3))(flash, q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


class TestSlidingWindow:
    """Windowed (local) flash attention vs the windowed dense oracle.

    Window sizes are chosen against the 16-wide blocks to hit every gating
    case: window inside one block (8), window == block (16), window
    spanning blocks at a non-block-multiple (24), and window >= seq
    (degenerates to plain causal). The dense oracle's own window mask is
    three lines of iota arithmetic, independently checkable by eye."""

    # Rectangular (block_q != block_k) pairs exercise the asymmetric
    # span/anchor arithmetic of the trimmed grid (ADVICE r4: square-only
    # coverage left the bq != bk branches untested).
    @pytest.mark.parametrize("block_q,block_k", [(16, 16), (8, 32), (32, 8)])
    @pytest.mark.parametrize("window", [8, 16, 24, 56, 1000])
    def test_forward_matches_windowed_dense(self, window, block_q, block_k):
        q, k, v = qkv()
        out = flash_attention(
            q, k, v, causal=True, window=window,
            block_q=block_q, block_k=block_k,
        )
        ref = dense_attention(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_window_actually_masks(self):
        """Guards against a no-op window: out-of-window keys must not
        influence the output (perturb a stale key -> output unchanged)."""
        q, k, v = qkv(S=64)
        out = flash_attention(
            q, k, v, causal=True, window=8, block_q=16, block_k=16
        )
        k2 = k.at[:, 0].add(100.0)  # key 0 is outside every window for t >= 8
        v2 = v.at[:, 0].add(100.0)
        out2 = flash_attention(
            q, k2, v2, causal=True, window=8, block_q=16, block_k=16
        )
        np.testing.assert_allclose(
            np.asarray(out[:, 8:]), np.asarray(out2[:, 8:]), atol=2e-5
        )
        assert not np.allclose(np.asarray(out[:, :8]), np.asarray(out2[:, :8]))

    # Windows 50/56 are the near-sequence regime (window >= S - block_q + 2
    # = 50 here): the dkv kernel's trimmed-grid anchor overshoots the last
    # real q block and must be clamped BEFORE the span subtraction —
    # unclamped, dk/dv silently dropped the earliest in-window q blocks
    # (found by review, verified numerically: O(1) absolute dk/dv error).
    @pytest.mark.slow
    @pytest.mark.parametrize("block_q,block_k", [(16, 16), (8, 16), (16, 8)])
    @pytest.mark.parametrize("window", [8, 24, 50, 56])
    def test_grads_match_windowed_dense(self, window, block_q, block_k):
        q, k, v = qkv(S=64)

        def loss(attn, q, k, v):
            return jnp.sum(attn(q, k, v) ** 2)

        flash = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, window=window,
            block_q=block_q, block_k=block_k,
        )
        dense = lambda q, k, v: dense_attention(  # noqa: E731
            q, k, v, causal=True, window=window
        )
        g_ref = jax.grad(loss, argnums=(1, 2, 3))(dense, q, k, v)
        g_out = jax.grad(loss, argnums=(1, 2, 3))(flash, q, k, v)
        for a, b in zip(g_out, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_bhsd_entry_matches(self):
        from deeplearning_mpi_tpu.ops.pallas.flash_attention import (
            flash_attention_bhsd,
        )

        q, k, v = qkv()
        qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        out = flash_attention_bhsd(
            qh, kh, vh, causal=True, window=24, block_q=16, block_k=16
        ).transpose(0, 2, 1, 3)
        ref = dense_attention(q, k, v, causal=True, window=24)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_window_requires_causal(self):
        q, k, v = qkv()
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=8)
        with pytest.raises(ValueError, match="causal"):
            dense_attention(q, k, v, causal=False, window=8)


class TestShiftedWindow:
    """The static ``shift`` (q-position offset) the ring's off-diagonal
    rotations use: queries sit ``shift = t * s_local`` positions after the
    visiting K/V block. Oracle: ``dense_attention(q_offset=shift)``."""

    @pytest.mark.parametrize("block_q,block_k", [(16, 16), (8, 32), (32, 8)])
    @pytest.mark.parametrize("shift,window", [
        (64, 40),    # partial overlap; rows 39.. fully masked (zero rows)
        (64, 80),    # every row keeps some in-window keys
        (64, 200),   # rotation fully inside the window (mask all-true)
        (128, 150),  # distance-2 rotation, partial overlap
    ])
    def test_forward_matches_offset_dense(self, shift, window,
                                          block_q, block_k):
        from deeplearning_mpi_tpu.ops.pallas.flash_attention import (
            flash_fwd_block,
        )

        q, k, v = qkv()
        out, _ = flash_fwd_block(
            q, k, v, True, block_q, block_k, True, with_lse=False,
            window=window, shift=shift,
        )
        ref = dense_attention(
            q, k, v, causal=True, window=window, q_offset=shift
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.slow
    @pytest.mark.parametrize("shift,window", [(64, 80), (64, 200)])
    def test_backward_matches_offset_dense(self, shift, window):
        """Shifted backward vs dense-oracle grads. Windows keep every q row
        at least one valid key (window > shift): a standalone single-block
        call has no global lse to rescue fully-masked rows (p = exp(0) = 1
        garbage, the documented _tile_p_ds caveat) — the RING covers that
        regime end-to-end with its finite global lse."""
        from deeplearning_mpi_tpu.ops.pallas.flash_attention import (
            flash_bwd_block,
            flash_fwd_block,
        )

        q, k, v = qkv(S=64)
        o, lse = flash_fwd_block(
            q, k, v, True, 16, 16, True, with_lse=True,
            window=window, shift=shift,
        )

        def dense_loss(q, k, v):
            return jnp.sum(dense_attention(
                q, k, v, causal=True, window=window, q_offset=shift
            ) ** 2)

        g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        do = 2.0 * o
        g_out = flash_bwd_block(
            q, k, v, o, do, lse, True, 16, 16, True,
            window=window, shift=shift,
        )
        for a, b in zip(g_out, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_indivisible_seq_falls_back_to_dense():
    q, k, v = qkv(S=48)  # 48 % 32 != 0 after clamping
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_single_block():
    """S smaller than the block size clamps to one block."""
    q, k, v = qkv(S=16)
    out = flash_attention(q, k, v, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_bf16_io_f32_accumulation():
    q, k, v = qkv(S=32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, causal=True, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


class TestBwdBlockCap:
    """fit_bwd_blocks: the backward tile must fit the 16 MiB scoped-VMEM
    stack (hit on chip: 64k-seq f32 train_lm, 17.75 MB > 16 MB compile
    error; see _BWD_TILE_BYTES_BUDGET)."""

    def test_f32_default_blocks_shrink(self):
        from deeplearning_mpi_tpu.ops.pallas.flash_attention import fit_bwd_blocks

        bq, bk = fit_bwd_blocks(1024, 1024, jnp.float32)
        # 1024x1024 f32 measured over-limit; one halving must occur and the
        # result must stay sublane-aligned and a power-of-two divisor.
        assert (bq, bk) == (512, 1024)

    def test_bf16_default_blocks_survive(self):
        from deeplearning_mpi_tpu.ops.pallas.flash_attention import fit_bwd_blocks

        # bf16 1024x1024 compiles on chip (the measured-fast config for the
        # whole LM baseline table) — the cap must NOT regress it.
        assert fit_bwd_blocks(1024, 1024, jnp.bfloat16) == (1024, 1024)

    def test_small_blocks_untouched(self):
        from deeplearning_mpi_tpu.ops.pallas.flash_attention import fit_bwd_blocks

        assert fit_bwd_blocks(256, 256, jnp.float32) == (256, 256)

    @pytest.mark.slow
    def test_grads_exact_through_capped_path(self):
        """An over-budget block request is capped inside _bwd_pallas; the
        gradient must be unchanged vs the dense oracle (block size is a
        schedule choice, never a semantics choice)."""
        import importlib

        # The package __init__ rebinds the `flash_attention` attribute to
        # the function, so `import ... as` would grab the function.
        fa_mod = importlib.import_module(
            "deeplearning_mpi_tpu.ops.pallas.flash_attention"
        )

        q, k, v = qkv(S=64)

        def loss(attn, q, k, v):
            return jnp.sum(attn(q, k, v, causal=True) ** 2)

        # Force the cap to trigger at this tiny size by shrinking the budget
        # so a 32x32 f32 tile is "over" (32*32*18 > 16384).
        orig = fa_mod._BWD_TILE_BYTES_BUDGET
        fa_mod._BWD_TILE_BYTES_BUDGET = 16384
        try:
            flash = lambda q, k, v, causal=True: fa_mod.flash_attention(  # noqa: E731
                q, k, v, causal=causal, block_q=32, block_k=32
            )
            g_out = jax.grad(loss, argnums=(1, 2, 3))(flash, q, k, v)
        finally:
            fa_mod._BWD_TILE_BYTES_BUDGET = orig
        g_ref = jax.grad(loss, argnums=(1, 2, 3))(dense_attention, q, k, v)
        for a, b in zip(g_out, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ulysses_with_flash_inner():
    """Flash kernel as the inner core of all-to-all sequence parallelism."""
    from deeplearning_mpi_tpu.parallel import make_ulysses_attention_fn
    from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

    mesh = create_mesh(MeshSpec(data=2, seq=4))
    q, k, v = qkv(B=4, S=64, H=4)
    inner = lambda q, k, v, causal: flash_attention(  # noqa: E731
        q, k, v, causal=causal, block_q=16, block_k=16
    )
    fn = make_ulysses_attention_fn(mesh, inner=inner)
    out = fn(q, k, v, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestBHSDNativeEntry:
    """flash_attention_bhsd: the zero-transpose layout path."""

    def _bhsd(self, B=2, S=64, H=2, D=16, seed=0):
        rng = np.random.default_rng(seed)
        return tuple(
            jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
            for _ in range(3)
        )

    @pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
    def test_forward_matches_bshd_entry(self, causal):
        from deeplearning_mpi_tpu.ops.pallas import flash_attention_bhsd

        q, k, v = self._bhsd()
        swap = lambda x: x.transpose(0, 2, 1, 3)  # noqa: E731
        out = flash_attention_bhsd(
            q, k, v, causal=causal, block_q=16, block_k=16
        )
        ref = flash_attention(
            swap(q), swap(k), swap(v), causal=causal, block_q=16, block_k=16
        )
        np.testing.assert_allclose(
            np.asarray(swap(out)), np.asarray(ref), atol=1e-6
        )

    @pytest.mark.slow
    def test_grads_match_dense_oracle(self):
        from deeplearning_mpi_tpu.ops.pallas import flash_attention_bhsd

        q, k, v = self._bhsd(S=32)
        swap = lambda x: x.transpose(0, 2, 1, 3)  # noqa: E731

        def loss_bhsd(q, k, v):
            return jnp.sum(flash_attention_bhsd(q, k, v, block_q=16, block_k=16) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(swap(q), swap(k), swap(v)) ** 2)

        g_out = jax.grad(loss_bhsd, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_out, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_untileable_seq_falls_back_to_dense(self):
        from deeplearning_mpi_tpu.ops.pallas import flash_attention_bhsd

        q, k, v = self._bhsd(S=20)  # 20 rows: not sublane-tileable
        swap = lambda x: x.transpose(0, 2, 1, 3)  # noqa: E731
        out = flash_attention_bhsd(q, k, v, causal=True)
        ref = dense_attention(swap(q), swap(k), swap(v), causal=True)
        np.testing.assert_allclose(
            np.asarray(swap(out)), np.asarray(ref), atol=2e-5
        )

    def test_layout_attribute(self):
        from deeplearning_mpi_tpu.ops.pallas import flash_attention_bhsd

        assert flash_attention_bhsd.layout == "bhsd"
        assert getattr(flash_attention, "layout", "bshd") == "bshd"
