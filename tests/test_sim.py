"""Load-harness tests: trace generator determinism and schema, fake-clock
simulator conservation/determinism/calibration, the predictive-vs-reactive
A/B, and the policy sweep's TuningDB round-trip.

Everything here is pure host Python on a fake clock — no JAX, no
subprocesses. The real-process half (predictive warm-up beating a live
flash crowd) is ``tools/sim_drill.py --phase predictive`` / ``make
sim-smoke``.

The A/B test encodes the regime finding the drill is built on: a trend
forecast only has signal when the fleet carries CONTINUOUS load (slow
decodes, slots near saturation). An idle fleet turns any ramp into a
0-to-avalanche step in the load signal, and on a step the forecaster's
smoothing lag cancels its trend lead — the arms tie by construction.
"""

import json

import numpy as np
import pytest

from deeplearning_mpi_tpu.serving.autoscaler import (
    AutoscalerConfig,
    LoadForecaster,
)
from deeplearning_mpi_tpu.sim import (
    FlashCrowd,
    FleetSimulator,
    ServiceModel,
    SimConfig,
    TenantSpec,
    TraceConfig,
    apply_params,
    generate_entries,
    run_sweep,
    tenant_policies,
    to_fleet_entries,
    trace_digest,
    write_jsonl,
)


def _small_cfg(**kw):
    base = dict(
        duration_s=120.0,
        base_rps=5.0,
        diurnal_period_s=120.0,
        diurnal_amplitude=0.3,
        burst_rate_per_s=0.01,
        flash_crowds=(
            FlashCrowd(at_s=60.0, amplitude=5.0, ramp_s=8.0, decay_s=5.0),
        ),
        tenants=(
            TenantSpec("free", share=2.0, priority=0.0),
            TenantSpec("pro", share=1.0, priority=2.0, budget_tokens=4096),
        ),
    )
    base.update(kw)
    return TraceConfig(**base)


class TestTraceGenerator:
    def test_same_seed_same_entries(self):
        cfg = _small_cfg()
        a = generate_entries(cfg, seed=7)
        b = generate_entries(cfg, seed=7)
        assert a == b
        assert trace_digest(a) == trace_digest(b)

    def test_different_seed_different_trace(self):
        cfg = _small_cfg()
        assert trace_digest(generate_entries(cfg, seed=1)) != trace_digest(
            generate_entries(cfg, seed=2)
        )

    def test_write_jsonl_byte_identical(self, tmp_path):
        entries = generate_entries(_small_cfg(), seed=3)
        p1 = write_jsonl(entries, tmp_path / "a.jsonl")
        p2 = write_jsonl(entries, tmp_path / "b.jsonl")
        assert p1.read_bytes() == p2.read_bytes()

    def test_entries_sorted_and_schema(self):
        entries = generate_entries(_small_cfg(), seed=0)
        assert entries, "empty trace"
        arrivals = [e["arrival"] for e in entries]
        assert arrivals == sorted(arrivals)
        for e in entries[:50]:
            assert set(e) <= {"arrival", "prompt", "max_new", "tenant",
                              "deadline"}
            assert isinstance(e["prompt"], str) and e["prompt"]
            assert e["max_new"] >= 1
            assert e["tenant"] in ("free", "pro")

    def test_flash_crowd_raises_local_rate(self):
        cfg = _small_cfg(diurnal_amplitude=0.0, burst_rate_per_s=0.0)
        entries = generate_entries(cfg, seed=0)
        arrivals = np.array([e["arrival"] for e in entries])
        crowd = ((arrivals >= 55.0) & (arrivals < 65.0)).sum() / 10.0
        calm = (arrivals < 40.0).sum() / 40.0
        assert crowd > 2.0 * calm, (crowd, calm)

    def test_adversarial_tenant_storms_and_tight_deadlines(self):
        cfg = _small_cfg(
            tenants=(
                TenantSpec("good", share=1.0, deadline_s=8.0,
                           deadline_jitter=0.0),
                TenantSpec("bot", share=1.0, deadline_s=8.0,
                           deadline_jitter=0.0, adversarial=True,
                           storm_window_s=10.0),
            ),
        )
        entries = generate_entries(cfg, seed=0)
        bot = [e for e in entries if e["tenant"] == "bot"]
        good = [e for e in entries if e["tenant"] == "good"]
        assert bot and good
        # Storm re-clustering halves the deadline for the adversary.
        assert max(e["deadline"] for e in bot) < min(
            e["deadline"] for e in good
        )

    def test_tenant_policies_mirror_specs(self):
        cfg = _small_cfg()
        pol = tenant_policies(cfg)
        assert pol["pro"] == {"budget_tokens": 4096, "priority": 2.0}
        assert pol["free"] == {"budget_tokens": 0, "priority": 0.0}

    def test_serve_lm_replay_round_trip(self, tmp_path):
        """write_jsonl output must load through the REAL serve_lm trace
        loader, token-for-token equal to to_fleet_entries — both replay
        paths see identical streams."""
        from deeplearning_mpi_tpu.cli.serve_lm import _load_trace

        entries = generate_entries(_small_cfg(), seed=5)[:200]
        path = write_jsonl(entries, tmp_path / "trace.jsonl")
        loaded = _load_trace(str(path), 16, 0.0)
        fleet = to_fleet_entries(entries)
        assert len(loaded) == len(fleet) == 200
        for le, fe in zip(loaded, fleet):
            assert le["arrival"] == fe["arrival"]
            assert le["max_new"] == fe["max_new"]
            assert le["tenant"] == fe["tenant"]
            assert list(le["prompt"]) == fe["prompt"]

    def test_fleet_entries_are_plain_json(self):
        fleet = to_fleet_entries(generate_entries(_small_cfg(), seed=0))
        json.dumps(fleet[:20])  # numpy scalars would raise


def _sim_cfg(**kw):
    base = dict(
        initial_replicas=2,
        max_slots=8,
        autoscale=AutoscalerConfig(
            min_replicas=1, max_replicas=4,
            up_load_per_replica=4.0, down_load_per_replica=0.5,
            hysteresis_s=0.4, cooldown_s=1.5,
        ),
    )
    base.update(kw)
    return SimConfig(**base)


class TestSimulator:
    @pytest.fixture(scope="class")
    def entries(self):
        return to_fleet_entries(generate_entries(_small_cfg(), seed=0))

    def test_books_balance(self, entries):
        res = FleetSimulator(_sim_cfg()).run(entries)
        assert res.requests == len(entries)
        assert res.completed + res.shed_total == res.requests
        assert res.completed > 0

    def test_deterministic(self, entries):
        cfg = _sim_cfg()
        a = FleetSimulator(cfg).run(entries)
        b = FleetSimulator(cfg).run(entries)
        assert a.summary() == b.summary()
        assert a.curves == b.curves

    def test_scale_books_reconcile(self, entries):
        res = FleetSimulator(_sim_cfg()).run(entries)
        # The policy fired at least once on the flash crowd, and every
        # replica-second is accounted (fleet never below the floor).
        assert res.scale_ups >= 1
        assert res.replica_seconds > 0
        assert res.slo_per_chip == pytest.approx(
            res.slo_ok / res.replica_seconds
        )

    def test_tenant_budget_sheds_flow_through(self, entries):
        cfg = _sim_cfg(tenants={"free": {"budget_tokens": 64,
                                         "priority": 0.0},
                                "pro": {"budget_tokens": 0,
                                        "priority": 2.0}})
        res = FleetSimulator(cfg).run(entries)
        assert res.shed.get("tenant_budget", 0) > 0
        assert res.completed + res.shed_total == res.requests

    def test_hedging_counts(self, entries):
        cfg = _sim_cfg(hedge_ms=200.0)
        res = FleetSimulator(cfg).run(entries)
        assert res.completed + res.shed_total == res.requests
        # Hedges fire on the crowd's tail latencies; losers are cancelled.
        assert res.hedges_fired >= 0  # smoke: accounting stays coherent

    def test_summary_keys_are_canonical_names(self, entries):
        from deeplearning_mpi_tpu.telemetry.schema import METRICS

        res = FleetSimulator(_sim_cfg()).run(entries)
        s = res.summary()
        for name in ("sim_requests_total", "sim_completed_total",
                     "sim_shed_total", "sim_slo_ok_total",
                     "sim_replica_seconds", "sim_slo_attainment",
                     "sim_hedge_fired_total", "sim_brownout_max_stage"):
            assert name in s
            assert name in METRICS


class TestServiceModel:
    def test_from_telemetry_round_trip(self):
        m = ServiceModel.from_telemetry(
            ttft_p50_s=0.08, tpot_p50_s=0.02, mean_prompt_len=40,
            warmup_s=2.0,
        )
        # The measured medians must be reproducible at calibration
        # conditions (single active request, no prefix hit).
        assert m.ttft_s(40, active=1, max_slots=8,
                        prefix_hit=False) == pytest.approx(0.08, rel=0.01)
        assert m.tpot_s == pytest.approx(0.02)
        assert m.warmup_s == 2.0

    def test_batch_factor_monotonic(self):
        m = ServiceModel()
        f = [m.batch_factor(a, 8) for a in (1, 2, 4, 8)]
        assert f == sorted(f)
        assert f[0] == 1.0

    def test_prefix_hit_cuts_prefill(self):
        m = ServiceModel()
        hit = m.ttft_s(200, active=1, max_slots=8, prefix_hit=True)
        miss = m.ttft_s(200, active=1, max_slots=8, prefix_hit=False)
        assert hit < miss

    def test_calibrated_sim_matches_measured_surge_drill(self):
        """The autoscale-drill surge trace (32-deep burst + 20-trickle,
        max_new=12) through the simulator, with the ServiceModel
        calibrated from that drill's own measured telemetry. Reference
        numbers from ``tools/autoscale_drill.py --fault surge`` on a warm
        CPU (fleet_metrics.jsonl fleet_summary, 2026-08-07): unloaded
        TTFT p50 0.078 s, during-burst TTFT p50 10.1-11.4 s across
        replicas, 0 sheds, 0 drops, scale-up fired, drain-retire on the
        tail. The sim must land in the same regime — generous tolerance
        (the drill also carries a chaos kill + an 8-request load_spike
        the sim does not model)."""
        rng = np.random.default_rng(7)
        entries = []
        for i in range(52):
            n_prompt = int(rng.integers(3, 21))
            entries.append({
                "arrival": 0.0 if i < 32 else (i - 32 + 1) * 0.8,
                "prompt": [int(t) for t in rng.integers(1, 256,
                                                        size=n_prompt)],
                "max_new": 12,
            })
        service = ServiceModel.from_telemetry(
            ttft_p50_s=0.078, tpot_p50_s=0.05, mean_prompt_len=12,
            warmup_s=8.0,
        )
        cfg = SimConfig(
            initial_replicas=1,
            max_slots=3,
            max_queue=64,
            kv_blocks=32,
            kv_block_size=8,
            service=service,
            autoscale=AutoscalerConfig(
                min_replicas=1, max_replicas=3,
                up_load_per_replica=3.0, down_load_per_replica=0.25,
                hysteresis_s=0.2, cooldown_s=0.8,
            ),
            slo_ttft_s=30.0,
        )
        res = FleetSimulator(cfg).run(entries)
        assert res.shed_total == 0, res.shed  # measured: 0 sheds
        assert res.completed == 52
        assert res.scale_ups >= 1  # measured: the burst fires the up arm
        p50 = res.ttft_quantile(0.5)
        # Measured burst-window p50 was ~10.5 s; the sim blends burst and
        # trickle completions, so accept the 2x band around the burst
        # figure's half (the trickle's sub-second TTFTs drag the blended
        # median down, exactly as ttft_after_p50=3.0 s did in the drill).
        assert 1.5 < p50 < 21.0, p50
        assert res.ttft_quantile(0.95) < 25.0, res.ttfts


class TestPredictiveAB:
    def test_predictive_beats_reactive_under_continuous_load(self):
        """The tentpole claim, in miniature: same trace, same fleet, only
        ``predictive`` differs — the forecast arm must scale earlier and
        convert that lead into strictly more SLO-attained completions."""
        cfg = TraceConfig(
            duration_s=180.0,
            base_rps=6.0,
            diurnal_period_s=180.0,
            diurnal_amplitude=0.3,
            burst_rate_per_s=0.0,
            flash_crowds=(
                FlashCrowd(at_s=108.0, amplitude=6.0, ramp_s=12.0,
                           decay_s=8.0),
            ),
            tenants=(TenantSpec("default", output_mean=32,
                                deadline_s=10.0),),
        )
        entries = to_fleet_entries(generate_entries(cfg, seed=0))

        def arm(predictive):
            sim_cfg = SimConfig(
                initial_replicas=3,
                max_slots=4,
                service=ServiceModel(tpot_s=0.05),
                autoscale=AutoscalerConfig(
                    min_replicas=2, max_replicas=8,
                    up_load_per_replica=6.0, down_load_per_replica=1.0,
                    hysteresis_s=0.4, cooldown_s=2.0,
                    predictive=predictive, forecast_horizon_s=3.0,
                    forecast_tau_s=1.0, forecast_trend_tau_s=2.0,
                ),
            )
            return FleetSimulator(sim_cfg).run(entries)

        reactive = arm(False)
        predictive = arm(True)
        assert predictive.slo_ok > reactive.slo_ok, (
            predictive.summary(), reactive.summary()
        )
        assert predictive.up_times and reactive.up_times
        assert predictive.up_times[0] <= reactive.up_times[0]


class TestForecaster:
    def test_needs_two_observations(self):
        f = LoadForecaster(tau_s=1.0, trend_tau_s=1.0)
        assert f.forecast(0.0, 1.0) is None
        f.observe(0.0, 2.0)
        assert f.forecast(0.0, 1.0) is None
        f.observe(1.0, 2.0)
        assert f.forecast(1.0, 1.0) is not None

    def test_constant_load_flat_forecast(self):
        f = LoadForecaster(tau_s=1.0, trend_tau_s=1.0)
        for i in range(50):
            f.observe(i * 0.5, 4.0)
        assert f.forecast(25.0, 5.0) == pytest.approx(4.0, abs=0.1)

    def test_ramp_projects_above_current(self):
        f = LoadForecaster(tau_s=1.0, trend_tau_s=1.0)
        for i in range(50):
            f.observe(i * 0.5, 1.0 + i * 0.5)
        last = 1.0 + 49 * 0.5
        assert f.forecast(24.5, 5.0) > last


class TestSweep:
    @pytest.fixture(scope="class")
    def entries(self):
        cfg = _small_cfg(duration_s=60.0)
        return to_fleet_entries(generate_entries(cfg, seed=0))

    def test_winner_recorded_and_deterministic(self, entries, tmp_path):
        from deeplearning_mpi_tpu.compiler.autotune import TuningDB

        grid = [{}, {"hysteresis_s": 0.2},
                {"predictive": True, "forecast_horizon_s": 2.0}]
        db_path = tmp_path / "db.json"
        a = run_sweep(entries, _sim_cfg(), grid, trace_key="t1",
                      db=db_path)
        b = run_sweep(entries, _sim_cfg(), grid, trace_key="t1")
        assert a.winner == b.winner
        assert [t["score"] for t in a.trials] == [
            t["score"] for t in b.trials
        ]
        assert a.winner_score >= a.baseline_score
        assert TuningDB.load(db_path).lookup_key(a.key) == a.winner

    def test_apply_params_routes_fields(self):
        base = _sim_cfg()
        out = apply_params(base, {"hysteresis_s": 0.9, "hedge_ms": 50.0})
        assert out.autoscale.hysteresis_s == 0.9
        assert out.hedge_ms == 50.0
        assert base.autoscale.hysteresis_s == 0.4  # original untouched

    def test_apply_params_rejects_unknown_key(self):
        with pytest.raises(ValueError):
            apply_params(_sim_cfg(), {"no_such_knob": 1})
