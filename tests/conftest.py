"""Test harness: 8 virtual CPU devices, no TPU required.

The reference tests multi-node behavior without a GPU cluster by running N
Gloo processes on one machine (``pytorch/hello_world/hello_world.py:19-22,44``
— SURVEY.md §4). The JAX equivalent is a single process with N fake CPU
devices via ``--xla_force_host_platform_device_count``, giving every mesh /
collective / sharding test a real 8-way SPMD execution on any machine.

Must run before the first JAX backend initialization: the environment pins
``JAX_PLATFORMS`` via a sitecustomize hook, so we both set the env vars and
force the config, which wins as long as no array op has run yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's cost is dominated by XLA:CPU
# compiles of many distinct jitted programs on this box's single core, and
# the cache works for CPU executables too (measured: a tiny-ResNet
# init+apply drops 21.7s -> 4.0s process wall on the second run). First run
# populates `.jax_cache/` (gitignored); every later run — including the
# driver's — pays only trace time for unchanged programs. A changed program
# gets a new key, so the cache can't mask a code change.
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_virtual_mesh():
    assert jax.device_count() == 8, (
        "tests require 8 virtual CPU devices; got "
        f"{jax.device_count()} on {jax.devices()[0].platform}"
    )
    yield


@pytest.fixture()
def mesh():
    from deeplearning_mpi_tpu.runtime.mesh import create_mesh

    return create_mesh()
