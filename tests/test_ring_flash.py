"""Ring attention with the Pallas flash inner vs the dense oracle.

Runs the Pallas interpreter inside an 8-virtual-device CPU shard_map ring —
the same no-hardware trick as the rest of the sequence-parallel suite
(SURVEY.md §4), with small blocks so every shard tiles into multiple kernel
grid steps and the cross-rotation logsumexp merge is actually exercised.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.ops.attention import dense_attention
from deeplearning_mpi_tpu.parallel import make_ring_attention_fn
from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh


def seq_mesh(seq=4, data=2):
    return create_mesh(MeshSpec(data=data, seq=seq))


def qkv(B=4, S=64, H=2, D=16, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, S, H, D)).astype(dtype)) for _ in range(3)
    )


def flash_ring_fn(mesh, block=8):
    # block=8 on S_local=16 shards: 2x2 kernel grid per rotation, so the
    # in-kernel accumulator AND the cross-rotation merge both run.
    return make_ring_attention_fn(mesh, flash=True, block_q=block, block_k=block)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_matches_dense_oracle(causal):
    mesh = seq_mesh()
    q, k, v = qkv()
    out = flash_ring_fn(mesh)(q, k, v, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_grads_match_dense(causal):
    """The custom ring VJP (dK/dV riding the ring home, global-lse backward
    kernels) must reproduce dense attention's gradients."""
    mesh = seq_mesh()
    q, k, v = qkv(S=32)

    def loss(attn, q, k, v):
        return jnp.sum(attn(q, k, v, causal=causal) ** 2)

    g_ref = jax.grad(loss, argnums=(1, 2, 3))(dense_attention, q, k, v)
    g_out = jax.grad(loss, argnums=(1, 2, 3))(flash_ring_fn(mesh), q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_single_shard_ring_is_one_flash_call():
    """seq axis of size 1: the ring degenerates to a single flash kernel."""
    mesh = seq_mesh(seq=1, data=8)
    q, k, v = qkv(B=8, S=16)
    out = flash_ring_fn(mesh)(q, k, v, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize(
    "block",
    [pytest.param(16, marks=pytest.mark.slow), 1024],
    ids=["small-block", "default-block"],
)
def test_untileable_local_seq_falls_back_to_xla_ring(block):
    """S_local=20 cannot tile (no sublane-aligned divisor — with the default
    block it 'fits' as one 20-row block, which Mosaic would reject): the
    flash inner hands off to the XLA ring block update, still correct."""
    mesh = seq_mesh(seq=4, data=2)
    q, k, v = qkv(S=80)
    out = flash_ring_fn(mesh, block=block)(q, k, v, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.slow
def test_bf16_grads_close_to_dense():
    """bf16 path: per-rotation grad partials leave the kernels in f32
    (grad_dtype) before the ring accumulation — tolerances are bf16-input
    scale, not n-fold accumulation drift."""
    mesh = seq_mesh()
    q, k, v = qkv(S=32, dtype=np.float32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def loss(attn, q, k, v):
        return jnp.sum(attn(q, k, v, causal=True).astype(jnp.float32) ** 2)

    g_ref = jax.grad(loss, argnums=(1, 2, 3))(dense_attention, qb, kb, vb)
    g_out = jax.grad(loss, argnums=(1, 2, 3))(flash_ring_fn(mesh), qb, kb, vb)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=0.05, rtol=0.05,
        )


@pytest.mark.slow
def test_lm_trains_with_flash_ring():
    """End-to-end: a TransformerLM step with the flash-ring attention_fn."""
    from deeplearning_mpi_tpu.models.transformer import TransformerConfig, TransformerLM
    from deeplearning_mpi_tpu.parallel import shard_state
    from deeplearning_mpi_tpu.runtime.mesh import batch_sharding
    from deeplearning_mpi_tpu.train import create_train_state, make_train_step
    from deeplearning_mpi_tpu.train.trainer import build_optimizer

    mesh = seq_mesh(seq=4, data=2)
    cfg = TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=2, head_dim=16,
        d_model=32, d_ff=64,
    )
    model = TransformerLM(
        config=cfg, dtype=jnp.float32,
        attention_fn=flash_ring_fn(mesh),
    )
    tx = build_optimizer("adam", 1e-2, clip_norm=1.0)
    state = shard_state(
        create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 32), jnp.int32), tx
        ),
        mesh,
    )
    step = make_train_step("lm", donate=False)
    tokens = np.random.default_rng(0).integers(0, 64, (4, 32)).astype(np.int32)
    batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh, ndim=2))}
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0]
