"""Training-layer tests: step semantics, DP equivalence, NaN guard,
checkpoint/resume, and a miniature end-to-end learning run."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeplearning_mpi_tpu.data import ShardedLoader, SyntheticCIFAR10
from deeplearning_mpi_tpu.data.cifar10 import eval_transform
from deeplearning_mpi_tpu.models import resnet18
from deeplearning_mpi_tpu.runtime.mesh import batch_sharding, replicated_sharding
from deeplearning_mpi_tpu.train import (
    Checkpointer,
    Trainer,
    create_train_state,
    make_eval_step,
    make_train_step,
)
from deeplearning_mpi_tpu.train.trainer import build_optimizer


def tiny_model():
    # Small enough for 1-core CPU, same codepaths (BN, stages, head).
    from deeplearning_mpi_tpu.models.resnet import ResNet, BasicBlock

    return ResNet(stage_sizes=(1, 1), block_cls=BasicBlock, num_classes=10,
                  num_filters=8, stem="cifar")


def make_state(tx=None, seed=0):
    model = tiny_model()
    tx = tx or build_optimizer("sgd", 0.05, momentum=0.9, weight_decay=1e-5)
    return create_train_state(
        model, jax.random.key(seed), jnp.zeros((1, 32, 32, 3)), tx
    )


def make_batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(rng.normal(size=(n, 32, 32, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, n), jnp.int32),
    }


class TestTrainStep:
    @pytest.mark.slow
    def test_step_advances_and_loss_finite(self):
        state = make_state()
        step = make_train_step("classification", donate=False)
        new_state, metrics = step(state, make_batch())
        assert int(new_state.step) == 1
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["finite"]) == 1.0

    def test_grad_accum_matches_full_batch(self):
        """On a batch-stat-free model, grad_accum=4 must produce the same
        update as one full-batch step (mean of equal-sized chunk means ==
        full-batch mean), modulo f32 summation order."""
        from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM

        # Plain SGD: the update is linear in the grads, so the only allowed
        # difference is f32 summation order. (Adam at step 1 is ~sign(g)*lr,
        # which amplifies associativity noise on near-zero grads.)
        model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
        tx = build_optimizer("sgd", 1e-2, momentum=0.0)

        def fresh():
            return create_train_state(
                model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
            )

        batch = {
            "tokens": jnp.asarray(
                np.random.default_rng(0).integers(0, 256, (8, 16)), jnp.int32
            )
        }
        s1, m1 = make_train_step("lm", donate=False)(fresh(), batch)
        s4, m4 = make_train_step("lm", donate=False, grad_accum=4)(fresh(), batch)
        np.testing.assert_allclose(float(m4["loss"]), float(m1["loss"]), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(s4.params), jax.tree.leaves(s1.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_grad_accum_matches_full_batch_ragged_mask(self):
        """With a RAGGED per-token mask (chunks carry very different
        valid-token counts), chunked accumulation must still equal the
        full-batch masked mean: chunks combine by valid-token weight, not a
        plain mean of chunk means (which would up-weight sparse chunks)."""
        from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM

        model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
        tx = build_optimizer("sgd", 1e-2, momentum=0.0)

        def fresh():
            return create_train_state(
                model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
            )

        rng = np.random.default_rng(1)
        mask = np.ones((8, 16), np.float32)
        mask[0:2, 2:] = 0.0   # chunk 0: almost everything masked
        mask[4, 8:] = 0.0     # chunk 2: half a row masked
        batch = {
            "tokens": jnp.asarray(rng.integers(0, 256, (8, 16)), jnp.int32),
            "mask": jnp.asarray(mask),
        }
        s1, m1 = make_train_step("lm", donate=False)(fresh(), batch)
        s4, m4 = make_train_step("lm", donate=False, grad_accum=4)(fresh(), batch)
        np.testing.assert_allclose(float(m4["loss"]), float(m1["loss"]), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(s4.params), jax.tree.leaves(s1.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_grad_accum_moe_aux_stays_close(self):
        """aux_weight > 0 with grad_accum: the aux load-balance loss is
        nonlinear in batch composition, so chunked is not bit-equal to
        full-batch — but the reported data loss must match exactly (aux is
        excluded from it) and the update must stay close and finite."""
        from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM

        model = TransformerLM(
            config=TransformerConfig.tiny_moe(num_experts=4), dtype=jnp.float32
        )
        tx = build_optimizer("sgd", 1e-2, momentum=0.0)

        def fresh():
            return create_train_state(
                model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
            )

        batch = {
            "tokens": jnp.asarray(
                np.random.default_rng(2).integers(0, 256, (8, 16)), jnp.int32
            )
        }
        s1, m1 = make_train_step("lm", donate=False, aux_weight=0.01)(
            fresh(), batch
        )
        s2, m2 = make_train_step("lm", donate=False, aux_weight=0.01, grad_accum=2)(
            fresh(), batch
        )
        np.testing.assert_allclose(float(m2["loss"]), float(m1["loss"]), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(s2.params), jax.tree.leaves(s1.params)):
            assert np.all(np.isfinite(np.asarray(a)))
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)

    def test_grad_accum_batchnorm_chunks_stats(self):
        """With BatchNorm, each chunk normalizes over its own examples (the
        same semantics as DDP's per-replica BN stats), so chunked training is
        deliberately NOT bit-equal to full-batch — but it must stay close and
        must advance the EMA stats off init."""
        batch = make_batch()
        s1, m1 = make_train_step("classification", donate=False)(
            make_state(), batch
        )
        s4, m4 = make_train_step("classification", donate=False, grad_accum=4)(
            make_state(), batch
        )
        np.testing.assert_allclose(float(m4["loss"]), float(m1["loss"]), rtol=0.02)
        init_stats = jax.tree.leaves(make_state().batch_stats)
        moved = [
            not np.allclose(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree.leaves(s4.batch_stats), init_stats)
        ]
        assert any(moved)

    def test_grad_accum_matches_full_batch_unet(self):
        """UNet (BatchNorm) under grad_accum, on a duplicated-halves batch:
        each chunk's batch statistics equal the full batch's by construction
        (concat([half, half]) normalizes identically whole or chunked), so
        the per-chunk-BN caveat of test_grad_accum_batchnorm_chunks_stats
        vanishes and the accumulation arithmetic itself must reproduce the
        full-batch update to tight tolerance. (The EMA batch_stats still
        advance once per chunk — documented semantics — so only loss and
        params are held to the tight bound.)"""
        from deeplearning_mpi_tpu.models import UNet

        model = UNet(out_classes=1, features=(4, 8))
        tx = build_optimizer("sgd", 1e-2, momentum=0.0)

        def fresh():
            return create_train_state(
                model, jax.random.key(0), jnp.zeros((1, 16, 16, 3)), tx
            )

        rng = np.random.default_rng(3)
        half_img = rng.normal(size=(4, 16, 16, 3)).astype(np.float32)
        half_mask = (rng.random((4, 16, 16)) > 0.5).astype(np.float32)
        batch = {
            "image": jnp.asarray(np.concatenate([half_img, half_img])),
            "mask": jnp.asarray(np.concatenate([half_mask, half_mask])),
        }
        s1, m1 = make_train_step("segmentation", donate=False)(fresh(), batch)
        s2, m2 = make_train_step("segmentation", donate=False, grad_accum=2)(
            fresh(), batch
        )
        np.testing.assert_allclose(float(m2["loss"]), float(m1["loss"]), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(s2.params), jax.tree.leaves(s1.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_grad_accum_indivisible_raises(self):
        step = make_train_step("classification", donate=False, grad_accum=3)
        with pytest.raises(ValueError, match="divisible"):
            step(make_state(), make_batch(n=16))

    def test_grad_accum_indivisible_names_offending_leaf(self):
        """The error must identify WHICH batch leaf failed and its shape —
        'not divisible' alone sends the user hunting through every input."""
        step = make_train_step("classification", donate=False, grad_accum=3)
        with pytest.raises(
            ValueError, match=r"image.*\(16, 32, 32, 3\).*grad_accum=3"
        ):
            step(make_state(), make_batch(n=16))
        # LM path with a mask: same naming contract through the other task.
        from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM

        model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32),
            build_optimizer("sgd", 1e-2, momentum=0.0),
        )
        lm_step = make_train_step("lm", donate=False, grad_accum=4)
        lm_batch = {
            "tokens": jnp.zeros((3, 16), jnp.int32),
            "mask": jnp.ones((3, 16), jnp.float32),
        }
        with pytest.raises(ValueError, match=r"\(3, 16\).*grad_accum=4"):
            lm_step(state, lm_batch)

    def test_params_change(self):
        state = make_state()
        step = make_train_step("classification", donate=False)
        new_state, _ = step(state, make_batch())
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), state.params, new_state.params
        )
        assert max(jax.tree.leaves(diffs)) > 0

    @pytest.mark.slow
    def test_nonfinite_loss_skips_update(self):
        state = make_state()
        step = make_train_step("classification", donate=False)
        bad = make_batch()
        bad["image"] = bad["image"].at[0, 0, 0, 0].set(jnp.nan)
        new_state, metrics = step(state, bad)
        assert float(metrics["finite"]) == 0.0
        # parameters unchanged (update skipped, train.py:186-188 parity)...
        for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(new_state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # ...but the step counter still advances (batch consumed)
        assert int(new_state.step) == 1

    @pytest.mark.slow
    def test_dp_equals_single_device(self, mesh):
        """The DDP-parity property: training on an 8-way sharded batch gives
        the same parameters as unsharded training on the same global batch."""
        batch = make_batch(16, seed=7)
        step = make_train_step("classification", donate=False)

        state_a = make_state(seed=1)
        sharded_batch = {
            "image": jax.device_put(batch["image"], batch_sharding(mesh)),
            "label": jax.device_put(batch["label"], batch_sharding(mesh, ndim=1)),
        }
        state_a = jax.device_put(state_a, replicated_sharding(mesh))
        for _ in range(3):
            state_a, _ = step(state_a, sharded_batch)

        state_b = make_state(seed=1)
        for _ in range(3):
            state_b, _ = step(state_b, batch)

        for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    @pytest.mark.slow
    def test_grad_clip_engages(self):
        tx = build_optimizer("adam", 1e-3, clip_norm=1e-6)
        state = make_state(tx=tx)
        step = make_train_step("classification", donate=False)
        new_state, _ = step(state, make_batch())
        # with clip 1e-6 and lr 1e-3 the update magnitude must be tiny
        max_delta = max(
            float(jnp.abs(a - b).max())
            for a, b in zip(
                jax.tree.leaves(state.params), jax.tree.leaves(new_state.params)
            )
        )
        assert max_delta < 2e-3  # adam normalizes, but clipped grads keep it small


class _ListLoader:
    """Minimal loader stub: replays fixed batches for any epoch."""

    def __init__(self, batches):
        self.batches = batches

    def epoch(self, epoch):
        return iter(self.batches)


class TestEMA:
    def _ema_state(self):
        model = tiny_model()
        tx = build_optimizer("sgd", 0.05, momentum=0.9)
        return create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 32, 32, 3)), tx, ema=True
        )

    def test_initialized_to_params(self):
        state = self._ema_state()
        for e, p in zip(
            jax.tree.leaves(state.ema_params), jax.tree.leaves(state.params)
        ):
            np.testing.assert_array_equal(np.asarray(e), np.asarray(p))

    def test_off_by_default_keeps_tree(self):
        # ema_params=None must not add leaves: existing checkpoints keep
        # their tree structure exactly.
        state = make_state()
        assert state.ema_params is None
        n_core = len(jax.tree.leaves(
            (state.step, state.params, state.batch_stats, state.opt_state)
        ))
        assert len(jax.tree.leaves(state)) == n_core

    def test_update_rule_matches_manual(self):
        d = 0.9
        state = self._ema_state()
        step = make_train_step("classification", donate=False, ema_decay=d)
        batch = make_batch()
        manual = jax.tree.map(jnp.copy, state.params)
        for _ in range(3):
            state, _ = step(state, batch)
            manual = jax.tree.map(
                lambda e, p: d * e + (1 - d) * p, manual, state.params
            )
        for e, m in zip(
            jax.tree.leaves(state.ema_params), jax.tree.leaves(manual)
        ):
            np.testing.assert_allclose(
                np.asarray(e), np.asarray(m), rtol=1e-6, atol=1e-7
            )
        # And the EMA genuinely lags the raw params.
        diffs = [
            float(jnp.max(jnp.abs(e - p)))
            for e, p in zip(
                jax.tree.leaves(state.ema_params), jax.tree.leaves(state.params)
            )
        ]
        assert max(diffs) > 0

    def test_decay_without_ema_state_raises(self):
        state = make_state()
        step = make_train_step("classification", donate=False, ema_decay=0.9)
        with pytest.raises(ValueError, match="tracks no EMA"):
            step(state, make_batch())

    def test_checkpoint_roundtrips_ema_bits(self, tmp_path):
        # The silent-drop failure mode: _arrays_only once omitted ema_params,
        # so restore kept the template's fresh EMA and eval quietly served
        # init-tinted weights. Bits must survive the roundtrip.
        state = self._ema_state()
        step = make_train_step("classification", donate=False, ema_decay=0.9)
        for _ in range(2):
            state, _ = step(state, make_batch())
        ck = Checkpointer(tmp_path / "ck")
        ck.save(state, epoch=0)
        template = self._ema_state()
        restored = ck.restore(template)
        ck.close()
        for a, b in zip(
            jax.tree.leaves(state.ema_params),
            jax.tree.leaves(restored.ema_params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_eval_uses_ema_weights(self):
        state = self._ema_state()
        batch = make_batch()
        eval_step = make_eval_step("classification")
        base = float(eval_step(state, batch)["loss"])
        # Corrupt the RAW params only: eval must be insensitive (it reads
        # the EMA), and corrupting the EMA must move it.
        corrupt = lambda t: jax.tree.map(lambda x: x + 1.0, t)  # noqa: E731
        same = float(
            eval_step(state.replace(params=corrupt(state.params)), batch)["loss"]
        )
        moved = float(
            eval_step(
                state.replace(ema_params=corrupt(state.ema_params)), batch
            )["loss"]
        )
        assert same == pytest.approx(base)
        assert moved != pytest.approx(base)


class TestNonFiniteHandling:
    @pytest.mark.slow
    def test_nan_batch_excluded_from_epoch_mean(self, mesh):
        from deeplearning_mpi_tpu.train.trainer import Trainer

        good = make_batch(seed=1)
        poisoned = make_batch(seed=2)
        poisoned["image"] = poisoned["image"].at[0, 0, 0, 0].set(jnp.nan)
        trainer = Trainer(make_state(), "classification", mesh)
        # Oracle: same state/batches without the poisoned batch in between.
        oracle = Trainer(make_state(), "classification", mesh)
        oracle_stats = oracle.run_epoch(_ListLoader([good, good]), epoch=0)
        stats = trainer.run_epoch(_ListLoader([good, poisoned, good]), epoch=0)
        # One NaN batch: skipped by the step, excluded from the mean — the
        # denominator must be the finite count (2), not the batch count (3).
        assert stats["loss"] == pytest.approx(oracle_stats["loss"], abs=1e-6)


class TestEvalPaddingExclusion:
    @pytest.mark.slow
    def test_evaluate_matches_exact_dataset_metrics(self, mesh):
        from deeplearning_mpi_tpu.data.cifar10 import SyntheticCIFAR10, eval_transform
        from deeplearning_mpi_tpu.data.loader import ShardedLoader
        from deeplearning_mpi_tpu.train.trainer import Trainer

        ds = SyntheticCIFAR10(40)  # 2 full batches of 16 + 8-row padded tail
        loader = ShardedLoader(
            ds, 16, mesh, shuffle=False, drop_last=False, transform=eval_transform
        )
        state = make_state()
        trainer = Trainer(state, "classification", mesh)
        result = trainer.evaluate(loader)
        # Oracle: run the whole dataset (no padding) through the model once.
        examples = [ds[i] for i in range(len(ds))]
        batch = eval_transform(
            {
                "image": np.stack([ex["image"] for ex in examples]),
                "label": np.stack([ex["label"] for ex in examples]),
            },
            np.random.default_rng(0),
        )
        logits = state.apply_fn(
            state.variables(), jnp.asarray(batch["image"]), train=False
        )
        expected = float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(batch["label"])))
        assert result["accuracy"] == pytest.approx(expected, abs=1e-6)


class TestEvalStep:
    @pytest.mark.slow
    def test_classification_metrics(self):
        state = make_state()
        ev = make_eval_step("classification")
        metrics = ev(state, make_batch())
        assert 0.0 <= float(metrics["accuracy"]) <= 1.0
        assert np.isfinite(float(metrics["loss"]))

    @pytest.mark.slow
    def test_segmentation_metrics(self):
        from deeplearning_mpi_tpu.models import UNet

        model = UNet(out_classes=1, features=(4, 8))
        tx = build_optimizer("adam", 1e-3)
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 16, 16, 3)), tx
        )
        ev = make_eval_step("segmentation")
        batch = {
            "image": jnp.zeros((2, 16, 16, 3)),
            "mask": jnp.zeros((2, 16, 16)),
        }
        metrics = ev(state, batch)
        assert 0.0 <= float(metrics["dice"]) <= 1.0


class TestCheckpoint:
    @pytest.mark.slow
    def test_roundtrip(self, tmp_path):
        state = make_state()
        step = make_train_step("classification", donate=False)
        state, _ = step(state, make_batch())
        ckpt = Checkpointer(tmp_path / "ckpt")
        ckpt.save(state, epoch=0)
        assert ckpt.latest_epoch() == 0

        restored = ckpt.restore(make_state(seed=99))  # template with different init
        assert int(restored.step) == int(state.step)
        for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # optimizer state (momentum buffers) restored too — unlike the
        # reference's weights-only .pth (SURVEY.md §5.4)
        for a, b in zip(jax.tree.leaves(restored.opt_state), jax.tree.leaves(state.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        ckpt.close()

    @pytest.mark.slow
    def test_restore_empty_raises(self, tmp_path):
        ckpt = Checkpointer(tmp_path / "none")
        with pytest.raises(FileNotFoundError):
            ckpt.restore(make_state())
        ckpt.close()

    @pytest.mark.slow
    def test_keeps_history(self, tmp_path):
        state = make_state()
        ckpt = Checkpointer(tmp_path / "ckpt", max_to_keep=2)
        for e in range(3):
            ckpt.save(state, epoch=e)
        assert ckpt.latest_epoch() == 2
        assert ckpt.manager.all_steps() == [1, 2]
        ckpt.close()


class TestTrainerEndToEnd:
    @pytest.mark.slow
    def test_learns_synthetic_cifar(self, mesh, tmp_path):
        """Mini e2e: loss drops and accuracy beats chance on learnable data."""
        ds = SyntheticCIFAR10(128, seed=0)
        loader = ShardedLoader(ds, 32, mesh, shuffle=True, transform=eval_transform)
        state = make_state(tx=build_optimizer("sgd", 0.1, momentum=0.9))
        trainer = Trainer(
            state, "classification", mesh,
            checkpointer=Checkpointer(tmp_path / "ckpt"), eval_every=10,
        )
        trainer.replicate_state()
        history = trainer.fit(loader, 12, eval_loader=loader)
        assert history[-1]["loss"] < history[0]["loss"]
        final_eval = trainer.evaluate(loader)
        assert final_eval["accuracy"] > 0.4  # chance = 0.1
        trainer.checkpointer.close()

    @pytest.mark.slow
    def test_resume_continues(self, mesh, tmp_path):
        ds = SyntheticCIFAR10(64, seed=0)
        loader = ShardedLoader(ds, 32, mesh, shuffle=True, transform=eval_transform)
        ckpt = Checkpointer(tmp_path / "ckpt")
        trainer = Trainer(make_state(), "classification", mesh, checkpointer=ckpt)
        trainer.replicate_state()
        trainer.fit(loader, 1)
        steps_after_one_epoch = int(trainer.state.step)
        ckpt.close()

        ckpt2 = Checkpointer(tmp_path / "ckpt")
        assert ckpt2.latest_epoch() == 0
        restored = ckpt2.restore(make_state(seed=5))
        assert int(restored.step) == steps_after_one_epoch
        ckpt2.close()


class TestOptimizerFamilies:
    """build_optimizer beyond the reference pair (sgd/adam): adamw,
    adafactor, lion. Each must actually optimize through the standard train
    step, and adafactor must deliver its factored-moment memory claim."""

    @pytest.mark.parametrize("name", ["adamw", "adafactor", "lion"])
    def test_family_learns(self, name):
        lr = {"adamw": 1e-3, "adafactor": 1e-2, "lion": 1e-4}[name]
        state = make_state(
            tx=build_optimizer(name, lr, weight_decay=1e-4, clip_norm=1.0)
        )
        step = make_train_step("classification", donate=False)
        batch = make_batch(n=8)
        _, first = step(state, batch)
        for _ in range(12):
            state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["loss"]) < float(first["loss"])

    def test_adafactor_factors_large_matrices(self):
        """A [256, 256] kernel costs Adam 2×256² f32 moments; adafactor keeps
        O(rows+cols) factors — the reason it's the TPU large-model default."""
        params = {"w": jnp.zeros((256, 256))}
        size = lambda tree: sum(  # noqa: E731
            leaf.size for leaf in jax.tree.leaves(tree)
            if hasattr(leaf, "size")
        )
        adam_sz = size(build_optimizer("adam", 1e-3).init(params))
        fact_sz = size(build_optimizer("adafactor", 1e-2).init(params))
        assert adam_sz >= 2 * 256 * 256
        assert fact_sz < 0.1 * adam_sz

    def test_unknown_name_raises(self):
        with pytest.raises(ValueError, match="unknown optimizer"):
            build_optimizer("adagrad", 1e-3)


class TestLRSchedule:
    def test_constant_is_bare_float(self):
        from deeplearning_mpi_tpu.train.trainer import build_lr_schedule

        assert build_lr_schedule(0.1, "constant") == 0.1

    def test_warmup_then_cosine(self):
        from deeplearning_mpi_tpu.train.trainer import build_lr_schedule

        sched = build_lr_schedule(0.1, "cosine", warmup_steps=10, decay_steps=100)
        assert float(sched(0)) == 0.0
        np.testing.assert_allclose(float(sched(10)), 0.1, rtol=1e-6)
        assert float(sched(55)) < 0.1
        np.testing.assert_allclose(float(sched(100)), 0.0, atol=1e-8)

    def test_linear_and_warmup_constant(self):
        from deeplearning_mpi_tpu.train.trainer import build_lr_schedule

        lin = build_lr_schedule(0.2, "linear", warmup_steps=4, decay_steps=24)
        np.testing.assert_allclose(float(lin(4)), 0.2, rtol=1e-6)
        np.testing.assert_allclose(float(lin(14)), 0.1, rtol=1e-5)
        const = build_lr_schedule(0.2, "constant", warmup_steps=4)
        np.testing.assert_allclose(float(const(2)), 0.1, rtol=1e-5)
        np.testing.assert_allclose(float(const(400)), 0.2, rtol=1e-6)

    def test_decay_shorter_than_warmup_raises(self):
        from deeplearning_mpi_tpu.train.trainer import build_lr_schedule

        with pytest.raises(ValueError, match="decay_steps"):
            build_lr_schedule(0.1, "cosine", warmup_steps=50, decay_steps=40)

    def test_scheduled_optimizer_trains(self):
        """End-to-end: a cosine schedule drives the SGD step (optax resolves
        the LR from the optimizer step count inside state.tx)."""
        from deeplearning_mpi_tpu.train.trainer import build_lr_schedule

        tx = build_optimizer(
            "sgd",
            build_lr_schedule(0.05, "cosine", warmup_steps=2, decay_steps=20),
            momentum=0.9,
        )
        state = make_state(tx=tx)
        step = make_train_step("classification", donate=False)
        batch = make_batch()
        p0 = jax.tree.leaves(state.params)[0].copy()
        for _ in range(3):
            state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert not np.allclose(np.asarray(jax.tree.leaves(state.params)[0]), np.asarray(p0))


class TestSegLossSelector:
    def test_variants_and_composition(self):
        from deeplearning_mpi_tpu.train.trainer import _task_loss

        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(2, 4, 4, 1)), jnp.float32)
        batch = {
            "mask": jnp.asarray(
                (rng.random((2, 4, 4)) > 0.5).astype(np.float32)
            )
        }
        bce = float(_task_loss("segmentation")(logits, batch))
        dice = float(_task_loss("segmentation", seg_loss="dice")(logits, batch))
        both = float(
            _task_loss("segmentation", seg_loss="bce_dice")(logits, batch)
        )
        assert bce != pytest.approx(dice)
        assert both == pytest.approx(bce + dice, rel=1e-6)
        with pytest.raises(ValueError, match="seg_loss"):
            _task_loss("segmentation", seg_loss="jaccard")

    def test_dice_training_step_decreases_dice_loss(self):
        # A tiny conv head trained under seg_loss='dice' must reduce the
        # dice objective — the selector reaches the jitted step end to end.
        import flax.linen as nn

        from deeplearning_mpi_tpu.train import create_train_state
        from deeplearning_mpi_tpu.train.trainer import (
            build_optimizer,
            make_train_step,
        )

        class Head(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                return nn.Conv(1, (3, 3), padding="SAME")(x)

        rng = np.random.default_rng(1)
        images = jnp.asarray(rng.normal(size=(8, 8, 8, 3)), jnp.float32)
        masks = jnp.asarray(
            (images.sum(-1) > 0).astype(np.float32)
        )
        batch = {"image": images, "mask": masks}
        state = create_train_state(
            Head(), jax.random.key(0), jnp.zeros((1, 8, 8, 3)),
            build_optimizer("adam", 1e-2),
        )
        step = make_train_step("segmentation", donate=False, seg_loss="dice")
        losses = []
        for _ in range(10):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
