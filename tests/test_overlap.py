"""Explicit bucketed ZeRO-1 schedule vs the GSPMD path — bit-equality.

The acceptance bar for ``parallel.zero.make_overlapped_train_step``: over
>= 5 optimizer steps on a dp=2 CPU mesh, the overlapped schedule must
produce *bit-identical* optimizer state (and params, and per-step losses)
to the GSPMD ZeRO-1 step. Bitwise claims use untied embeddings and the
one-hot embedding gradient (``TransformerConfig.onehot_embed``) — the two
documented association caveats (see parallel/zero.py's module docstring);
tied embeddings are covered at allclose.
"""

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.parallel import shard_state
from deeplearning_mpi_tpu.parallel.tensor_parallel import infer_state_sharding
from deeplearning_mpi_tpu.parallel.zero import (
    BUCKET_BYTES,
    OverlapUnsupported,
    make_overlapped_train_step,
    plan_buckets,
    zero1_dim,
)
from deeplearning_mpi_tpu.runtime.mesh import (
    MeshSpec,
    batch_sharding,
    create_mesh,
)
from deeplearning_mpi_tpu.train import create_train_state, make_train_step
from deeplearning_mpi_tpu.train.trainer import build_optimizer

VOCAB = 256


def _mesh(dp=2, **axes):
    n = dp
    for v in axes.values():
        n *= v
    return create_mesh(MeshSpec(data=dp, **axes), devices=jax.devices()[:n])


def _lm_state(*, tied=False, clip=None, ema=False, tx=None, onehot=True):
    cfg = TransformerConfig(
        vocab_size=VOCAB, num_layers=1, num_heads=2, head_dim=32,
        d_model=64, d_ff=256, tied_embeddings=tied, onehot_embed=onehot,
    )
    model = TransformerLM(config=cfg, dtype=jnp.float32)
    tx = tx if tx is not None else build_optimizer("adam", 1e-2, clip_norm=clip)
    return create_train_state(
        model, jax.random.key(0), jnp.zeros((1, 8), jnp.int32), tx, ema=ema
    )


def _batches(mesh, n=5, batch=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        tokens = jnp.asarray(rng.integers(0, VOCAB, (batch, seq)), jnp.int32)
        mask = jnp.asarray(rng.integers(0, 2, (batch, seq)), jnp.float32)
        out.append({
            "tokens": jax.device_put(tokens, batch_sharding(mesh, ndim=2)),
            "mask": jax.device_put(mask, batch_sharding(mesh, ndim=2)),
        })
    return out


def _run(step, state, batches):
    losses = []
    for b in batches:
        state, metrics = step(state, b)
        losses.append(float(metrics["loss"]))
    return state, losses


def _assert_tree_bit_equal(a, b, what):
    for (kp, x), (_, y) in zip(
        jtu.tree_flatten_with_path(a)[0], jtu.tree_flatten_with_path(b)[0]
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"{what}{jtu.keystr(kp)} not bit-identical",
        )


class TestBitEquality:
    """Overlapped schedule == GSPMD schedule, bit for bit (dp=2, 5 steps)."""

    def _compare(self, *, clip=None, ema=False, n_steps=5):
        mesh = _mesh()
        ema_decay = 0.9 if ema else 0.0
        state_g = shard_state(_lm_state(clip=clip, ema=ema), mesh, zero=True)
        state_o = shard_state(_lm_state(clip=clip, ema=ema), mesh, zero=True)
        step_g = make_train_step(
            "lm", donate=False, ema_decay=ema_decay,
            state_shardings=infer_state_sharding(state_g, mesh, zero=True),
        )
        step_o = make_overlapped_train_step(
            "lm", state_o, mesh, donate=False, clip_norm=clip,
            ema_decay=ema_decay,
        )
        batches = _batches(mesh, n=n_steps)
        state_g, losses_g = _run(step_g, state_g, batches)
        state_o, losses_o = _run(step_o, state_o, batches)
        assert losses_g == losses_o, "per-step losses diverged"
        _assert_tree_bit_equal(state_g.opt_state, state_o.opt_state, "opt_state")
        _assert_tree_bit_equal(state_g.params, state_o.params, "params")
        if ema:
            _assert_tree_bit_equal(state_g.ema_params, state_o.ema_params, "ema")
        assert int(state_o.step) == n_steps

    def test_bitwise_vs_gspmd_5_steps(self):
        self._compare()

    def test_bitwise_with_clip_and_ema(self):
        # The pre-clip mirrors optax.clip_by_global_norm's exact form, so
        # even the clipped path lands bit-equal on this mesh.
        self._compare(clip=1.0, ema=True)

    def test_tied_embeddings_allclose(self):
        # Tied embed grads: GSPMD adds two separately all-reduced cotangent
        # contributions; the local backward adds before one reduce. Same
        # value to ~2 ulp — allclose, not bitwise (module docstring).
        mesh = _mesh()
        state_g = shard_state(_lm_state(tied=True), mesh, zero=True)
        state_o = shard_state(_lm_state(tied=True), mesh, zero=True)
        step_g = make_train_step(
            "lm", donate=False,
            state_shardings=infer_state_sharding(state_g, mesh, zero=True),
        )
        step_o = make_overlapped_train_step("lm", state_o, mesh, donate=False)
        batches = _batches(mesh)
        state_g, losses_g = _run(step_g, state_g, batches)
        state_o, losses_o = _run(step_o, state_o, batches)
        np.testing.assert_allclose(losses_g, losses_o, rtol=1e-6)
        for a, b in zip(
            jax.tree.leaves(state_g.params), jax.tree.leaves(state_o.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
            )

    def test_nan_batch_skipped_like_gspmd(self):
        mesh = _mesh()
        state_o = shard_state(_lm_state(), mesh, zero=True)
        step_o = make_overlapped_train_step("lm", state_o, mesh, donate=False)
        batches = _batches(mesh, n=1)
        before = jax.tree.map(np.asarray, state_o.params)
        poisoned = dict(batches[0])
        poisoned["mask"] = poisoned["mask"] * jnp.nan
        state_o, metrics = step_o(state_o, poisoned)
        assert float(metrics["finite"]) == 0.0
        _assert_tree_bit_equal(before, state_o.params, "params after NaN skip")
        assert int(state_o.step) == 1  # step counter still advances


class TestGradAccum:
    def test_grad_accum_matches_full_batch(self):
        mesh = _mesh()
        state_1 = shard_state(_lm_state(), mesh, zero=True)
        state_k = shard_state(_lm_state(), mesh, zero=True)
        step_1 = make_overlapped_train_step("lm", state_1, mesh, donate=False)
        step_k = make_overlapped_train_step(
            "lm", state_k, mesh, donate=False, grad_accum=2
        )
        batches = _batches(mesh, n=3)
        state_1, losses_1 = _run(step_1, state_1, batches)
        state_k, losses_k = _run(step_k, state_k, batches)
        # Local chunking is algebraically identical to the full-batch masked
        # mean (weights fold exactly); only fp association differs — and
        # Adam's nu-normalization amplifies ulp-level grad differences on
        # near-zero coordinates, hence the looser param tolerance.
        np.testing.assert_allclose(losses_1, losses_k, rtol=1e-6)
        for a, b in zip(
            jax.tree.leaves(state_1.params), jax.tree.leaves(state_k.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3
            )

    def test_nondivisible_batch_names_offender(self):
        mesh = _mesh()
        state = shard_state(_lm_state(), mesh, zero=True)
        step = make_overlapped_train_step(
            "lm", state, mesh, donate=False, grad_accum=4
        )
        [batch] = _batches(mesh, n=1, batch=6)  # local batch 3, accum 4
        with pytest.raises(ValueError, match=r"\(3, 16\).*grad_accum=4"):
            step(state, batch)


class TestUnsupportedFallsBack:
    def test_no_data_parallelism(self):
        mesh = _mesh(dp=1)
        state = _lm_state()
        with pytest.raises(OverlapUnsupported, match="size 1"):
            make_overlapped_train_step("lm", state, mesh)

    def test_non_data_axes(self):
        mesh = _mesh(dp=2, model=2)
        state = _lm_state()
        with pytest.raises(OverlapUnsupported, match="non-data"):
            make_overlapped_train_step("lm", state, mesh)

    def test_aux_weight(self):
        with pytest.raises(OverlapUnsupported, match="aux_weight"):
            make_overlapped_train_step("lm", _lm_state(), _mesh(), aux_weight=0.1)

    def test_loss_chunk(self):
        with pytest.raises(OverlapUnsupported, match="loss_chunk"):
            make_overlapped_train_step("lm", _lm_state(), _mesh(), loss_chunk=8)

    def test_batch_stats(self):
        state = _lm_state().replace(
            batch_stats={"bn": {"mean": jnp.zeros((4,))}}
        )
        with pytest.raises(OverlapUnsupported, match="batch_stats"):
            make_overlapped_train_step("lm", state, _mesh())

    def test_non_mirroring_optimizer_state(self):
        # Factored adafactor moments don't mirror parameter shapes; the
        # build-time eval_shape probe must catch it, not a mid-step error.
        tx = optax.adafactor(
            1e-2, multiply_by_parameter_scale=False, min_dim_size_to_factor=32
        )
        state = _lm_state(tx=tx)
        with pytest.raises(OverlapUnsupported, match="mirror"):
            make_overlapped_train_step("lm", state, _mesh())


class TestBucketPlan:
    def _leaves(self):
        return [
            jnp.zeros((256, 64)),   # 64 KiB, shardable on dim 0
            jnp.zeros((8,)),        # tiny -> replicated
            jnp.zeros((64, 512)),   # 128 KiB, shardable on dim 1
            jnp.zeros((512, 64)),   # 128 KiB, shardable on dim 0
        ]

    def test_byte_bounded_buckets(self):
        plan = plan_buckets(self._leaves(), dp=2, bucket_bytes=128 * 1024)
        assert plan.replicated == (1,)
        assert plan.shard_dims == (0, None, 1, 0)
        # 64K fits; adding 128K would exceed the 128K bound -> new bucket.
        assert plan.buckets == ((0,), (2,), (3,))
        assert plan.n_sharded == 3

    def test_single_bucket_when_large_bound(self):
        plan = plan_buckets(self._leaves(), dp=2, bucket_bytes=BUCKET_BYTES)
        assert plan.buckets == ((0, 2, 3),)

    def test_deterministic(self):
        a = plan_buckets(self._leaves(), dp=2, bucket_bytes=64 * 1024)
        b = plan_buckets(self._leaves(), dp=2, bucket_bytes=64 * 1024)
        assert a == b

    def test_min_size_respected(self):
        leaves = [jnp.zeros((64, 64))]  # 4096 elements < MIN_SIZE
        plan = plan_buckets(leaves, dp=2)
        assert plan.buckets == () and plan.replicated == (0,)

    def test_zero1_dim_matches_plan(self):
        leaves = self._leaves()
        plan = plan_buckets(leaves, dp=2)
        assert plan.shard_dims == tuple(
            zero1_dim(leaf, P(), 2) for leaf in leaves
        )


class TestTrainerIntegration:
    """Trainer.place_state's overlap routing and apply_tuned_step overlay."""

    def test_place_state_activates_overlapped_schedule(self):
        from deeplearning_mpi_tpu.train.trainer import Trainer

        mesh = _mesh(dp=2)
        trainer = Trainer(
            _lm_state(tx=build_optimizer("adam", 1e-2)), "lm", mesh,
            zero=True, overlap=True,
        )
        trainer.place_state()
        # The overlapped step is the only one carrying a bucket plan.
        assert hasattr(trainer.train_step, "bucket_plan")
        batch = _batches(mesh, n=1)[0]
        state, metrics = trainer.train_step(trainer.state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_place_state_falls_back_on_unsupported(self):
        """dp=1 cannot overlap (nothing to reduce-scatter): place_state must
        log-and-fall-back to the GSPMD ZeRO-1 step, never raise."""
        from deeplearning_mpi_tpu.train.trainer import Trainer

        mesh = _mesh(dp=1)
        trainer = Trainer(
            _lm_state(tx=build_optimizer("adam", 1e-2)), "lm", mesh,
            zero=True, overlap=True,
        )
        trainer.place_state()  # must not raise
        assert not hasattr(trainer.train_step, "bucket_plan")
        batch = _batches(mesh, n=1)[0]
        state, metrics = trainer.train_step(trainer.state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_apply_tuned_step_hit_applies_schedule(self, tmp_path):
        from deeplearning_mpi_tpu.compiler import autotune
        from deeplearning_mpi_tpu.train.trainer import Trainer

        mesh = _mesh(dp=2)
        db = autotune.TuningDB(tmp_path / "t.json")
        db.record_key(
            autotune.step_tuning_key("lm", (8, 16), mesh, jnp.float32),
            {"remat": "dots", "grad_accum": 2, "donate": True,
             "overlap": True},
            best_seconds=0.01, kernel="step",
        )
        trainer = Trainer(
            _lm_state(tx=build_optimizer("adam", 1e-2)), "lm", mesh,
            zero=True,
        )
        params = trainer.apply_tuned_step(
            db, model="lm", batch_size=8, seq_len=16
        )
        # remat is returned for the model builder; grad_accum and the
        # schedule choice are applied to the trainer directly.
        assert params["remat"] == "dots"
        assert trainer._step_kwargs["grad_accum"] == 2
        assert trainer.overlap is True

    def test_apply_tuned_step_never_raises_and_keeps_defaults(self, tmp_path):
        from deeplearning_mpi_tpu.train.trainer import Trainer

        mesh = _mesh(dp=2)

        def fresh():
            return Trainer(
                _lm_state(tx=build_optimizer("adam", 1e-2)), "lm", mesh,
                zero=True,
            )

        # Entry-less DB, corrupt file, and missing path: all miss cleanly.
        trainer = fresh()
        from deeplearning_mpi_tpu.compiler import autotune

        assert trainer.apply_tuned_step(
            autotune.TuningDB(), model="lm", batch_size=8, seq_len=16
        ) is None
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert trainer.apply_tuned_step(
            str(bad), model="lm", batch_size=8, seq_len=16
        ) is None
        assert trainer.apply_tuned_step(
            str(tmp_path / "nope.json"), model="lm", batch_size=8, seq_len=16
        ) is None
        # Settings untouched on every miss.
        assert trainer.overlap is False
        assert trainer._step_kwargs.get("grad_accum", 1) == 1
