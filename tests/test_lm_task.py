"""LM training task: trainer integration, MoE aux loss, datasets, CLI."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_mpi_tpu.data import ShardedLoader, SyntheticTokens
from deeplearning_mpi_tpu.data.lm_text import ByteTextDataset
from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.train import Trainer, create_train_state
from deeplearning_mpi_tpu.train.trainer import build_optimizer
from deeplearning_mpi_tpu.runtime.mesh import create_mesh


def _make_trainer(mesh, cfg, *, aux_weight=0.0, seq_len=32, n_seqs=64, lr=1e-2):
    model = TransformerLM(config=cfg, dtype=jnp.float32)
    tx = build_optimizer("adam", lr, clip_norm=1.0)
    state = create_train_state(
        model, jax.random.key(0), jnp.zeros((1, seq_len), jnp.int32), tx
    )
    trainer = Trainer(state, "lm", mesh, aux_weight=aux_weight)
    trainer.place_state()
    loader = ShardedLoader(
        SyntheticTokens(n_seqs, seq_len, seed=0), 16, mesh, shuffle=True, seed=0
    )
    return trainer, loader


class TestLMTask:
    def test_dense_lm_loss_decreases(self, mesh):
        cfg = TransformerConfig.tiny()
        trainer, loader = _make_trainer(mesh, cfg)
        stats = [trainer.run_epoch(loader, e) for e in range(3)]
        assert stats[-1]["loss"] < stats[0]["loss"]

    @pytest.mark.slow
    def test_moe_lm_trains_and_evaluates(self, mesh):
        cfg = TransformerConfig.tiny_moe(num_experts=4)
        trainer, loader = _make_trainer(mesh, cfg, aux_weight=0.01)
        first = trainer.run_epoch(loader, 0)
        assert np.isfinite(first["loss"])
        eval_loader = ShardedLoader(
            SyntheticTokens(16, 32, seed=1), 16, mesh,
            shuffle=False, drop_last=False,
        )
        metrics = trainer.evaluate(eval_loader)
        assert "perplexity" in metrics
        assert metrics["perplexity"] > 1.0
        assert np.isfinite(metrics["loss"])


class TestByteTextDataset:
    def test_chunks_file_bytes(self, tmp_path):
        path = tmp_path / "corpus.txt"
        path.write_bytes(b"abcdefgh" * 10)  # 80 bytes
        ds = ByteTextDataset(path, seq_len=16)
        assert len(ds) == 5
        ex = ds[0]
        assert ex["tokens"].shape == (16,)
        assert ex["tokens"].dtype == np.int32
        np.testing.assert_array_equal(ex["tokens"][:8], np.frombuffer(b"abcdefgh", np.uint8))

    def test_synthetic_deterministic(self):
        a = SyntheticTokens(4, 32, seed=7)[2]["tokens"]
        b = SyntheticTokens(4, 32, seed=7)[2]["tokens"]
        np.testing.assert_array_equal(a, b)


class TestExpertChoiceGuard:
    """The causal trainer refuses acausal routing without an explicit ack
    (fail-loud doctrine, ``train/resilience.py``). Fast: ``parser.error``
    fires before any runtime setup."""

    GUARD_ARGS = [
        "--moe_experts", "4", "--moe_routing", "expert_choice",
        "--num_epochs", "1", "--batch_size", "8", "--seq_len", "32",
    ]

    def test_refuses_without_ack(self, capsys):
        from deeplearning_mpi_tpu.cli import train_lm

        with pytest.raises(SystemExit) as exc:
            train_lm.main(self.GUARD_ARGS)
        assert exc.value.code == 2
        assert "allow_acausal_routing" in capsys.readouterr().err

    def test_refuses_single_expert_too(self, capsys):
        # The model builds a routed MoE for ANY moe_experts >= 1, and a lone
        # expert's top-C selection still ranks the whole sequence — the
        # guard must match the model's threshold, not the help text's "N>1".
        from deeplearning_mpi_tpu.cli import train_lm

        with pytest.raises(SystemExit) as exc:
            train_lm.main([
                "--moe_experts", "1", "--moe_routing", "expert_choice",
            ])
        assert exc.value.code == 2

    def test_token_choice_not_guarded(self):
        # token_choice is causal-safe; the parser must accept it without the
        # ack flag (parse only — build_parser().parse_args, no training).
        from deeplearning_mpi_tpu.cli import train_lm

        args = train_lm.build_parser().parse_args(
            ["--moe_experts", "4", "--moe_routing", "token_choice"]
        )
        assert not args.allow_acausal_routing

    @pytest.mark.slow
    def test_ack_flag_trains(self, tmp_path):
        from deeplearning_mpi_tpu.cli import train_lm

        rc = train_lm.main(self.GUARD_ARGS + [
            "--allow_acausal_routing",
            "--num_layers", "1", "--num_heads", "2", "--head_dim", "4",
            "--d_model", "8", "--d_ff", "16", "--train_sequences", "32",
            "--model_dir", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0


@pytest.mark.slow
class TestTrainLMCLI:
    def test_moe_dropped_frac_in_metrics_sidecar(self, tmp_path):
        """A --moe_experts run must surface the over-capacity dropped-token
        fraction in its .metrics.jsonl epoch records (round-4 weak #6) —
        low capacity_factor is not exposed on the CLI, so assert presence
        and range rather than forcing a collapse."""
        import json

        from deeplearning_mpi_tpu.cli import train_lm

        rc = train_lm.main([
            "--num_epochs", "1", "--batch_size", "8", "--seq_len", "32",
            "--num_layers", "1", "--num_heads", "2", "--head_dim", "4",
            "--d_model", "8", "--d_ff", "16", "--moe_experts", "4",
            "--train_sequences", "32",
            "--model_dir", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0
        records = [
            json.loads(line)
            for f in sorted((tmp_path / "logs").glob("*.metrics.jsonl"))
            for line in f.read_text().splitlines()
        ]
        epochs = [r for r in records if r.get("kind") == "epoch"]
        assert epochs and all("moe_dropped_frac" in r for r in epochs)
        assert all(0.0 <= r["moe_dropped_frac"] <= 1.0 for r in epochs)

    def test_one_epoch_synthetic(self, tmp_path):
        from deeplearning_mpi_tpu.cli import train_lm

        rc = train_lm.main([
            "--num_epochs", "1", "--batch_size", "8", "--seq_len", "32",
            "--num_layers", "1", "--num_heads", "2", "--head_dim", "4",
            "--d_model", "8", "--d_ff", "16",
            "--train_sequences", "32",
            "--model_dir", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0
        assert any((tmp_path / "logs").iterdir())

    def test_flash_attention_core(self, tmp_path):
        # Pins the CLI -> flash_attention_bhsd wiring (round 4 switched
        # --attention flash to the BHSD-native entry): the whole epoch runs
        # the kernel-layout projection path end to end (interpret on CPU).
        from deeplearning_mpi_tpu.cli import train_lm

        rc = train_lm.main([
            "--attention", "flash",
            "--num_epochs", "1", "--batch_size", "8", "--seq_len", "32",
            "--num_layers", "1", "--num_heads", "2", "--head_dim", "8",
            "--d_model", "16", "--d_ff", "32",
            "--train_sequences", "32",
            "--model_dir", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0

    def test_sliding_window_through_flash(self, tmp_path):
        # --attention_window with the flash core: a full epoch through the
        # windowed kernels (block gating + in-tile mask, interpret on CPU).
        from deeplearning_mpi_tpu.cli import train_lm

        rc = train_lm.main([
            "--attention", "flash", "--attention_window", "16",
            "--num_epochs", "1", "--batch_size", "8", "--seq_len", "32",
            "--num_layers", "1", "--num_heads", "2", "--head_dim", "8",
            "--d_model", "16", "--d_ff", "32",
            "--train_sequences", "32",
            "--model_dir", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0

    def test_sliding_window_composes_with_ulysses(self, tmp_path):
        # --sp 4 + --attention_window: the window rides the all-to-all
        # schedule's full-sequence inner core (values pinned to the windowed
        # oracle in test_sequence_parallel; this is the CLI wiring).
        from deeplearning_mpi_tpu.cli import train_lm

        rc = train_lm.main([
            "--attention", "ulysses", "--sp", "4", "--attention_window", "16",
            "--num_epochs", "1", "--batch_size", "8", "--seq_len", "64",
            "--num_layers", "1", "--num_heads", "4", "--head_dim", "8",
            "--d_model", "16", "--d_ff", "32",
            "--train_sequences", "32",
            "--model_dir", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0

    def test_sliding_window_composes_with_ring(self, tmp_path):
        # Rotation-skipping ring (r5): window x the O(S/N)-memory SP path —
        # a full CLI epoch with --attention ring --attention_window must
        # train green (window 16 over sp=4 shards of 16 = 2 rotations).
        from deeplearning_mpi_tpu.cli import train_lm

        rc = train_lm.main([
            "--attention", "ring", "--sp", "4", "--attention_window", "16",
            "--num_epochs", "1", "--batch_size", "8", "--seq_len", "64",
            "--num_layers", "1", "--num_heads", "2", "--head_dim", "8",
            "--d_model", "16", "--d_ff", "32",
            "--train_sequences", "32",
            "--model_dir", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0

    def test_ring_attention_sequence_parallel(self, tmp_path):
        # --sp 4 over the 8 virtual devices: the ring schedule through the
        # CLI (mesh construction, loader seq handling, collective epoch).
        from deeplearning_mpi_tpu.cli import train_lm

        rc = train_lm.main([
            "--attention", "ring", "--sp", "4",
            "--num_epochs", "1", "--batch_size", "8", "--seq_len", "64",
            "--num_layers", "1", "--num_heads", "2", "--head_dim", "8",
            "--d_model", "16", "--d_ff", "32",
            "--train_sequences", "32",
            "--model_dir", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0
