"""ZeRO-1 optimizer-state sharding over the data axis."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.parallel import shard_state
from deeplearning_mpi_tpu.parallel.zero import MIN_SIZE, zero1_dim, zero1_spec
from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, batch_sharding, create_mesh
from deeplearning_mpi_tpu.train import create_train_state, make_train_step
from deeplearning_mpi_tpu.train.trainer import build_optimizer


def _state(d_model=128, d_ff=512):
    cfg = TransformerConfig(
        vocab_size=128, num_layers=1, num_heads=4, head_dim=32,
        d_model=d_model, d_ff=d_ff,
    )
    model = TransformerLM(config=cfg, dtype=jnp.float32)
    tx = build_optimizer("adam", 1e-2, clip_norm=1.0)
    return create_train_state(
        model, jax.random.key(0), jnp.zeros((1, 8), jnp.int32), tx
    )


class TestZero1Spec:
    def test_picks_largest_free_divisible_dim(self):
        leaf = jnp.zeros((64, 512))
        assert zero1_spec(leaf, P(), 8) == P(None, "data")

    def test_respects_taken_dims(self):
        leaf = jnp.zeros((64, 512))
        assert zero1_spec(leaf, P(None, "model"), 8) == P("data", "model")

    def test_small_leaves_stay_replicated(self):
        assert zero1_spec(jnp.zeros((8,)), P(), 8) == P()

    def test_indivisible_stays(self):
        leaf = jnp.zeros((63, 129, 3))
        assert zero1_spec(leaf, P(), 8, min_size=1) == P()

    def test_min_size_boundary(self):
        # size < MIN_SIZE stays replicated; size == MIN_SIZE shards.
        assert zero1_spec(jnp.zeros((MIN_SIZE // 2, 1)), P(), 2) == P()
        assert zero1_spec(jnp.zeros((MIN_SIZE, 1)), P(), 2) == P("data", None)

    def test_tie_breaking_deterministic(self):
        # Equal-size dims: the FIRST largest wins, every time — the explicit
        # schedule (plan_buckets) and the GSPMD annotation must agree on the
        # shard dim, so the choice is a pure function of the shape.
        leaf = jnp.zeros((128, 128))
        assert zero1_spec(leaf, P(), 2) == P("data", None)
        assert all(zero1_spec(leaf, P(), 2) == P("data", None) for _ in range(8))
        assert zero1_dim(leaf, P(), 2) == 0
        # With dim 0 taken, the tie is gone: dim 1 is the largest free dim.
        assert zero1_spec(leaf, P("model"), 2) == P("model", "data")

    def test_no_free_dim_stays(self):
        leaf = jnp.zeros((128, 128))
        assert zero1_spec(leaf, P("model", "expert"), 2) == P("model", "expert")

    def test_zero1_dim_matches_spec(self):
        for shape in [(256, 64), (8,), (63, 3), (128, 128), (2, 8192)]:
            leaf = jnp.zeros(shape)
            d = zero1_dim(leaf, P(), 4)
            spec = zero1_spec(leaf, P(), 4)
            if d is None:
                assert spec == P()
            else:
                assert spec[d] == "data"


class TestZeroSharding:
    def test_moments_sharded_params_replicated(self):
        mesh = create_mesh(MeshSpec(data=8))
        state = shard_state(_state(), mesh, zero=True)
        embed = state.params["embed"]["embedding"]
        assert embed.sharding.spec == P()  # params stay replicated (ZeRO-1)
        mu_embed = state.opt_state[1][0].mu["embed"]["embedding"]
        assert "data" in (mu_embed.sharding.spec or ())
        nu_ff = state.opt_state[1][0].nu["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert "data" in (nu_ff.sharding.spec or ())
        # scalars/counters replicated
        assert state.opt_state[1][0].count.sharding.spec == P()

    @pytest.mark.slow
    def test_training_matches_unsharded(self):
        """One optimizer step with ZeRO-sharded moments must produce the same
        params as the fully replicated step."""
        mesh = create_mesh(MeshSpec(data=8))
        step = make_train_step("lm", donate=False)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 128, (16, 8)), jnp.int32)

        state_ref = shard_state(_state(), mesh, zero=False)
        state_zero = shard_state(_state(), mesh, zero=True)
        batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh, ndim=2))}

        new_ref, m_ref = step(state_ref, batch)
        new_zero, m_zero = step(state_zero, batch)
        assert float(m_ref["loss"]) == float(m_zero["loss"])
        for a, b in zip(
            jax.tree.leaves(new_ref.params), jax.tree.leaves(new_zero.params)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_zero_with_tp_composes(self):
        mesh = create_mesh(MeshSpec(data=4, model=2))
        state = shard_state(_state(), mesh, zero=True)
        mu_ff = state.opt_state[1][0].mu["layer_0"]["mlp"]["gate_proj"]["kernel"]
        # TP takes the output dim, ZeRO the input dim.
        assert mu_ff.sharding.spec == P("data", "model")
