"""ZeRO-1 optimizer-state sharding over the data axis."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.parallel import shard_state
from deeplearning_mpi_tpu.parallel.zero import zero1_spec
from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, batch_sharding, create_mesh
from deeplearning_mpi_tpu.train import create_train_state, make_train_step
from deeplearning_mpi_tpu.train.trainer import build_optimizer


def _state(d_model=128, d_ff=512):
    cfg = TransformerConfig(
        vocab_size=128, num_layers=1, num_heads=4, head_dim=32,
        d_model=d_model, d_ff=d_ff,
    )
    model = TransformerLM(config=cfg, dtype=jnp.float32)
    tx = build_optimizer("adam", 1e-2, clip_norm=1.0)
    return create_train_state(
        model, jax.random.key(0), jnp.zeros((1, 8), jnp.int32), tx
    )


class TestZero1Spec:
    def test_picks_largest_free_divisible_dim(self):
        leaf = jnp.zeros((64, 512))
        assert zero1_spec(leaf, P(), 8) == P(None, "data")

    def test_respects_taken_dims(self):
        leaf = jnp.zeros((64, 512))
        assert zero1_spec(leaf, P(None, "model"), 8) == P("data", "model")

    def test_small_leaves_stay_replicated(self):
        assert zero1_spec(jnp.zeros((8,)), P(), 8) == P()

    def test_indivisible_stays(self):
        leaf = jnp.zeros((63, 129, 3))
        assert zero1_spec(leaf, P(), 8, min_size=1) == P()


class TestZeroSharding:
    def test_moments_sharded_params_replicated(self):
        mesh = create_mesh(MeshSpec(data=8))
        state = shard_state(_state(), mesh, zero=True)
        embed = state.params["embed"]["embedding"]
        assert embed.sharding.spec == P()  # params stay replicated (ZeRO-1)
        mu_embed = state.opt_state[1][0].mu["embed"]["embedding"]
        assert "data" in (mu_embed.sharding.spec or ())
        nu_ff = state.opt_state[1][0].nu["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert "data" in (nu_ff.sharding.spec or ())
        # scalars/counters replicated
        assert state.opt_state[1][0].count.sharding.spec == P()

    @pytest.mark.slow
    def test_training_matches_unsharded(self):
        """One optimizer step with ZeRO-sharded moments must produce the same
        params as the fully replicated step."""
        mesh = create_mesh(MeshSpec(data=8))
        step = make_train_step("lm", donate=False)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 128, (16, 8)), jnp.int32)

        state_ref = shard_state(_state(), mesh, zero=False)
        state_zero = shard_state(_state(), mesh, zero=True)
        batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh, ndim=2))}

        new_ref, m_ref = step(state_ref, batch)
        new_zero, m_zero = step(state_zero, batch)
        assert float(m_ref["loss"]) == float(m_zero["loss"])
        for a, b in zip(
            jax.tree.leaves(new_ref.params), jax.tree.leaves(new_zero.params)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_zero_with_tp_composes(self):
        mesh = create_mesh(MeshSpec(data=4, model=2))
        state = shard_state(_state(), mesh, zero=True)
        mu_ff = state.opt_state[1][0].mu["layer_0"]["mlp"]["gate_proj"]["kernel"]
        # TP takes the output dim, ZeRO the input dim.
        assert mu_ff.sharding.spec == P("data", "model")
