"""Compilation service: AOT warmup, tuning DB, cache management, donation.

Covers the ``compiler/`` subsystem end to end on the virtual-CPU harness:

- TuningDB round-trip / corruption / exact-key lookup semantics;
- autotuned candidates match the default kernels numerically (the DB can
  make kernels faster, never wrong);
- the buffer-donation veto policy matrix (moved here from
  ``runtime/compat.py`` — the regression test for the XLA:CPU
  deserialized-executable heap corruption);
- cold-vs-warm AOT compile classification against a persistent cache
  (miss writes an entry, a second identical program deserializes);
- CompileCache LRU eviction and digest-manifest quarantine (fabricated
  entries — no real compiles needed);
- a warmed ServingEngine performs ZERO compiles on its first request
  (the ``serve_compile_total`` trace counter), and ``Trainer.warmup``
  swaps in a working AOT step.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_mpi_tpu.compiler import aot, autotune
from deeplearning_mpi_tpu.compiler import cache as ccache
from deeplearning_mpi_tpu.telemetry import MetricsRegistry

F32 = jnp.float32


# -- tuning DB ----------------------------------------------------------------

class TestTuningDB:
    def test_round_trip(self, tmp_path):
        db = autotune.TuningDB(tmp_path / "t.json")
        db.record("flash_attention", (1, 64, 2, 16), F32,
                  {"block_q": 32, "block_k": 64}, backend="cpu",
                  best_seconds=0.01)
        db.record("flash_decode", (2, 64, 2, 16), F32,
                  {"schedule": "einsum", "block": None}, backend="cpu")
        db.save()
        back = autotune.TuningDB.load(tmp_path / "t.json")
        assert len(back) == 2
        assert back.lookup("flash_attention", (1, 64, 2, 16), F32,
                           backend="cpu") == {"block_q": 32, "block_k": 64}

    def test_corrupt_file_loads_empty_and_saves(self, tmp_path):
        p = tmp_path / "t.json"
        p.write_text("{not json")
        db = autotune.TuningDB.load(p)
        assert len(db) == 0
        db.record("flash_attention", (1, 8, 1, 8), F32,
                  {"block_q": 8, "block_k": 8}, backend="cpu")
        db.save()  # path survived the corrupt load
        assert len(autotune.TuningDB.load(p)) == 1

    def test_version_mismatch_ignored(self, tmp_path):
        p = tmp_path / "t.json"
        p.write_text('{"version": 99, "entries": {"x": {}}}')
        assert len(autotune.TuningDB.load(p)) == 0

    def test_lookup_is_exact_key_only(self):
        db = autotune.TuningDB()
        db.record("flash_attention", (1, 64, 2, 16), F32,
                  {"block_q": 32, "block_k": 64}, backend="cpu")
        assert db.lookup("flash_attention", (1, 128, 2, 16), F32,
                         backend="cpu") is None
        assert db.lookup("flash_attention", (1, 64, 2, 16), F32,
                         backend="tpu") is None
        assert db.lookup("flash_attention", (1, 64, 2, 16), jnp.bfloat16,
                         backend="cpu") is None

    def test_env_var_default_db(self, tmp_path, monkeypatch):
        db = autotune.TuningDB(tmp_path / "env.json")
        db.record("flash_attention", (1, 64, 2, 16), F32,
                  {"block_q": 16, "block_k": 16})
        db.save()
        monkeypatch.setenv(autotune.ENV_DB, str(tmp_path / "env.json"))
        autotune.set_default_db(None)  # re-arm the env fallback
        try:
            loaded = autotune.default_db()
            assert loaded is not None and len(loaded) == 1
        finally:
            monkeypatch.delenv(autotune.ENV_DB)
            autotune.set_default_db(None)


# -- autotuner ----------------------------------------------------------------

class TestAutotune:
    SHAPE = (1, 64, 2, 16)

    def test_attention_candidates_legal(self):
        pairs = autotune.attention_candidates(64, candidates=(16, 32, 64, 128))
        assert pairs, "64-seq shape must admit candidates"
        for bq, bk in pairs:
            assert bq <= 64 and bk <= 64
            assert 64 % bq == 0 and 64 % bk == 0

    def test_tuned_attention_matches_oracle(self, tmp_path):
        from deeplearning_mpi_tpu.ops.attention import dense_attention
        from deeplearning_mpi_tpu.ops.pallas import flash_attention

        db = autotune.TuningDB(tmp_path / "t.json")
        params = autotune.tune_flash_attention(
            self.SHAPE, db=db, candidates=(32, 64), repeats=1,
        )
        assert set(params) == {"block_q", "block_k"}
        kq, kk, kv = jax.random.split(jax.random.key(7), 3)
        q = jax.random.normal(kq, self.SHAPE)
        k = jax.random.normal(kk, self.SHAPE)
        v = jax.random.normal(kv, self.SHAPE)
        tuned = flash_attention(
            q, k, v, block_q=params["block_q"], block_k=params["block_k"]
        )
        np.testing.assert_allclose(
            np.asarray(tuned), np.asarray(dense_attention(q, k, v)),
            rtol=2e-5, atol=2e-5,
        )

    def test_tune_decode_schedule_and_lookup(self, tmp_path):
        db = autotune.TuningDB(tmp_path / "t.json")
        params = autotune.tune_flash_decode(
            (2, 64, 2, 16), db=db, blocks=(16, 32), repeats=1,
        )
        assert params["schedule"] in ("kernel", "einsum")
        autotune.set_default_db(db)
        try:
            got = autotune.tuned_decode_schedule((2, 64, 2, 16), F32)
            assert got is not None and got["schedule"] == params["schedule"]
            # einsum winner must never hand a block to the kernel path.
            if got["schedule"] == "einsum":
                assert got["block"] is None
        finally:
            autotune.set_default_db(None)

    def test_resolve_blocks_db_override(self):
        from deeplearning_mpi_tpu.ops.pallas.flash_attention import (
            DEFAULT_BLOCK_K,
            DEFAULT_BLOCK_Q,
            resolve_blocks,
        )

        db = autotune.TuningDB()
        db.record("flash_attention", self.SHAPE, F32,
                  {"block_q": 16, "block_k": 32})
        autotune.set_default_db(db)
        try:
            assert resolve_blocks(None, None, self.SHAPE, F32) == (16, 32)
            # Explicit kwargs always beat the DB, per-axis.
            assert resolve_blocks(8, None, self.SHAPE, F32) == (8, 32)
            assert resolve_blocks(None, 8, self.SHAPE, F32) == (16, 8)
            # Untuned shape: module defaults.
            assert resolve_blocks(None, None, (1, 128, 2, 16), F32) == (
                DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
            )
        finally:
            autotune.set_default_db(None)

    def test_broken_default_db_never_raises(self):
        from deeplearning_mpi_tpu.ops.pallas.flash_attention import (
            resolve_blocks,
        )

        class Broken:
            def lookup(self, *a, **k):
                raise RuntimeError("boom")

        autotune._default_db = Broken()  # simulate a poisoned DB object
        try:
            assert autotune.tuned_attention_blocks(self.SHAPE, F32) is None
            assert resolve_blocks(None, None, self.SHAPE, F32)
        finally:
            autotune.set_default_db(None)


# -- decode bucket tuner (`decode_bucket|...`) + spec-k (`spec_k|...`) --------

class TestDecodeBucketTuning:
    SHAPE = (2, 64, 2, 16)

    def test_pow2_bucket(self):
        assert autotune.pow2_bucket(1) == 1
        assert autotune.pow2_bucket(3) == 4
        assert autotune.pow2_bucket(8) == 8
        assert autotune.pow2_bucket(9) == 16
        # Clamped to the buffer's real extent: a bucket can never name a
        # condition the gathered pool cannot hold.
        assert autotune.pow2_bucket(40, cap=32) == 32
        assert autotune.pow2_bucket(0) == 1

    def test_decode_bucket_key_canonical(self):
        key = autotune.decode_bucket_key(2, 64, self.SHAPE, F32, backend="cpu")
        assert key == "decode_bucket|b2xc64|2x64x2x16|float32|cpu"
        # dtype objects and names collapse to one spelling.
        assert key == autotune.decode_bucket_key(
            2, 64, self.SHAPE, "float32", backend="cpu"
        )

    def test_expected_tokens_per_step(self):
        # a=0: only the bonus token ever lands. a=1: all k + bonus.
        assert autotune.expected_tokens_per_step(0.0, 4) == 1.0
        assert autotune.expected_tokens_per_step(1.0, 4) == 5.0
        # Truncated geometric series: a=0.5, k=2 -> 1 + .5 + .25 = 1.75.
        assert autotune.expected_tokens_per_step(0.5, 2) == 1.75
        # Out-of-range rates clamp instead of exploding.
        assert autotune.expected_tokens_per_step(2.0, 3) == 4.0

    def test_tune_buckets_round_trip_and_live_consult(self, tmp_path):
        db = autotune.TuningDB(tmp_path / "b.json")
        tuned = autotune.tune_decode_buckets(
            self.SHAPE, F32, db=db,
            batch_buckets=(1, 2), context_buckets=(32, 64),
            blocks=(16,), repeats=1,
        )
        assert len(tuned) == 4
        for key, params in tuned.items():
            assert key.startswith("decode_bucket|")
            assert params["schedule"] in ("kernel", "einsum")
        db.save()
        autotune.set_default_db(autotune.TuningDB.load(db.path))
        try:
            # Live values bucket up: batch 2 -> b2, context 40 -> c64.
            got = autotune.tuned_decode_bucket(2, 40, self.SHAPE, F32)
            want = tuned[autotune.decode_bucket_key(2, 64, self.SHAPE, F32)]
            assert got == want
            # An untuned dtype misses cleanly.
            assert (
                autotune.tuned_decode_bucket(2, 40, self.SHAPE, jnp.bfloat16)
                is None
            )
        finally:
            autotune.set_default_db(None)
        # No DB installed: consult degrades to None, never raises.
        assert autotune.tuned_decode_bucket(2, 40, self.SHAPE, F32) is None

    def test_bucket_consult_never_raises(self):
        class Broken:
            def lookup_key(self, *a, **k):
                raise RuntimeError("boom")

        autotune._default_db = Broken()
        try:
            assert (
                autotune.tuned_decode_bucket(2, 40, self.SHAPE, F32) is None
            )
            assert (
                autotune.tuned_spec_k(
                    __import__(
                        "deeplearning_mpi_tpu.models", fromlist=["models"]
                    ).TransformerConfig.tiny(),
                    1, F32,
                ) is None
            )
        finally:
            autotune.set_default_db(None)

    def test_tune_spec_k_records_winner(self, tmp_path):
        from deeplearning_mpi_tpu.models import TransformerConfig

        db = autotune.TuningDB(tmp_path / "s.json")
        won = autotune.tune_spec_k(
            draft_layers=1, db=db, candidates=(0, 2),
            num_requests=2, max_new_tokens=8,
        )
        assert isinstance(won["spec_k"], int) and won["spec_k"] in (0, 2)
        autotune.set_default_db(db)
        try:
            got = autotune.tuned_spec_k(TransformerConfig.tiny(), 1, F32)
            assert got is not None and got["spec_k"] == won["spec_k"]
            # A different draft depth is a different key: clean miss.
            assert autotune.tuned_spec_k(TransformerConfig.tiny(), 3, F32) is None
        finally:
            autotune.set_default_db(None)


# -- whole-step schedule tuner (`step|...` key space) -------------------------

class TestStepTuning:
    def test_key_canonical_across_mesh_forms(self):
        key = autotune.step_tuning_key(
            "lm", (8, 16), {"data": 2}, F32, backend="cpu"
        )
        assert key == "step|lm|8x16|data2|float32|cpu"
        # Size-1 axes carry no sharding: a MeshSpec that materializes every
        # axis and a hand-built data-only Mesh must agree on the key.
        assert autotune.step_tuning_key(
            "lm", (8, 16), {"data": 2, "pipe": 1, "model": 1}, F32,
            backend="cpu",
        ) == key
        assert autotune.step_tuning_key(
            "lm", (8, 16), "data2", F32, backend="cpu"
        ) == key
        # All-size-1 mesh canonicalizes to "1", not an empty field.
        assert autotune.step_tuning_key(
            "lm", (8, 16), {"data": 1}, F32, backend="cpu"
        ) == "step|lm|8x16|1|float32|cpu"

    def test_step_candidates_space(self):
        flat = autotune.step_candidates(1)
        assert flat and all(not c["overlap"] for c in flat)
        dp = autotune.step_candidates(2)
        assert any(c["overlap"] for c in dp)
        assert {c["remat"] for c in dp} == set(autotune.STEP_REMAT_CANDIDATES)
        # Overlap doubles the space; nothing else changes.
        assert len(dp) == 2 * len(flat)

    def test_tune_persists_verified_winner_and_round_trips(self, tmp_path):
        from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

        db = autotune.TuningDB(tmp_path / "t.json")
        params = autotune.tune_step_schedule(
            "lm", batch_size=8, seq_len=16, db=db,
            candidates=[
                {"remat": "none", "grad_accum": 1, "donate": False,
                 "overlap": False},
                {"remat": "dots", "grad_accum": 2, "donate": False,
                 "overlap": False},
                # 8 % 3 != 0 — must be recorded rejected, not attempted.
                {"remat": "none", "grad_accum": 3, "donate": False,
                 "overlap": False},
            ],
            steps=3, repeats=1,
        )
        assert set(params) == {"remat", "grad_accum", "donate", "overlap"}
        db.save()
        text = (tmp_path / "t.json").read_text()
        assert '"rejected": "unsupported"' in text  # the ga=3 candidate
        # Round-trip through a freshly loaded DB, consulting with the same
        # (default) mesh the tuner keyed on.
        back = autotune.TuningDB.load(tmp_path / "t.json")
        mesh = create_mesh(MeshSpec(data=len(jax.devices())))
        got = autotune.tuned_step_schedule("lm", (8, 16), mesh, F32, db=back)
        assert got == params
        # The consult is logged for bench provenance (key + recorded median).
        assert back.consulted and back.consulted[0]["params"] == params
        assert back.consulted[0]["key"].startswith("step|lm|8x16|")
        assert back.consulted[0]["best_seconds"] > 0

    def test_tuned_step_schedule_never_raises(self, tmp_path):
        mesh = {"data": 2}
        # Empty DB and corrupt-file DB: miss, not exception.
        assert autotune.tuned_step_schedule(
            "lm", (8, 16), mesh, F32, db=autotune.TuningDB()
        ) is None
        p = tmp_path / "bad.json"
        p.write_text("{not json")
        assert autotune.tuned_step_schedule(
            "lm", (8, 16), mesh, F32, db=autotune.TuningDB.load(p)
        ) is None

        class Broken:
            def lookup_key(self, *a, **k):
                raise RuntimeError("boom")

        # A poisoned DB object — passed explicitly or installed as the
        # process default — degrades to None, never into the training run.
        assert autotune.tuned_step_schedule(
            "lm", (8, 16), mesh, F32, db=Broken()
        ) is None
        autotune._default_db = Broken()
        try:
            assert autotune.tuned_step_schedule("lm", (8, 16), mesh, F32) is None
        finally:
            autotune.set_default_db(None)

    def test_non_lm_model_rejected(self, tmp_path):
        import pytest

        with pytest.raises(ValueError, match="lm"):
            autotune.tune_step_schedule(
                "classification", batch_size=8, seq_len=16,
                db=autotune.TuningDB(tmp_path / "t.json"), steps=1, repeats=1,
            )


# -- donation veto policy (regression: XLA:CPU heap corruption) ---------------

class TestDonationPolicy:
    def test_policy_matrix(self):
        assert ccache.donation_safe("cpu", True) is False
        assert ccache.donation_safe("cpu", False) is True
        assert ccache.donation_safe("tpu", True) is True
        assert ccache.donation_safe("gpu", True) is True

    def test_live_config_vetoed_under_test_cache(self):
        # conftest.py enables the persistent cache on CPU — the exact
        # configuration the veto exists for.
        from deeplearning_mpi_tpu.runtime.compat import (
            buffer_donation_supported,
        )

        assert jax.config.jax_compilation_cache_dir
        assert ccache.donation_safe() is False
        assert buffer_donation_supported() is False  # compat shim delegates

    def test_compile_program_strips_donation(self):
        prog = aot.compile_program(
            "donation_probe", lambda x: x * 2.0,
            jnp.ones((4,), F32), donate_argnums=(0,),
        )
        assert prog.donated == ()
        np.testing.assert_allclose(
            np.asarray(prog(jnp.ones((4,), F32))), 2.0 * np.ones((4,))
        )


# -- CompileCache management (fabricated entries; no real compiles) -----------

def _fake_entry(path, name, size, age):
    """One synthetic `jit_*-cache` entry + its `-atime` sibling, `age`
    seconds old in LRU terms."""
    entry = path / f"jit_{name}-cache"
    entry.write_bytes(b"x" * size)
    atime = path / f"jit_{name}-atime"
    atime.write_bytes(b"")
    t = 1_700_000_000 + age
    os.utime(atime, (t, t))
    return entry


class TestCompileCache:
    def test_entries_lru_order_and_stats(self, tmp_path):
        _fake_entry(tmp_path, "b", 10, age=200)
        _fake_entry(tmp_path, "a", 30, age=100)
        cache = ccache.CompileCache(tmp_path)
        names = [e.name for e in cache.entries()]
        assert names == ["jit_a-cache", "jit_b-cache"]  # oldest-used first
        assert cache.size_bytes() == 40
        assert cache.stats()["entries"] == 2

    def test_evict_lru(self, tmp_path):
        registry = MetricsRegistry()
        _fake_entry(tmp_path, "old", 100, age=0)
        _fake_entry(tmp_path, "mid", 100, age=100)
        kept = _fake_entry(tmp_path, "hot", 100, age=200)
        cache = ccache.CompileCache(tmp_path, registry=registry)
        evicted = cache.evict(max_bytes=150)
        assert [e.name for e in evicted] == ["jit_old-cache", "jit_mid-cache"]
        assert kept.exists()
        assert not (tmp_path / "jit_old-cache").exists()
        assert not (tmp_path / "jit_old-atime").exists()  # sibling removed
        assert registry.counter("compile_cache_evicted_total").value == 2
        assert cache.evict(max_bytes=150) == []  # already fits

    def test_quarantine_corrupt_entry(self, tmp_path):
        registry = MetricsRegistry()
        good = _fake_entry(tmp_path, "good", 50, age=0)
        bad = _fake_entry(tmp_path, "bad", 50, age=0)
        cache = ccache.CompileCache(tmp_path, registry=registry)
        cache.write_manifest()
        bad.write_bytes(b"flipped bits")  # corrupt after manifest
        assert cache.verify() == ["jit_bad-cache"]
        assert not bad.exists()
        qdir = tmp_path / ccache.QUARANTINE_DIR
        assert (qdir / "jit_bad-cache").exists()
        assert (qdir / "jit_bad-atime").exists()
        assert good.exists()
        assert registry.counter("compile_cache_quarantined_total").value == 1
        assert cache.verify() == []  # quarantined entry no longer listed

    def test_new_entries_pass_verify(self, tmp_path):
        cache = ccache.CompileCache(tmp_path)
        _fake_entry(tmp_path, "a", 10, age=0)
        cache.write_manifest()
        _fake_entry(tmp_path, "later", 10, age=10)  # post-manifest entry
        assert cache.verify() == []

    def test_disabled_cache_degrades(self, tmp_path):
        cache = ccache.CompileCache(tmp_path / "missing")
        assert not cache.enabled
        assert cache.entries() == []
        assert cache.evict(0) == []
        assert cache.verify() == []
        assert cache.observe_compile("x", 0.1, frozenset()) is None


# -- AOT compile + warmup -----------------------------------------------------

class TestAOT:
    def test_abstractify(self):
        tree = {"a": jnp.ones((2, 3), jnp.bfloat16), "b": np.zeros((4,))}
        out = aot.abstractify(tree)
        assert out["a"] == jax.ShapeDtypeStruct((2, 3), jnp.bfloat16)
        assert out["b"].shape == (4,)

    def test_compile_program_matches_jit(self):
        f = lambda x, y: (x @ y).sum()
        x = jnp.arange(12.0).reshape(3, 4)
        y = jnp.ones((4, 5))
        prog = aot.compile_program("matmul_sum", f, x, y)
        np.testing.assert_allclose(np.asarray(prog(x, y)), np.asarray(f(x, y)))
        assert prog.lower_seconds >= 0 and prog.compile_seconds >= 0

    def test_cold_then_warm_cache_classification(self, tmp_path):
        prev_dir = jax.config.jax_compilation_cache_dir
        prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
        try:
            ccache.enable(tmp_path / "xla")  # min_compile_time 0: persist all
            x = jnp.arange(8.0)

            reg1 = MetricsRegistry()
            cold = aot.compile_program(
                "probe", jax.jit(lambda x: (x * 3.0 + 1.0).sum()), x,
                cache=ccache.CompileCache(registry=reg1),
            )
            assert cold.cache_hit is False
            assert reg1.counter("compile_cache_miss_total").value == 1

            reg2 = MetricsRegistry()  # fresh jit object, identical program
            warm = aot.compile_program(
                "probe", jax.jit(lambda x: (x * 3.0 + 1.0).sum()), x,
                cache=ccache.CompileCache(registry=reg2),
            )
            assert warm.cache_hit is True
            assert reg2.counter("compile_cache_hit_total").value == 1
            np.testing.assert_allclose(np.asarray(cold(x)), np.asarray(warm(x)))
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", prev_min
            )
            ccache._reset_backend_cache()  # un-pin the tmp dir

    def test_warm_program_fallback_on_shape_drift(self):
        jitted = jax.jit(lambda x: x * 2.0)
        prog = aot.compile_program("doubler", jitted, jnp.ones((8,), F32))
        warm = aot.WarmProgram(prog, jitted)
        np.testing.assert_allclose(
            np.asarray(warm(jnp.ones((8,), F32))), 2.0 * np.ones((8,))
        )
        assert warm.fallback_calls == 0
        # Unseen aval: the Compiled rejects, the fallback answers.
        np.testing.assert_allclose(
            np.asarray(warm(jnp.ones((4,), F32))), 2.0 * np.ones((4,))
        )
        assert warm.fallback_calls == 1

    def test_warmup_registry_sweep(self):
        registry = MetricsRegistry()
        reg = aot.WarmupRegistry(registry=registry)
        reg.register("f", lambda x: x + 1.0, jnp.zeros((3,), F32))
        reg.register("g", lambda x: x * 2.0, jnp.zeros((3,), F32))
        programs = reg.warm_all()
        assert set(programs) == {"f", "g"}
        np.testing.assert_allclose(
            np.asarray(reg.get("f")(jnp.zeros((3,), F32))), np.ones((3,))
        )


# -- warmed engine / trainer --------------------------------------------------

class TestWarmedEngine:
    def _engine(self, registry):
        from deeplearning_mpi_tpu.models import (
            TransformerConfig,
            TransformerLM,
        )
        from deeplearning_mpi_tpu.serving import EngineConfig, ServingEngine

        cfg = TransformerConfig.tiny()
        params = TransformerLM(config=cfg, dtype=F32).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        return ServingEngine(
            cfg, params,
            EngineConfig(max_slots=2, block_size=8, num_blocks=16,
                         max_blocks_per_seq=4, prefill_chunk=8, max_queue=8),
            dtype=F32, registry=registry,
        )

    def test_zero_compiles_on_first_request(self):
        from deeplearning_mpi_tpu.serving import RequestState

        registry = MetricsRegistry()
        engine = self._engine(registry)
        engine.warmup()
        # Warmup traced the two AOT programs once each (the trace-time tick
        # in _decode_step/_prefill_chunk) plus one decode variant per
        # narrower gather-width bucket (here widths [1, 2] below MB=4).
        compiles = registry.counter("serve_compile_total").value
        assert compiles == 2 + (len(engine._gather_widths()) - 1)
        req = engine.submit(np.arange(1, 9, dtype=np.int32), 4)
        while not engine.scheduler.idle():
            engine.step()
        assert req.state is RequestState.FINISHED
        # The actual contract: the first request compiled NOTHING.
        assert registry.counter("serve_compile_total").value == compiles
        # Prefill stayed on the AOT executable. Decode rows holding fewer
        # than max_blocks_per_seq blocks dispatch through the pre-traced
        # narrow-width jit — counted as fallback calls, but the zero
        # compile-delta above proves those widths were already warm.
        assert engine._prefill_fn.fallback_calls == 0

    def test_tuned_einsum_buckets_stay_on_base_program(self):
        """A decode_bucket| entry whose winner IS the base program's
        schedule (einsum, no block) must not spawn a duplicate lazy-compiled
        variant — the warmed engine stays at zero compiles even with
        per-bucket consults live (use_kernel=None)."""
        from deeplearning_mpi_tpu.compiler import autotune
        from deeplearning_mpi_tpu.models import (
            TransformerConfig,
            TransformerLM,
        )
        from deeplearning_mpi_tpu.serving import (
            EngineConfig,
            RequestState,
            ServingEngine,
        )

        cfg = TransformerConfig.tiny()
        params = TransformerLM(config=cfg, dtype=F32).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        ecfg = EngineConfig(max_slots=2, block_size=8, num_blocks=16,
                            max_blocks_per_seq=4, prefill_chunk=8,
                            max_queue=8, use_kernel=None)
        shape = (2, 32, cfg.num_kv_heads or cfg.num_heads, cfg.head_dim)
        db = autotune.TuningDB()
        for bb in (1, 2):
            for cb in (8, 16, 32):
                db.record_key(
                    autotune.decode_bucket_key(bb, cb, shape, F32),
                    {"schedule": "einsum", "block": None},
                )
        autotune.set_default_db(db)
        try:
            registry = MetricsRegistry()
            engine = ServingEngine(
                cfg, params, ecfg, dtype=F32, registry=registry,
            )
            engine.warmup()
            compiles = registry.counter("serve_compile_total").value
            req = engine.submit(np.arange(1, 9, dtype=np.int32), 4)
            while not engine.scheduler.idle():
                engine.step()
            assert req.state is RequestState.FINISHED
            assert db.consulted, "bucket entries were never consulted"
            assert engine._decode_variants == {}
            assert registry.counter("serve_compile_total").value == compiles
        finally:
            autotune.set_default_db(None)

    def test_warmed_matches_unwarmed_tokens(self):
        from deeplearning_mpi_tpu.serving import RequestState

        prompt = np.arange(1, 9, dtype=np.int32)

        def run(warm):
            engine = self._engine(MetricsRegistry())
            if warm:
                engine.warmup()
            req = engine.submit(prompt, 4)
            while not engine.scheduler.idle():
                engine.step()
            assert req.state is RequestState.FINISHED
            return req.generated

        assert run(warm=True) == run(warm=False)


class TestTrainerWarmup:
    def test_trainer_warmup_swaps_working_step(self, mesh):
        import optax

        from deeplearning_mpi_tpu.models import (
            TransformerConfig,
            TransformerLM,
        )
        from deeplearning_mpi_tpu.train import Trainer, create_train_state

        model = TransformerLM(config=TransformerConfig.tiny(), dtype=F32)
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32),
            optax.sgd(1e-2),
        )
        trainer = Trainer(state, "lm", mesh)
        batch = {
            "tokens": jnp.asarray(
                np.random.default_rng(0).integers(0, 256, (4, 16)), jnp.int32
            )
        }
        prog = trainer.warmup(batch)
        assert isinstance(trainer.train_step, aot.WarmProgram)
        assert prog.compile_seconds >= 0
        new_state, metrics = trainer.train_step(trainer.state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert int(new_state.step) == int(state.step) + 1
        assert trainer.train_step.fallback_calls == 0
