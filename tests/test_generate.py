"""KV-cache decoding + generation tests: cached decode vs the full forward."""

import pytest

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.models.generate import generate, generate_jit, sample_logits


def _model_and_params(seq=16, batch=2):
    cfg = TransformerConfig.tiny()
    model = TransformerLM(config=cfg, dtype=jnp.float32)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    return model, params


def _dense_cfg():
    return TransformerConfig.tiny()


def _windowed_cfg():
    # Sliding-window attention as a MODEL property: window 5 < seq 12 so
    # later positions genuinely drop old keys, and the stepwise decode
    # (decode_attention's windowed cache walk) must reproduce the windowed
    # full forward (dense_attention's window mask) position by position —
    # the train/decode receptive-field consistency claim.
    return dataclasses.replace(TransformerConfig.tiny(), attention_window=5)


def _gqa_cfg():
    # Grouped KV heads: the cache stores Hkv=2 for H=4 query heads, and
    # decode_attention consumes the grouped buffers natively — stepwise
    # decode must still reproduce the full causal forward exactly.
    return dataclasses.replace(
        TransformerConfig.tiny(), num_heads=4, num_kv_heads=2
    )


def _moe_dropfree_cfg():
    # Drop-free routing is the comparison's precondition: decode steps (S=1)
    # never drop a token, so the full forward must not drop either —
    # capacity_factor E/k makes every expert able to absorb all tokens, BY
    # DERIVATION so changed tiny_moe defaults can't silently break it.
    cfg = TransformerConfig.tiny_moe()
    return dataclasses.replace(
        cfg, moe_capacity_factor=cfg.moe_experts / cfg.moe_top_k
    )


def _moe_droppy_cfg():
    # Deliberately TIGHT capacity: the batched full-prompt forward suffers
    # expert contention across prompt positions (drops), which the
    # per-position decode walk never sees. Prefill must route per position
    # (stepwise) for these configs or the "execution-schedule change only"
    # invariant of the fast path / shared_prefix / timed CLI breaks.
    return dataclasses.replace(
        TransformerConfig.tiny_moe(), moe_capacity_factor=0.5
    )


class TestCachedDecode:
    @pytest.mark.slow
    @pytest.mark.parametrize("make_cfg",
                             [_dense_cfg, _moe_dropfree_cfg, _gqa_cfg,
                              _windowed_cfg],
                             ids=["dense", "moe", "gqa", "windowed"])
    def test_stepwise_decode_matches_full_forward(self, make_cfg):
        """Feeding tokens one at a time through the KV cache must reproduce
        the full-sequence causal forward logits position by position."""
        seq = 12
        model = TransformerLM(config=make_cfg(), dtype=jnp.float32)
        tokens_init = jnp.zeros((2, seq), jnp.int32)
        params = model.init(jax.random.key(0), tokens_init)["params"]
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 256, (2, seq)), jnp.int32)

        full_logits = model.apply({"params": params}, tokens)

        decode_model = dataclasses.replace(model, decode=True)
        cache = decode_model.init(jax.random.key(0), tokens_init)["cache"]
        for i in range(seq):
            step_logits, mutated = decode_model.apply(
                {"params": params, "cache": cache},
                tokens[:, i : i + 1],
                positions=jnp.full((2, 1), i, jnp.int32),
                mutable=["cache"],
            )
            cache = mutated["cache"]
            np.testing.assert_allclose(
                np.asarray(step_logits[:, 0]),
                np.asarray(full_logits[:, i]),
                atol=2e-4,
            )

    def test_decode_rejects_multitoken_step(self):
        model, params = _model_and_params()
        decode_model = dataclasses.replace(model, decode=True)
        cache = decode_model.init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["cache"]
        try:
            decode_model.apply(
                {"params": params, "cache": cache},
                jnp.zeros((1, 3), jnp.int32),
                positions=jnp.zeros((1, 3), jnp.int32),
                mutable=["cache"],
            )
        except ValueError as e:
            assert "one token" in str(e)
        else:
            raise AssertionError("expected ValueError for seq>1 decode step")


class TestPrefill:
    """Batched cache-fill forward vs the stepwise decode ground truth."""

    @pytest.mark.parametrize("make_cfg",
                             [_dense_cfg, _gqa_cfg, _windowed_cfg,
                              _moe_dropfree_cfg],
                             ids=["dense", "gqa", "windowed", "moe"])
    def test_prefill_matches_stepwise_cache_and_logits(self, make_cfg):
        """One prefill forward must leave the cache in the same state as
        feeding the prompt token by token, and its logits must equal the
        full causal forward — the two-phase serving path's correctness
        contract (GQA caches grouped heads; windowed masks the chunk)."""
        import dataclasses as dc

        from deeplearning_mpi_tpu.models.generate import prefill

        seq, total = 12, 16
        model = TransformerLM(config=make_cfg(), dtype=jnp.float32)
        tokens_init = jnp.zeros((2, total), jnp.int32)
        params = model.init(jax.random.key(0), tokens_init)["params"]
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 256, (2, seq)), jnp.int32)

        full_logits = model.apply({"params": params}, tokens)
        cache_pre, logits_pre = prefill(
            model, params, tokens, total_len=total, last_logits_only=False
        )
        np.testing.assert_allclose(
            np.asarray(logits_pre), np.asarray(full_logits), atol=2e-4
        )
        # The serving default (last-only via return_prehead) must agree
        # with the full path's final position.
        _, logits_last = prefill(model, params, tokens, total_len=total)
        np.testing.assert_allclose(
            np.asarray(logits_last), np.asarray(full_logits[:, -1]),
            atol=2e-4,
        )

        decode_model = dc.replace(model, decode=True)
        cache_step = decode_model.init(jax.random.key(0), tokens_init)["cache"]
        for i in range(seq):
            _, mutated = decode_model.apply(
                {"params": params, "cache": cache_step},
                tokens[:, i : i + 1],
                positions=jnp.full((2, 1), i, jnp.int32),
                mutable=["cache"],
            )
            cache_step = mutated["cache"]
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            cache_pre, cache_step,
        )

    def test_moe_prefill_routes_per_position(self):
        """Under TIGHT expert capacity, prefill's cache and logits must
        equal the token-by-token decode walk exactly — NOT the batched
        training forward, whose whole-prompt routing drops tokens under
        contention the walk never sees. (Before this route-per-position
        fix, MoE prefill ran training routing, silently changing fast-path
        generate, shared_prefix, beam seeding, and the CLI's timed split
        vs the stepwise scan — ADVICE's schedule-invariance break.)"""
        import dataclasses as dc

        from deeplearning_mpi_tpu.models.generate import prefill

        seq, total = 12, 16
        model = TransformerLM(config=_moe_droppy_cfg(), dtype=jnp.float32)
        tokens_init = jnp.zeros((2, total), jnp.int32)
        params = model.init(jax.random.key(0), tokens_init)["params"]
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 256, (2, seq)), jnp.int32)

        cache_pre, logits_pre = prefill(
            model, params, tokens, total_len=total, last_logits_only=False
        )
        decode_model = dc.replace(model, decode=True)
        cache_step = decode_model.init(jax.random.key(0), tokens_init)["cache"]
        step_logits = []
        for i in range(seq):
            logits_i, mutated = decode_model.apply(
                {"params": params, "cache": cache_step},
                tokens[:, i : i + 1],
                positions=jnp.full((2, 1), i, jnp.int32),
                mutable=["cache"],
            )
            cache_step = mutated["cache"]
            step_logits.append(np.asarray(logits_i[:, 0]))
        np.testing.assert_allclose(
            np.asarray(logits_pre), np.stack(step_logits, axis=1), atol=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            cache_pre, cache_step,
        )
        # Precondition making this test meaningful: the batched training
        # forward genuinely drops here (routing contention), so agreeing
        # with the WALK is a real choice, not a vacuous one.
        full_logits = model.apply({"params": params}, tokens)
        assert not np.allclose(
            np.asarray(full_logits), np.asarray(logits_pre), atol=1e-3
        ), "droppy config produced no drops; tighten moe_capacity_factor"

    def test_moe_fast_path_equals_uniform_scan(self):
        """Greedy fast-path generate must stay byte-identical to the forced
        uniform scan for a droppy MoE model — the invariant the stepwise
        MoE prefill restores."""
        model = TransformerLM(config=_moe_droppy_cfg(), dtype=jnp.float32)
        params = model.init(
            jax.random.key(0), jnp.zeros((2, 16), jnp.int32)
        )["params"]
        rng = np.random.default_rng(7)
        prompt = jnp.asarray(rng.integers(0, 256, (2, 5)), jnp.int32)
        fast = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0,
        )
        scan = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0,
            prompt_lens=jnp.asarray([5, 5], jnp.int32),
        )
        np.testing.assert_array_equal(np.asarray(fast), np.asarray(scan))

    def test_fast_path_equals_uniform_scan(self):
        """Greedy generate via prefill+decode must emit byte-identical
        output to the uniform scan (forced via prompt_lens) — the fast path
        is an execution-schedule change, not a semantics change."""
        model, params = _model_and_params(seq=16)
        rng = np.random.default_rng(7)
        prompt = jnp.asarray(rng.integers(0, 256, (2, 5)), jnp.int32)
        fast = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0,
        )
        scan = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0,
            prompt_lens=jnp.asarray([5, 5], jnp.int32),
        )
        np.testing.assert_array_equal(np.asarray(fast), np.asarray(scan))

    def test_fast_path_eos_pads(self):
        """EOS stop-and-pad semantics hold on the two-phase path, including
        an EOS sampled as the very FIRST generated token (the done seed)."""
        model, params = _model_and_params(seq=16)
        prompt = jnp.asarray([[7, 7, 2]], jnp.int32)
        free = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0,
        )
        first = int(np.asarray(free)[0, 3])
        out = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0, eos_id=first,
        )
        np.testing.assert_array_equal(
            np.asarray(out)[0, 3:], np.full(6, first)
        )


class TestGenerate:
    @pytest.mark.slow
    def test_greedy_matches_iterated_full_forward(self):
        """Greedy generation through the cache == argmax-iterating the full
        (uncached) model — end-to-end equivalence of the decode path."""
        model, params = _model_and_params()
        rng = np.random.default_rng(1)
        prompt = jnp.asarray(rng.integers(0, 256, (2, 4)), jnp.int32)
        max_new = 6

        out = generate(
            model, params, prompt,
            max_new_tokens=max_new, rng=jax.random.key(0), temperature=0.0,
        )
        assert out.shape == (2, 10)
        np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))

        # Oracle: repeatedly run the full model and take argmax.
        seq = prompt
        for _ in range(max_new):
            logits = model.apply({"params": params}, seq)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))

    def test_jitted_sampling_runs_and_respects_vocab(self):
        model, params = _model_and_params()
        fn = generate_jit(model, max_new_tokens=5, temperature=0.8, top_k=10)
        prompt = jnp.ones((1, 3), jnp.int32)
        out = fn(params, prompt, jax.random.key(1))
        assert out.shape == (1, 8)
        assert int(out.min()) >= 0 and int(out.max()) < 256


class TestSampleLogits:
    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 0.0]])
        out = sample_logits(logits, jax.random.key(0), temperature=0.0)
        np.testing.assert_array_equal(np.asarray(out), [1, 0])

    def test_top_k_excludes_tail(self):
        logits = jnp.asarray([[10.0, 9.0, -50.0, -60.0]])
        for seed in range(20):
            out = sample_logits(
                logits, jax.random.key(seed), temperature=1.0, top_k=2
            )
            assert int(out[0]) in (0, 1)

    def test_top_p_keeps_smallest_nucleus(self):
        # softmax([2, 1, 0, -9]) ≈ [.70, .26, .095*, ...]: top-1 mass .70
        # clears p=.5 alone; p=.9 needs the top-2 (mass .96); the tail never
        # qualifies at either setting.
        logits = jnp.asarray([[2.0, 1.0, 0.0, -9.0]])
        for seed in range(20):
            only_top = sample_logits(
                logits, jax.random.key(seed), temperature=1.0, top_p=0.5
            )
            assert int(only_top[0]) == 0
            top_two = sample_logits(
                logits, jax.random.key(seed), temperature=1.0, top_p=0.9
            )
            assert int(top_two[0]) in (0, 1)

    def test_top_p_zero_degenerates_to_argmax(self):
        # top_p <= 0 pins the top token instead of masking everything to
        # -inf (which would make categorical silently emit id 0).
        logits = jnp.asarray([[0.5, 3.0, 1.0, 0.0]])
        for seed in range(10):
            out = sample_logits(
                logits, jax.random.key(seed), temperature=1.0, top_p=0.0
            )
            assert int(out[0]) == 1

    def test_top_p_one_is_identity(self):
        logits = jnp.asarray([[0.3, 0.1, -0.2, 0.0]])
        for seed in range(5):
            a = sample_logits(logits, jax.random.key(seed), temperature=1.0)
            b = sample_logits(
                logits, jax.random.key(seed), temperature=1.0, top_p=1.0
            )
            assert int(a[0]) == int(b[0])

    def test_top_p_composes_with_top_k(self):
        # top_k=3 drops index 2 (0.5); over the survivors softmax ≈
        # [.49, .066, —, .443], so top_p=.4 keeps only the argmax (its
        # exclusive cumulative mass 0 < .4, the runner-up's .49 is not).
        logits = jnp.asarray([[3.0, 1.0, 0.5, 2.9]])
        for seed in range(20):
            out = sample_logits(
                logits, jax.random.key(seed), temperature=1.0,
                top_k=3, top_p=0.4,
            )
            assert int(out[0]) == 0


class TestBeamSearch:
    """Beam search: the deterministic multi-hypothesis decode path."""

    def _tiny(self, vocab=6, seed=1):
        from deeplearning_mpi_tpu.models.generate import beam_search  # noqa: F401

        cfg = dataclasses.replace(TransformerConfig.tiny(), vocab_size=vocab)
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(
            jax.random.key(seed), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        return model, params

    def test_single_beam_equals_greedy(self):
        from deeplearning_mpi_tpu.models.generate import beam_search

        model, params = self._tiny(vocab=16)
        prompt = jnp.asarray([[3, 1, 4, 1]], jnp.int32)
        greedy = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0,
        )
        beam = beam_search(model, params, prompt, max_new_tokens=6, num_beams=1)
        np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))

    @pytest.mark.slow
    def test_wide_beam_finds_global_optimum(self):
        """With W >= vocab^(new-1) every prefix survives, so beam search is
        exhaustive and must return the continuation the full causal forward
        scores highest — catches backtrace frame bugs, cache-gather
        misalignment, and seed-step errors in one assertion."""
        import itertools

        from deeplearning_mpi_tpu.models.generate import beam_search

        vocab, new = 6, 3
        model, params = self._tiny(vocab)
        prompt = jnp.asarray([[2, 5, 0]], jnp.int32)
        conts = np.array(
            list(itertools.product(range(vocab), repeat=new)), np.int32
        )
        full = np.concatenate(
            [np.repeat(np.asarray(prompt), len(conts), 0), conts], axis=1
        )
        logits = model.apply({"params": params}, jnp.asarray(full))
        logp = np.asarray(jax.nn.log_softmax(logits.astype(jnp.float32), -1))
        p_len = prompt.shape[1]
        scores = sum(
            logp[np.arange(len(conts)), p_len - 1 + j, conts[:, j]]
            for j in range(new)
        )
        best = conts[int(np.argmax(scores))]
        out = beam_search(
            model, params, prompt, max_new_tokens=new, num_beams=vocab**2
        )
        np.testing.assert_array_equal(np.asarray(out)[0, p_len:], best)

    def test_prompt_preserved_and_batch_rows_independent(self):
        from deeplearning_mpi_tpu.models.generate import beam_search

        model, params = self._tiny(vocab=16)
        prompts = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        both = beam_search(model, params, prompts, max_new_tokens=4, num_beams=3)
        np.testing.assert_array_equal(np.asarray(both)[:, :3], np.asarray(prompts))
        for b in range(2):
            solo = beam_search(
                model, params, prompts[b : b + 1], max_new_tokens=4, num_beams=3
            )
            np.testing.assert_array_equal(np.asarray(both)[b], np.asarray(solo)[0])


class TestEOS:
    """eos_id: stop-and-pad semantics for sampling and beam search."""

    def _tiny(self, vocab=6, seed=1):
        cfg = dataclasses.replace(TransformerConfig.tiny(), vocab_size=vocab)
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(
            jax.random.key(seed), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        return model, params

    def test_greedy_pads_after_first_eos(self):
        model, params = self._tiny(vocab=16)
        prompt = jnp.asarray([[7, 7, 2]], jnp.int32)
        free = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0,
        )
        first = int(np.asarray(free)[0, 3])  # first generated token
        out = generate(
            model, params, prompt, max_new_tokens=6,
            rng=jax.random.key(0), temperature=0.0, eos_id=first,
        )
        # The row finishes at its first generated position; everything
        # after is EOS padding. Prompt occurrences of the byte don't count.
        np.testing.assert_array_equal(
            np.asarray(out)[0, 3:], np.full(6, first)
        )
        np.testing.assert_array_equal(np.asarray(out)[0, :3], [7, 7, 2])

    @pytest.mark.slow
    def test_exhaustive_beam_with_eos_matches_bruteforce(self):
        """Canonical sequences (everything after the first EOS is EOS) are
        scored by their pre-EOS log-prob; with an exhaustive beam width the
        search must return the best canonical sequence — pins the
        finished-beam freeze (EOS extension at zero cost) and padding."""
        import itertools

        from deeplearning_mpi_tpu.models.generate import beam_search

        vocab, new, eos = 6, 3, 2
        model, params = self._tiny(vocab)
        prompt = jnp.asarray([[4, 1, 3]], jnp.int32)
        p_len = prompt.shape[1]
        conts = np.array(
            list(itertools.product(range(vocab), repeat=new)), np.int32
        )
        full = np.concatenate(
            [np.repeat(np.asarray(prompt), len(conts), 0), conts], axis=1
        )
        logp = np.asarray(jax.nn.log_softmax(
            model.apply({"params": params}, jnp.asarray(full)).astype(
                jnp.float32
            ), -1,
        ))

        def canonical_score(row, cont):
            # sum through the first EOS inclusive; None if not canonical
            s, done = 0.0, False
            for j, t in enumerate(cont):
                if done:
                    if t != eos:
                        return None
                    continue  # forced padding, free
                s += logp[row, p_len - 1 + j, t]
                done = t == eos
            return s

        scored = [
            (canonical_score(r, c), c) for r, c in enumerate(conts)
        ]
        best_score, best = max(
            ((s, c) for s, c in scored if s is not None), key=lambda x: x[0]
        )
        out = beam_search(
            model, params, prompt, max_new_tokens=new, num_beams=vocab**2,
            eos_id=eos,
        )
        got = np.asarray(out)[0, p_len:]
        got_score = canonical_score(
            int(np.argwhere((conts == got).all(1))[0, 0]), got
        )
        # Ties between canonical sequences are possible in principle;
        # compare SCORES, not token identity.
        np.testing.assert_allclose(got_score, best_score, atol=1e-5)

    def test_length_penalty_requires_eos(self):
        from deeplearning_mpi_tpu.models.generate import beam_search

        model, params = self._tiny()
        with pytest.raises(ValueError, match="length_penalty requires"):
            beam_search(
                model, params, jnp.zeros((1, 2), jnp.int32),
                max_new_tokens=2, num_beams=2, length_penalty=0.6,
            )

    def test_length_penalty_runs_with_eos(self):
        from deeplearning_mpi_tpu.models.generate import beam_search

        model, params = self._tiny()
        out = beam_search(
            model, params, jnp.zeros((1, 2), jnp.int32),
            max_new_tokens=3, num_beams=3, eos_id=2, length_penalty=0.6,
        )
        assert out.shape == (1, 5)


class TestRaggedBatch:
    """prompt_lens: batched prompts of different lengths in one program."""

    def test_ragged_greedy_rows_match_solo_runs(self):
        cfg = dataclasses.replace(TransformerConfig.tiny(), vocab_size=32)
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(
            jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        new = 4
        p_a = jnp.asarray([[5, 9, 11, 2, 7]], jnp.int32)   # len 5
        p_b = jnp.asarray([[8, 1]], jnp.int32)             # len 2
        solo_a = generate(
            model, params, p_a, max_new_tokens=new,
            rng=jax.random.key(0), temperature=0.0,
        )
        solo_b = generate(
            model, params, p_b, max_new_tokens=new,
            rng=jax.random.key(0), temperature=0.0,
        )
        padded = jnp.asarray(
            [[5, 9, 11, 2, 7], [8, 1, 0, 0, 0]], jnp.int32
        )
        out = generate(
            model, params, padded, max_new_tokens=new,
            rng=jax.random.key(0), temperature=0.0,
            prompt_lens=jnp.asarray([5, 2], jnp.int32),
        )
        # Row a: full-length prompt — its window is the whole output. Row
        # b: compare its own len+new window against the solo run (greedy,
        # so the shared rng is irrelevant).
        np.testing.assert_array_equal(np.asarray(out)[0], np.asarray(solo_a)[0])
        np.testing.assert_array_equal(
            np.asarray(out)[1, : 2 + new], np.asarray(solo_b)[0]
        )

    def test_pad_bytes_never_fed(self):
        # Poison the pad region with a huge in-vocab byte: if it were fed,
        # row b's continuation would change vs the solo run above — covered
        # there — but also check directly that output row b's window start
        # equals its own prompt, not the pad.
        cfg = dataclasses.replace(TransformerConfig.tiny(), vocab_size=32)
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(
            jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        padded = jnp.asarray([[8, 1, 31, 31, 31]], jnp.int32)
        out = generate(
            model, params, padded, max_new_tokens=2,
            rng=jax.random.key(0), temperature=0.0,
            prompt_lens=jnp.asarray([2], jnp.int32),
        )
        np.testing.assert_array_equal(np.asarray(out)[0, :2], [8, 1])
        assert not np.array_equal(np.asarray(out)[0, 2:5], [31, 31, 31])

    def test_shared_prefix_matches_full_scan(self):
        """shared_prefix (the CLI's min-length hint) must be an execution-
        schedule change only: greedy outputs equal the full per-row-switch
        scan for every prefix length up to min(prompt_lens)."""
        cfg = dataclasses.replace(TransformerConfig.tiny(), vocab_size=32)
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(
            jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        padded = jnp.asarray(
            [[5, 9, 11, 2, 7], [8, 1, 0, 0, 0]], jnp.int32
        )
        plens = jnp.asarray([5, 2], jnp.int32)
        base = generate(
            model, params, padded, max_new_tokens=4,
            rng=jax.random.key(0), temperature=0.0, prompt_lens=plens,
        )
        for prefix in (1, 2):  # up to min(plens)
            out = generate(
                model, params, padded, max_new_tokens=4,
                rng=jax.random.key(0), temperature=0.0, prompt_lens=plens,
                shared_prefix=prefix,
            )
            np.testing.assert_array_equal(np.asarray(out), np.asarray(base))

    def test_moe_shared_prefix_matches_full_scan(self):
        """shared_prefix prefills via the stepwise MoE path, so a droppy
        MoE model must still produce scan-identical greedy output — the
        ragged-batch face of the same schedule-invariance contract."""
        model = TransformerLM(config=_moe_droppy_cfg(), dtype=jnp.float32)
        params = model.init(
            jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        padded = jnp.asarray(
            [[5, 9, 11, 2, 7], [8, 1, 0, 0, 0]], jnp.int32
        )
        plens = jnp.asarray([5, 2], jnp.int32)
        base = generate(
            model, params, padded, max_new_tokens=4,
            rng=jax.random.key(0), temperature=0.0, prompt_lens=plens,
        )
        out = generate(
            model, params, padded, max_new_tokens=4,
            rng=jax.random.key(0), temperature=0.0, prompt_lens=plens,
            shared_prefix=2,
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(base))

    def test_shared_prefix_composes_with_eos(self):
        """EOS done-seed at the prefix boundary: a row whose whole prompt
        fits the prefix and whose FIRST sample is the EOS must pad from
        there, exactly like the full scan."""
        cfg = dataclasses.replace(TransformerConfig.tiny(), vocab_size=32)
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(
            jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        padded = jnp.asarray(
            [[5, 9, 11, 2, 7], [8, 1, 0, 0, 0]], jnp.int32
        )
        plens = jnp.asarray([5, 2], jnp.int32)
        # Row b's first greedy token (position 2) becomes the EOS.
        free = generate(
            model, params, padded, max_new_tokens=4,
            rng=jax.random.key(0), temperature=0.0, prompt_lens=plens,
        )
        eos = int(np.asarray(free)[1, 2])
        base = generate(
            model, params, padded, max_new_tokens=4,
            rng=jax.random.key(0), temperature=0.0, prompt_lens=plens,
            eos_id=eos,
        )
        out = generate(
            model, params, padded, max_new_tokens=4,
            rng=jax.random.key(0), temperature=0.0, prompt_lens=plens,
            eos_id=eos, shared_prefix=2,
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(base))
        # And row b really padded with EOS from its first generated slot.
        np.testing.assert_array_equal(
            np.asarray(out)[1, 2:6], np.full(4, eos)
        )

    def test_ragged_batch_composes_with_eos(self):
        # Per-row EOS selection windows (i >= plens[b]-1) with per-row
        # prompt switches: each ragged row must equal its solo run under
        # the same eos_id, including the post-EOS padding.
        cfg = dataclasses.replace(TransformerConfig.tiny(), vocab_size=32)
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(
            jax.random.key(3), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        new = 5
        p_a = jnp.asarray([[5, 9, 11, 2, 7]], jnp.int32)
        p_b = jnp.asarray([[8, 1]], jnp.int32)
        # Pick row b's first greedy token as the EOS: row b must pad from
        # its first generated position; row a stops only if it emits the
        # same byte.
        free_b = generate(
            model, params, p_b, max_new_tokens=new,
            rng=jax.random.key(0), temperature=0.0,
        )
        eos = int(np.asarray(free_b)[0, 2])
        solo = [
            generate(
                model, params, p, max_new_tokens=new,
                rng=jax.random.key(0), temperature=0.0, eos_id=eos,
            )
            for p in (p_a, p_b)
        ]
        padded = jnp.asarray(
            [[5, 9, 11, 2, 7], [8, 1, 0, 0, 0]], jnp.int32
        )
        out = generate(
            model, params, padded, max_new_tokens=new,
            rng=jax.random.key(0), temperature=0.0, eos_id=eos,
            prompt_lens=jnp.asarray([5, 2], jnp.int32),
        )
        np.testing.assert_array_equal(np.asarray(out)[0], np.asarray(solo[0])[0])
        np.testing.assert_array_equal(
            np.asarray(out)[1, : 2 + new], np.asarray(solo[1])[0]
        )
        # And row b really did stop: padding from its first generated slot.
        np.testing.assert_array_equal(
            np.asarray(out)[1, 2 : 2 + new], np.full(new, eos)
        )
