"""tools/accuracy_run.py stays alive: the offline real-data accuracy path."""

import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

TOOLS = Path(__file__).parent.parent / "tools"


def _load():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "accuracy_run", TOOLS / "accuracy_run.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestDigitsDataset:
    def test_shapes_and_split(self):
        mod = _load()
        train = mod.DigitsAsImages(train=True)
        test = mod.DigitsAsImages(train=False)
        assert len(train) + len(test) == 1797
        assert len(test) == pytest.approx(0.2 * 1797, abs=1)
        ex = train[0]
        assert ex["image"].shape == (32, 32, 3)
        assert ex["image"].dtype.name == "uint8"
        # Disjoint split: no index appears in both (seeded permutation).
        import numpy as np

        a = {bytes(train[i]["image"].tobytes()) for i in range(20)}
        b = {bytes(test[i]["image"].tobytes()) for i in range(20)}
        # (hash-of-pixels overlap is possible in theory but not for digits)
        assert not (a & b)
        assert np.unique(train.labels).size == 10

    def test_one_epoch_runs(self, tmp_path):
        mod = _load()
        rc = mod.main([
            "--num_epochs", "1", "--eval_every", "1",
            "--min_accuracy", "0.0",
            "--log_dir", str(tmp_path / "logs"),
        ])
        assert rc == 0
        assert any((tmp_path / "logs").iterdir())
