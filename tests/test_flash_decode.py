"""Fused flash-decode kernel vs the blockwise-walk oracle.

Runs the Pallas interpreter on CPU (same kernel code the TPU compiles,
minus Mosaic lowering — the on-chip benchmark exercises that). The walk
(`decode_attention`'s fori_loop schedule) is the oracle: the kernel exists
to remove its per-iteration overhead, not to change its math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.ops.attention import decode_attention
from deeplearning_mpi_tpu.ops.pallas.flash_decode import (
    decode_block_fits,
    flash_decode,
)


def _bufs(B=2, L=64, H=4, Hkv=None, D=16, idx=37, seed=0):
    """Cache buffers with the real cache's contract: unfilled rows zero."""
    Hkv = Hkv or H
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, 1, H, D)).astype(np.float32))
    mask = (np.arange(L) <= idx)[None, :, None, None]
    k = jnp.asarray((rng.normal(size=(B, L, Hkv, D)) * mask).astype(np.float32))
    v = jnp.asarray((rng.normal(size=(B, L, Hkv, D)) * mask).astype(np.float32))
    return q, k, v


class TestFlashDecodeKernel:
    @pytest.mark.parametrize("idx", [0, 15, 16, 37, 63])
    @pytest.mark.parametrize("hkv", [4, 2, 1], ids=["mha", "gqa2", "mqa"])
    def test_matches_walk_at_every_fill(self, idx, hkv):
        q, k, v = _bufs(Hkv=hkv, idx=idx)
        ref = decode_attention(
            q, k, v, jnp.int32(idx), block=16, dense_max=0, use_kernel=False
        )
        out = flash_decode(q, k, v, jnp.int32(idx), block=16, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_blocks_past_boundary_never_read(self):
        """Poison every block past the boundary block with NaN: the clamped
        index map must revisit the boundary block instead of reading them
        (the O(index)-traffic property, testable in interpret mode as a
        NaN-freedom invariant)."""
        q, k, v = _bufs(B=1, L=64, idx=20)  # boundary block = rows 16..31
        k = np.array(k); v = np.array(v)  # writable copies
        k[:, 32:] = np.nan
        v[:, 32:] = np.nan
        out = flash_decode(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.int32(20), block=16, interpret=True,
        )
        assert np.all(np.isfinite(np.asarray(out)))

    def test_bf16_inputs(self):
        q, k, v = _bufs(idx=37)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        ref = decode_attention(
            qb, kb, vb, jnp.int32(37), block=16, dense_max=0, use_kernel=False
        )
        out = flash_decode(qb, kb, vb, jnp.int32(37), block=16, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=2e-2,
        )


class TestPerRowIndex:
    """[B]-shaped fill levels: the continuous-batching contract — every
    row clamps, gates, and masks against its OWN index."""

    def _ragged(self, idx, Hkv=2, L=64):
        B = len(idx)
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(B, 1, 4, 16)).astype(np.float32))
        mask = (np.arange(L)[None, :] <= np.asarray(idx)[:, None])[
            :, :, None, None
        ]
        k = jnp.asarray(
            (rng.normal(size=(B, L, Hkv, 16)) * mask).astype(np.float32)
        )
        v = jnp.asarray(
            (rng.normal(size=(B, L, Hkv, 16)) * mask).astype(np.float32)
        )
        return q, k, v

    @pytest.mark.parametrize("window", [None, 24])
    def test_kernel_matches_per_row_walk(self, window):
        """The kernel on an index VECTOR must equal running the scalar walk
        row by row — rows at different fills share one fixed-shape call."""
        idx = [0, 15, 37, 63]
        q, k, v = self._ragged(idx)
        ref = jnp.concatenate(
            [
                decode_attention(
                    q[b : b + 1], k[b : b + 1], v[b : b + 1],
                    jnp.int32(i), block=16, dense_max=0, use_kernel=False,
                    window=window,
                )
                for b, i in enumerate(idx)
            ],
            axis=0,
        )
        out = flash_decode(
            q, k, v, jnp.asarray(idx, jnp.int32), block=16, interpret=True,
            window=window,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_batched_dense_matches_per_row_walk(self):
        from deeplearning_mpi_tpu.ops.attention import (
            batched_decode_attention,
        )

        idx = [5, 37, 63]
        q, k, v = self._ragged(idx)
        ref = jnp.concatenate(
            [
                decode_attention(
                    q[b : b + 1], k[b : b + 1], v[b : b + 1],
                    jnp.int32(i), block=16, dense_max=0, use_kernel=False,
                )
                for b, i in enumerate(idx)
            ],
            axis=0,
        )
        out = batched_decode_attention(q, k, v, jnp.asarray(idx, jnp.int32))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
        kern = batched_decode_attention(
            q, k, v, jnp.asarray(idx, jnp.int32), use_kernel=True, block=16
        )
        np.testing.assert_allclose(
            np.asarray(kern), np.asarray(ref), atol=2e-5
        )

    def test_inactive_row_outputs_zero(self):
        """index < 0 marks an empty serving slot: its output must be zeros
        (not a softmax-renormalized average of garbage V rows), and live
        rows must be unaffected by its presence."""
        from deeplearning_mpi_tpu.ops.attention import (
            batched_decode_attention,
        )

        q, k, v = self._ragged([5, 37, 63])
        full = batched_decode_attention(
            q, k, v, jnp.asarray([5, 37, 63], jnp.int32)
        )
        mixed = batched_decode_attention(
            q, k, v, jnp.asarray([5, -1, 63], jnp.int32)
        )
        assert np.all(np.asarray(mixed)[1] == 0.0)
        np.testing.assert_array_equal(np.asarray(mixed)[0], np.asarray(full)[0])
        np.testing.assert_array_equal(np.asarray(mixed)[2], np.asarray(full)[2])

    def test_wrong_index_shape_rejected(self):
        from deeplearning_mpi_tpu.ops.attention import (
            batched_decode_attention,
        )

        q, k, v = self._ragged([5, 37])
        with pytest.raises(ValueError, match="one fill level per row"):
            batched_decode_attention(q, k, v, jnp.zeros((3,), jnp.int32))
        with pytest.raises(ValueError, match="one fill level per row"):
            flash_decode(
                q, k, v, jnp.zeros((3,), jnp.int32), block=16, interpret=True
            )


class TestInt8KV:
    """int8 KV-cache variant: half the cache bytes, VMEM dequantization."""

    @pytest.mark.parametrize("hkv", [4, 2], ids=["mha", "gqa2"])
    @pytest.mark.parametrize("window", [None, 24])
    def test_matches_walk_on_dequantized_buffers(self, hkv, window):
        """The kernel on (int8, scales) must equal the walk on the
        DEQUANTIZED buffers — quantization error is quantize_kv's contract,
        not the kernel's; the kernel itself must be exact."""
        from deeplearning_mpi_tpu.ops.pallas.flash_decode import quantize_kv

        q, k, v = _bufs(Hkv=hkv, idx=50)
        k8, ks = quantize_kv(k)
        v8, vs = quantize_kv(v)
        k_dq = k8.astype(jnp.float32) * ks[..., None]
        v_dq = v8.astype(jnp.float32) * vs[..., None]
        ref = decode_attention(
            q, k_dq, v_dq, jnp.int32(50), block=16, dense_max=0,
            use_kernel=False, window=window,
        )
        out = flash_decode(
            q, k8, v8, jnp.int32(50), block=16, interpret=True,
            window=window, k_scale=ks, v_scale=vs,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_quantization_error_bounded(self):
        from deeplearning_mpi_tpu.ops.pallas.flash_decode import quantize_kv

        _, k, _ = _bufs(idx=63)
        k8, ks = quantize_kv(k)
        k_dq = np.asarray(k8, np.float32) * np.asarray(ks)[..., None]
        err = np.abs(k_dq - np.asarray(k))
        assert np.all(err <= np.asarray(ks)[..., None] / 2 + 1e-6)

    def test_scales_without_int8_rejected(self):
        from deeplearning_mpi_tpu.ops.pallas.flash_decode import quantize_kv

        q, k, v = _bufs(idx=20)
        _, ks = quantize_kv(k)
        with pytest.raises(ValueError, match="int8"):
            flash_decode(
                q, k, v, jnp.int32(20), block=16, interpret=True,
                k_scale=ks, v_scale=ks,
            )


class TestDispatcher:
    def test_use_kernel_true_matches_walk(self):
        q, k, v = _bufs(idx=50)
        walk = decode_attention(
            q, k, v, jnp.int32(50), block=16, dense_max=0, use_kernel=False
        )
        kern = decode_attention(
            q, k, v, jnp.int32(50), block=16, dense_max=0, use_kernel=True
        )
        np.testing.assert_allclose(np.asarray(kern), np.asarray(walk), atol=2e-5)

    def test_non_tileable_length_falls_back_to_walk(self):
        # L=20: every power-of-two-halved block either fails L % b or b % 8
        # — the dispatcher must fall back, not crash.
        assert decode_block_fits(1024, 20) is None
        q, k, v = _bufs(L=20, idx=13)
        out = decode_attention(
            q, k, v, jnp.int32(13), block=16, dense_max=0, use_kernel=True
        )
        ref = decode_attention(
            q, k, v, jnp.int32(13), block=16, dense_max=0, use_kernel=False
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("window", [8, 16, 40, 100])
    def test_windowed_kernel_matches_windowed_walk(self, window):
        # Sliding-window decode through the kernel: the two-sided clamp
        # (pre-window AND post-prefix steps collapse onto boundary blocks)
        # must reproduce the windowed walk at every window size — inside a
        # block, block-aligned, spanning blocks, and >= fill (plain prefix).
        q, k, v = _bufs(idx=50)
        out = decode_attention(
            q, k, v, jnp.int32(50), block=16, dense_max=0, window=window,
            use_kernel=True,
        )
        ref = decode_attention(
            q, k, v, jnp.int32(50), block=16, dense_max=0, window=window,
            use_kernel=False,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_windowed_kernel_skips_prewindow_blocks(self):
        """Poison blocks wholly before the window AND wholly after the
        prefix: the clamped index map must read neither."""
        q, k, v = _bufs(B=1, L=128, idx=79)  # window 16 -> rows 64..79
        k = np.array(k); v = np.array(v)
        k[:, :48] = np.nan; v[:, :48] = np.nan   # pre-window blocks (16-row)
        k[:, 96:] = np.nan; v[:, 96:] = np.nan   # past the boundary block
        out = flash_decode(
            q, jnp.asarray(k), jnp.asarray(v), jnp.int32(79), block=16,
            interpret=True, window=16,
        )
        assert np.all(np.isfinite(np.asarray(out)))

    def test_cpu_auto_keeps_walk(self):
        # use_kernel=None on CPU: the walk (fast XLA) — the interpreter
        # would be a silent order-of-magnitude regression for CPU serving.
        q, k, v = _bufs(idx=50)
        out = decode_attention(q, k, v, jnp.int32(50), block=16, dense_max=0)
        ref = decode_attention(
            q, k, v, jnp.int32(50), block=16, dense_max=0, use_kernel=False
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_decode_block_fits():
    assert decode_block_fits(1024, 2048) == 1024
    assert decode_block_fits(1024, 1536) == 512
    assert decode_block_fits(16, 64) == 16
    assert decode_block_fits(1024, 20) is None
    # 1048 is only tileable by a degenerate 8-row block — a 131-step
    # near-scalar grid must fall back to the walk, not run (review r5).
    assert decode_block_fits(1024, 1048) is None
