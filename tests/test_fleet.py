"""Fleet router + replica fault-kind tests: fake clock, no processes.

The router (``serving/router.py``) is deliberately pure host-side policy —
every decision a function of (telemetry snapshots, ledger, clock) — so
selection scoring, the dead-replica exclusion window, and the full hedge
lifecycle (threshold → fire → first-winner-cancels-loser → duplicate
drop) are all pinned here deterministically. The process-level half of
the fleet (supervision, re-dispatch, rolling swap) lives in
``tools/fleet_drill.py`` / ``tests/test_multiprocess.py``.
"""

import pytest

from deeplearning_mpi_tpu.resilience import faults
from deeplearning_mpi_tpu.resilience.faults import (
    FAULT_UNITS,
    FLEET_KINDS,
    SERVE_KINDS,
    ChaosInjector,
    FaultPlan,
    fleet_entries,
    validate_plan_kinds,
)
from deeplearning_mpi_tpu.serving.router import Router
from deeplearning_mpi_tpu.telemetry import MetricsRegistry


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float = 1.0) -> None:
        self.t += dt


def _router(n=2, **kw):
    clock = FakeClock()
    return Router(range(n), clock=clock, **kw), clock


class TestRouterSelection:
    def test_select_prefers_lowest_reported_load(self):
        router, _ = _router()
        router.observe(0, {"queue_depth": 5, "slots_active": 3})
        router.observe(1, {"queue_depth": 1, "slots_active": 1})
        assert router.select() == 1

    def test_outstanding_ledger_beats_stale_snapshot(self):
        """The snapshot lags by a heartbeat; the router's own dispatch
        ledger does not — a burst must spread instead of piling onto the
        replica whose stale snapshot still says 'idle'."""
        router, _ = _router()
        targets = []
        for rid in range(4):
            t = router.select()
            router.dispatch(rid, t)
            targets.append(t)
        assert targets == [0, 1, 0, 1]

    def test_ties_break_to_lowest_id(self):
        router, _ = _router(n=3)
        assert router.select() == 0

    def test_ttft_in_score(self):
        router, _ = _router()
        router.observe(0, {"ttft_p50": 2.0})
        router.observe(1, {"ttft_p50": 0.1})
        assert router.select() == 1

    def test_select_none_when_fleet_unavailable(self):
        router, clock = _router()
        router.mark_dead(0, clock())
        router.exclude(1)
        assert router.select() is None


class TestRouterExclusion:
    def test_mark_dead_orphans_primaries_and_opens_window(self):
        router, clock = _router(exclusion_s=1.0)
        router.dispatch(0, 0, clock())
        router.dispatch(1, 0, clock())
        router.dispatch(2, 1, clock())
        orphans = router.mark_dead(0, clock())
        assert sorted(orphans) == [0, 1]
        assert router.eligible(clock()) == [1]
        # ready alone is not enough: the exclusion window must also pass
        # (a cold respawn would win every selection on an empty queue).
        router.mark_alive(0, clock())
        assert router.eligible(clock()) == [1]
        clock.advance(1.01)
        assert router.eligible(clock()) == [0, 1]

    def test_window_alone_is_not_enough_either(self):
        router, clock = _router(exclusion_s=0.5)
        router.mark_dead(0, clock())
        clock.advance(5.0)
        assert router.eligible(clock()) == [1]  # never marked alive
        router.mark_alive(0, clock())
        assert router.eligible(clock()) == [0, 1]

    def test_surviving_hedge_is_promoted_to_primary(self):
        """Primary's replica dies while a hedge copy runs elsewhere: the
        request is NOT orphaned — the hedge copy becomes the primary and
        its completion is a plain win (no phantom loser to cancel)."""
        router, clock = _router(hedge_ms=100.0, registry=MetricsRegistry())
        router.dispatch(0, 0, clock())
        clock.advance(0.2)
        assert router.maybe_hedge(clock()) == [(0, 1)]
        assert router.mark_dead(0, clock()) == []
        verdict, loser = router.on_complete(0, 1, clock())
        assert (verdict, loser) == ("win", None)


class TestPrefixAffinity:
    def test_affinity_steers_an_otherwise_tied_selection(self):
        """After replica 1 served a request with this leading-block
        signature, a later same-signature request breaks the idle tie
        toward it (instead of the lowest-id default) — and an unrelated
        signature still falls back to the default."""
        router, clock = _router()
        router.dispatch(0, 1, clock(), prefix_sig=42)
        assert router.on_complete(0, 1, clock())[0] == "win"
        assert router.select(clock()) == 0  # no signature: lowest id
        assert router.select(clock(), prefix_sig=42) == 1
        assert router.select(clock(), prefix_sig=7) == 0  # unknown sig

    def test_affinity_is_weaker_than_real_load(self):
        """The bonus is half a request: a probable cache hit must steer
        ties, not funnel a hot shared prefix's whole traffic onto one
        busy replica."""
        router, clock = _router()
        router.dispatch(0, 1, clock(), prefix_sig=42)  # still outstanding
        assert router.select(clock(), prefix_sig=42) == 0

    def test_mark_dead_clears_affinity(self):
        """The radix cache died with the process — a respawn starts cold,
        so its old signatures must not attract same-prefix traffic."""
        router, clock = _router(exclusion_s=0.5)
        router.dispatch(0, 1, clock(), prefix_sig=42)
        assert router.on_complete(0, 1, clock())[0] == "win"
        router.mark_dead(1, clock())
        router.mark_alive(1, clock())
        clock.advance(1.0)
        assert router.eligible(clock()) == [0, 1]
        assert router.select(clock(), prefix_sig=42) == 0

    def test_signature_history_is_bounded(self):
        router, clock = _router(n=1)
        for i in range(200):
            router.dispatch(i, 0, clock(), prefix_sig=i)
            clock.advance(0.01)
        sigs = router._replicas[0].prefix_sigs
        assert len(sigs) == 128
        assert 199 in sigs and 0 not in sigs  # oldest evicted first


class TestHedging:
    def test_fires_only_past_threshold(self):
        registry = MetricsRegistry()
        router, clock = _router(hedge_ms=50.0, registry=registry)
        router.dispatch(0, 0, clock())
        clock.advance(0.02)
        assert router.maybe_hedge(clock()) == []
        clock.advance(0.04)  # 60ms outstanding
        assert router.maybe_hedge(clock()) == [(0, 1)]
        # already hedged: never a third copy
        clock.advance(1.0)
        assert router.maybe_hedge(clock()) == []
        snap = registry.snapshot()
        assert snap['serve_hedge_total{outcome="fired"}'] == 1

    def test_deadline_budget_gates_hedging(self):
        """Hedging a request the client already gave up on is pure waste:
        past the absolute deadline, no duplicate fires."""
        router, clock = _router(hedge_ms=50.0)
        router.dispatch(0, 0, clock(), deadline=0.04)
        clock.advance(0.06)  # past hedge threshold AND past deadline
        assert router.maybe_hedge(clock()) == []

    def test_no_hedge_without_a_second_eligible_replica(self):
        router, clock = _router(hedge_ms=50.0)
        router.exclude(1)
        router.dispatch(0, 0, clock())
        clock.advance(0.1)
        assert router.maybe_hedge(clock()) == []

    def test_hedging_disabled_at_zero(self):
        router, clock = _router(hedge_ms=0.0)
        router.dispatch(0, 0, clock())
        clock.advance(100.0)
        assert router.maybe_hedge(clock()) == []

    def test_first_winner_cancels_loser_exactly_one_stream(self):
        registry = MetricsRegistry()
        router, clock = _router(hedge_ms=50.0, registry=registry)
        router.dispatch(7, 0, clock())
        clock.advance(0.06)
        assert router.maybe_hedge(clock()) == [(7, 1)]
        # hedge copy lands first: it wins, the primary is the loser...
        verdict, loser = router.on_complete(7, 1, clock(), ttft=0.08)
        assert (verdict, loser) == ("win", 0)
        # ...and the primary's late completion is a dropped duplicate.
        verdict, loser = router.on_complete(7, 0, clock(), ttft=0.09)
        assert (verdict, loser) == ("duplicate", None)
        snap = registry.snapshot()
        assert snap['serve_hedge_total{outcome="fired"}'] == 1
        assert snap['serve_hedge_total{outcome="hedge_win"}'] == 1
        assert snap['serve_hedge_total{outcome="duplicate"}'] == 1
        assert snap["serve_hedge_total"] == 3  # base counter sums outcomes
        # per-replica TTFT aggregation: each completion labeled by server
        assert any(k.startswith('serve_ttft_s{replica="1"}') for k in snap)

    def test_primary_win_cancels_hedge(self):
        registry = MetricsRegistry()
        router, clock = _router(hedge_ms=50.0, registry=registry)
        router.dispatch(3, 0, clock())
        clock.advance(0.06)
        router.maybe_hedge(clock())
        verdict, loser = router.on_complete(3, 0, clock())
        assert (verdict, loser) == ("win", 1)
        snap = registry.snapshot()
        assert snap['serve_hedge_total{outcome="primary_win"}'] == 1

    def test_unknown_rid_is_duplicate(self):
        router, clock = _router(registry=MetricsRegistry())
        assert router.on_complete(99, 0, clock()) == ("duplicate", None)


class TestReplicaFaultKinds:
    def test_fleet_entries_filters_to_fleet_kinds(self):
        spec = "replica_kill@step:4,serve_crash@step:2, replica_hang@step:6"
        assert fleet_entries(spec) == [
            "replica_kill@step:4", "replica_hang@step:6",
        ]
        assert fleet_entries("") == []

    def test_replica_kinds_registered_step_unit(self):
        assert FLEET_KINDS == {"replica_kill", "replica_hang", "replica_slow"}
        for kind in FLEET_KINDS:
            assert FAULT_UNITS[kind] == "step"
        FaultPlan.parse("replica_kill@step:4,replica_slow@step:2")  # parses

    def test_validate_plan_kinds_accepts_supported(self):
        validate_plan_kinds(
            "replica_kill@step:4,replica_hang@step:6", FLEET_KINDS,
            workload="serving fleet",
        )
        validate_plan_kinds("serve_crash@step:2", SERVE_KINDS,
                            workload="single-replica serving")

    def test_validate_plan_kinds_fails_loud_on_hookless_kind(self):
        with pytest.raises(ValueError, match="rank_kill.*no injection hook"):
            validate_plan_kinds("rank_kill@step:1", FLEET_KINDS,
                                workload="serving fleet")
        with pytest.raises(ValueError, match="replica_kill"):
            validate_plan_kinds("replica_kill@step:1", SERVE_KINDS,
                                workload="single-replica serving")

    def test_replica_kill_and_hang_detonate_at_step(self, monkeypatch):
        fired = []
        monkeypatch.setattr(faults, "_exit_rank",
                            lambda step: fired.append(("kill", step)))
        monkeypatch.setattr(faults, "_hang_rank",
                            lambda step: fired.append(("hang", step)))
        inj = ChaosInjector(
            FaultPlan.parse("replica_kill@step:4,replica_hang@step:6")
        )
        inj.check_replica_fault(step=3)
        assert fired == []
        inj.check_replica_fault(step=4)
        assert fired == [("kill", 4)]
        inj.check_replica_fault(step=6)
        assert fired == [("kill", 4), ("hang", 6)]

    def test_replica_slow_fires_once_then_persists(self):
        """The slowdown is a degraded replica, not a one-step blip — it
        persists after its trigger, but the fault is COUNTED exactly once
        so one supervisor-side recovery balances the books."""
        inj = ChaosInjector(FaultPlan.parse("replica_slow@step:2"),
                            stall_s=0.5)
        assert inj.check_replica_fault(step=1) == 0.0
        assert inj.check_replica_fault(step=2) == 0.5
        assert inj.check_replica_fault(step=3) == 0.5  # persists
        assert inj.counts().get("fault_injected_total") == 1
        inj.record_recovery("replica_slow")
        assert inj.balanced()


class TestServeLmChaosValidation:
    """Satellite: ``serve_lm --chaos`` used to silently accept kinds with
    no serving hook — they could never fire, leaving the reconciliation
    invariant unfalsifiable. Now it fails loud at startup."""

    def test_rejects_pod_kind_in_single_replica_mode(self, capsys):
        from deeplearning_mpi_tpu.cli import serve_lm

        rc = serve_lm.main(["--selftest", "--chaos", "rank_kill@step:1"])
        assert rc == 1
        err = capsys.readouterr().err
        assert "rank_kill" in err and "no injection hook" in err

    def test_rejects_fleet_kind_without_replicas(self, capsys):
        from deeplearning_mpi_tpu.cli import serve_lm

        rc = serve_lm.main(["--selftest", "--chaos", "replica_kill@step:1"])
        assert rc == 1
        assert "replica_kill" in capsys.readouterr().err
