"""Native (C++) data-loader core vs the numpy reference transforms."""

import numpy as np
import pytest

from deeplearning_mpi_tpu.data import native
from deeplearning_mpi_tpu.data.cifar10 import eval_transform as np_eval
from deeplearning_mpi_tpu.data.cifar10 import train_transform as np_train

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native library unavailable (no g++?)"
)


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": rng.integers(0, 256, (n, 32, 32, 3)).astype(np.uint8),
        "label": rng.integers(0, 10, n).astype(np.int32),
    }


class TestNativeTransforms:
    def test_train_transform_matches_numpy_bitwise_rng(self):
        """Same seeded rng ⇒ the native and numpy train transforms draw the
        same crops/flips and produce (near-)identical float batches."""
        batch = _batch()
        out_np = np_train(dict(batch), np.random.default_rng(123))
        out_nat = native.train_transform(dict(batch), np.random.default_rng(123))
        np.testing.assert_allclose(
            out_nat["image"], out_np["image"], rtol=0, atol=1e-6
        )
        np.testing.assert_array_equal(out_nat["label"], out_np["label"])

    def test_eval_transform_matches_numpy(self):
        batch = _batch(seed=1)
        out_np = np_eval(dict(batch))
        out_nat = native.eval_transform(dict(batch))
        np.testing.assert_allclose(
            out_nat["image"], out_np["image"], rtol=0, atol=1e-6
        )

    def test_zero_padding_region_is_normalized_zero(self):
        """A crop fully in the pad border must equal normalize(0)."""
        images = np.full((1, 32, 32, 3), 255, np.uint8)
        out = native.crop_flip_normalize(
            images,
            ys=np.array([0]), xs=np.array([0]), flips=np.array([0]),
        )
        # window at (0,0) in padded coords: first 4 rows/cols come from the
        # zero border.
        from deeplearning_mpi_tpu.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD

        expected_border = (-CIFAR10_MEAN / CIFAR10_STD).astype(np.float32)
        np.testing.assert_allclose(out[0, 0, 0], expected_border, atol=1e-6)
        expected_body = ((1.0 - CIFAR10_MEAN) / CIFAR10_STD).astype(np.float32)
        np.testing.assert_allclose(out[0, 10, 10], expected_body, atol=1e-6)

    def test_flip_reverses_width(self):
        rng = np.random.default_rng(2)
        images = rng.integers(0, 256, (2, 32, 32, 3)).astype(np.uint8)
        base = native.crop_flip_normalize(
            images, ys=np.array([4, 4]), xs=np.array([4, 4]),
            flips=np.array([0, 0]),
        )
        flipped = native.crop_flip_normalize(
            images, ys=np.array([4, 4]), xs=np.array([4, 4]),
            flips=np.array([1, 1]),
        )
        np.testing.assert_allclose(flipped, base[:, :, ::-1], atol=1e-6)

    def test_threaded_matches_single_thread(self):
        batch = _batch(n=64, seed=3)["image"]
        a = native.normalize(batch, max_threads=1)
        b = native.normalize(batch, max_threads=8)
        np.testing.assert_array_equal(a, b)
