"""Resilience-layer tests: chaos grammar, integrity, recovery, and the
headline end-to-end claims.

Structured bottom-up, like the subsystem (``docs/RESILIENCE.md``):

- :class:`FaultPlan` / :class:`ChaosInjector` — the deterministic grammar
  and the fire-once / reconciliation accounting contract.
- integrity primitives — atomic JSON, per-array and per-file digests,
  byte corruption.
- :class:`Checkpointer` hardening — manifest verification, rollback past
  corrupted steps, retention of manifests.
- supervisor pieces — :class:`Heartbeat`, :func:`preflight`,
  :func:`run_with_auto_resume`, :class:`GracefulShutdown`/:class:`Preempted`.
- :class:`ResilientLoader` — stall watchdog and poison-batch quarantine.
- the two headline e2e claims: a kill+corrupt chaos TRAINING run recovers
  onto the exact unfaulted trajectory (bit-identical final params), and a
  crash-recovered SERVING run stays bit-identical to offline greedy decode
  — with ``fault_injected_total == recovery_total + rollback_total``
  reconciling in both.
"""

import json
import os
import signal
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.data import ShardedLoader, SyntheticTokens
from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.resilience import (
    ChaosInjector,
    CheckpointCorruption,
    FaultPlan,
    GracefulShutdown,
    Heartbeat,
    InjectedFault,
    InjectedKill,
    Preempted,
    ResilientLoader,
    TrainingFailure,
    atomic_write_json,
    corrupt_checkpoint,
    preflight,
    run_with_auto_resume,
    tree_digests,
)
from deeplearning_mpi_tpu.resilience.faults import (
    FAULT_INJECTED,
    RECOVERY,
    ROLLBACK,
)
from deeplearning_mpi_tpu.resilience.integrity import (
    manifest_path,
    read_manifest,
)
from deeplearning_mpi_tpu.telemetry import MetricsRegistry, labeled
from deeplearning_mpi_tpu.train import Checkpointer, Trainer, create_train_state
from deeplearning_mpi_tpu.train.trainer import build_optimizer


# -- shared tiny-LM plumbing --------------------------------------------------

def _lm_factory(mesh=None, seed=0):
    model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
    tx = build_optimizer("sgd", 1e-2, momentum=0.0)

    def factory():
        return create_train_state(
            model, jax.random.key(seed), jnp.zeros((1, 16), jnp.int32), tx,
            mesh=mesh,
        )

    return factory


# -- FaultPlan grammar --------------------------------------------------------

class TestFaultPlan:
    def test_parse_full_spec(self):
        plan = FaultPlan.parse(
            "nan_grad@step:7, loader_stall@batch:3,kill@step:12,"
            "corrupt_ckpt@epoch:1"
        )
        assert len(plan) == 4
        assert [(s.kind, s.unit, s.at) for s in plan.specs] == [
            ("nan_grad", "step", 7),
            ("loader_stall", "batch", 3),
            ("kill", "step", 12),
            ("corrupt_ckpt", "epoch", 1),
        ]
        assert not any(s.fired or s.recovered for s in plan.specs)

    def test_bad_entry_rejected(self):
        with pytest.raises(ValueError, match="bad chaos entry"):
            FaultPlan.parse("kill@step")

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultPlan.parse("meteor@step:1")

    def test_wrong_unit_rejected(self):
        # The unit is part of the grammar, not decoration — kill counts in
        # steps, and a silent unit mismatch would make the fault never fire.
        with pytest.raises(ValueError, match="triggers on 'step'"):
            FaultPlan.parse("kill@epoch:1")

    def test_empty_spec_rejected(self):
        with pytest.raises(ValueError, match="empty chaos spec"):
            FaultPlan.parse(" , ")


class TestChaosInjector:
    def test_fires_exactly_once_at_planned_trigger(self):
        chaos = ChaosInjector(FaultPlan.parse("kill@step:5"))
        assert not chaos.should_fire("kill", 4)
        assert chaos.should_fire("kill", 5)
        assert not chaos.should_fire("kill", 5)  # once means once
        assert chaos.counts()[FAULT_INJECTED] == 1

    def test_check_kill_raises_injected_kill(self):
        chaos = ChaosInjector(FaultPlan.parse("kill@step:2"))
        chaos.check_kill(step=1)
        with pytest.raises(InjectedKill):
            chaos.check_kill(step=2)
        chaos.check_kill(step=2)  # fired: the restarted run passes through

    def test_persistent_kind_refires_until_recovered(self):
        # A poison batch is poison on every retry, but it is ONE fault.
        chaos = ChaosInjector(FaultPlan.parse("loader_die@batch:3"))
        for _ in range(3):
            with pytest.raises(InjectedFault):
                chaos.loader_fault(batch=3)
        assert chaos.counts()[FAULT_INJECTED] == 1
        assert chaos.record_recovery("loader_die", at=3)
        chaos.loader_fault(batch=3)  # recovered: no longer raises

    def test_recovery_is_idempotent_and_needs_a_fired_fault(self):
        chaos = ChaosInjector(FaultPlan.parse("kill@step:5"))
        assert not chaos.record_recovery("kill")  # nothing fired yet
        chaos.should_fire("kill", 5)
        assert chaos.record_recovery("kill")
        assert not chaos.record_recovery("kill")  # already recovered
        assert chaos.balanced()
        assert not chaos.unrecovered()

    def test_rollback_counts_against_the_same_invariant(self):
        chaos = ChaosInjector(FaultPlan.parse("corrupt_ckpt@epoch:1,kill@step:2"))
        assert chaos.should_corrupt(epoch=1)
        chaos.should_fire("kill", 2)
        assert not chaos.balanced()  # 2 injected, 0 handled
        assert chaos.record_rollback("corrupt_ckpt", at=1)
        assert chaos.record_recovery("kill")
        assert chaos.balanced()
        c = chaos.counts()
        assert (c[FAULT_INJECTED], c[RECOVERY], c[ROLLBACK]) == (2, 1, 1)
        assert c[labeled(ROLLBACK, kind="corrupt_ckpt")] == 1

    def test_maybe_poison_lm_nans_the_mask_only(self):
        chaos = ChaosInjector(FaultPlan.parse("nan_grad@step:1"))
        batch = {"tokens": jnp.ones((2, 4), jnp.int32)}
        assert chaos.maybe_poison(batch, "lm", step=0) is batch  # no copy off-plan
        poisoned = chaos.maybe_poison(batch, "lm", step=1)
        assert np.isnan(np.asarray(poisoned["mask"])).all()
        np.testing.assert_array_equal(
            np.asarray(poisoned["tokens"]), np.asarray(batch["tokens"])
        )

    def test_reconcile_nan_recoveries_is_bounded_by_skip_count(self):
        chaos = ChaosInjector(FaultPlan.parse("nan_grad@step:1,nan_grad@step:2"))
        chaos.should_fire("nan_grad", 1)
        chaos.should_fire("nan_grad", 2)
        assert chaos.reconcile_nan_recoveries(0) == 0  # guard skipped nothing
        assert chaos.reconcile_nan_recoveries(1) == 1  # one confirmed skip
        assert chaos.reconcile_nan_recoveries(5) == 1  # only one pending
        assert chaos.balanced()

    def test_bind_registry_backfills_pre_bind_counts(self):
        chaos = ChaosInjector(FaultPlan.parse("kill@step:5"), stall_s=0.0)
        chaos.should_fire("kill", 5)
        chaos.record_recovery("kill", latency_s=0.25)
        registry = MetricsRegistry()
        chaos.bind_registry(registry)
        snap = registry.snapshot()
        assert snap[FAULT_INJECTED] == 1
        assert snap[RECOVERY] == 1
        assert snap[ROLLBACK] == 0  # pre-created: explicit zero, not absent
        assert snap[labeled(FAULT_INJECTED, kind="kill")] == 1
        assert any(k.startswith("recovery_latency_s") for k in snap)

    def test_from_spec_none_without_plan(self, monkeypatch):
        monkeypatch.delenv("DMT_CHAOS", raising=False)
        assert ChaosInjector.from_spec(None) is None
        assert ChaosInjector.from_spec("  ") is None

    def test_from_spec_env_fallback(self, monkeypatch):
        monkeypatch.setenv("DMT_CHAOS", "kill@step:9")
        chaos = ChaosInjector.from_spec(None)
        assert chaos is not None
        assert chaos.plan.specs[0].at == 9
        monkeypatch.setenv("DMT_CHAOS_STALL_S", "0.125")
        assert ChaosInjector.from_spec("loader_stall@batch:1").stall_s == 0.125


# -- integrity primitives -----------------------------------------------------

class TestIntegrityPrimitives:
    def test_atomic_write_json_round_trips_and_leaves_no_temp(self, tmp_path):
        path = tmp_path / "m.json"
        atomic_write_json(path, {"a": 1})
        atomic_write_json(path, {"a": 2})  # overwrite is also atomic
        assert json.loads(path.read_text()) == {"a": 2}
        assert list(tmp_path.iterdir()) == [path]

    def test_tree_digests_deterministic_and_value_sensitive(self):
        tree = {"w": jnp.arange(8, dtype=jnp.float32), "b": {"c": jnp.ones(3)}}
        d1 = tree_digests(tree)
        d2 = tree_digests(jax.tree.map(lambda x: x, tree))
        assert d1 == d2
        assert set(d1) == {"['w']", "['b']['c']"}  # keyed by tree path
        mutated = {"w": tree["w"].at[0].set(7.0), "b": tree["b"]}
        d3 = tree_digests(mutated)
        assert d3["['w']"] != d1["['w']"]
        assert d3["['b']['c']"] == d1["['b']['c']"]

    def test_tree_digests_cover_dtype_and_shape(self):
        # Same bytes, different view: a silent dtype/shape drift must not
        # hash equal (f32 ones and a reshaped copy share a byte pattern).
        a = {"x": jnp.ones(4, jnp.float32)}
        b = {"x": jnp.ones((2, 2), jnp.float32)}
        assert tree_digests(a)["['x']"] != tree_digests(b)["['x']"]

    def test_corrupt_checkpoint_flips_bytes_in_largest_file(self, tmp_path):
        small = tmp_path / "meta.json"
        small.write_bytes(b"{}")
        big = tmp_path / "arrays.bin"
        big.write_bytes(bytes(4096))
        victim = corrupt_checkpoint(tmp_path, span=64)
        assert victim == big
        assert small.read_bytes() == b"{}"
        data = big.read_bytes()
        assert any(x != 0 for x in data)  # bytes really flipped
        assert len(data) == 4096  # size preserved: damage, not truncation


class TestCheckpointIntegrity:
    def test_restore_verified_rolls_back_past_corruption(self, tmp_path):
        factory = _lm_factory()
        ck = Checkpointer(tmp_path / "ck", max_to_keep=4)
        s0 = factory()
        ck.save(s0, epoch=0)
        ck.save(s0.replace(step=s0.step + 1), epoch=1)
        ck.manager.wait_until_finished()
        corrupt_checkpoint(ck.directory / "1")
        state, epoch = ck.restore_verified(factory())
        assert epoch == 0
        assert int(state.step) == 0
        assert tree_digests({"p": state.params}) == tree_digests({"p": s0.params})
        ck.close()

    def test_all_corrupt_history_raises(self, tmp_path):
        factory = _lm_factory()
        ck = Checkpointer(tmp_path / "ck", max_to_keep=4)
        ck.save(factory(), epoch=0)
        ck.manager.wait_until_finished()
        corrupt_checkpoint(ck.directory / "0")
        with pytest.raises(CheckpointCorruption, match="tried epochs"):
            ck.restore_verified(factory())
        ck.close()

    def test_step_without_manifest_restores_unverified(self, tmp_path):
        # Pre-integrity history must keep restoring (legacy tolerance).
        factory = _lm_factory()
        ck = Checkpointer(tmp_path / "ck")
        ck.save(factory(), epoch=0)
        ck.manager.wait_until_finished()
        assert manifest_path(ck.directory, 0).exists()
        manifest_path(ck.directory, 0).unlink()
        assert read_manifest(ck.directory, 0) is None
        _, epoch = ck.restore_verified(factory())
        assert epoch == 0
        ck.close()

    def test_chaos_corruption_is_injected_and_rolled_back(self, tmp_path):
        factory = _lm_factory()
        chaos = ChaosInjector(FaultPlan.parse("corrupt_ckpt@epoch:1"))
        ck = Checkpointer(tmp_path / "ck", max_to_keep=4, chaos=chaos)
        s0 = factory()
        ck.save(s0, epoch=0)
        ck.save(s0.replace(step=s0.step + 1), epoch=1)  # corrupted on commit
        _, epoch = ck.restore_verified(factory())
        assert epoch == 0
        assert chaos.balanced()
        assert chaos.counts()[ROLLBACK] == 1
        ck.close()

    def test_manifest_retention_follows_max_to_keep(self, tmp_path):
        factory = _lm_factory()
        ck = Checkpointer(tmp_path / "ck", max_to_keep=2)
        state = factory()
        for epoch in range(4):
            ck.save(state, epoch=epoch)
        ck.manager.wait_until_finished()
        ck._prune_manifests()
        kept = sorted(
            int(p.stem.split("-", 1)[1])
            for p in ck.directory.glob("manifest-*.json")
        )
        assert kept == sorted(ck.manager.all_steps())
        assert len(kept) <= 2
        ck.close()


# -- supervisor: heartbeat, preflight, auto-resume, preemption ----------------

class TestHeartbeat:
    def test_beats_carry_progress_and_stop_stops(self, tmp_path):
        path = tmp_path / "hb" / "heartbeat.json"
        hb = Heartbeat(path, interval_s=0.02)
        hb.progress = {"epoch": 3, "step_in_epoch": 7}
        with hb:
            deadline = time.monotonic() + 5.0
            while not path.exists() and time.monotonic() < deadline:
                time.sleep(0.01)
            payload = json.loads(path.read_text())
        assert payload["epoch"] == 3
        assert payload["step_in_epoch"] == 7
        assert payload["pid"] == os.getpid()
        assert hb._thread is None  # stopped by __exit__
        mtime = path.stat().st_mtime_ns
        time.sleep(0.08)
        assert path.stat().st_mtime_ns == mtime  # no beats after stop

    def test_stop_without_start_is_a_noop(self, tmp_path):
        Heartbeat(tmp_path / "hb.json").stop()


class TestPreflight:
    def test_clean_config_passes(self, tmp_path, mesh):
        preflight(
            data_dir=str(tmp_path),
            model_dir=str(tmp_path / "models"),
            log_dir=str(tmp_path / "logs"),
            global_batch_size=16, mesh=mesh, grad_accum=2,
        )
        assert (tmp_path / "models").is_dir()  # created, not just checked

    def test_missing_data_dir_fails_specifically(self, tmp_path):
        with pytest.raises(SystemExit, match="does not exist"):
            preflight(data_dir=str(tmp_path / "nope"))

    def test_indivisible_batch_fails_before_compile(self, mesh):
        with pytest.raises(SystemExit, match="not divisible"):
            preflight(global_batch_size=7, mesh=mesh)

    def test_grad_accum_divisibility_checked(self, mesh):
        with pytest.raises(SystemExit, match="grad_accum"):
            preflight(global_batch_size=16, mesh=mesh, grad_accum=3)


class _FakeCkpt:
    def __init__(self, latest=None):
        self.latest = latest

    def latest_epoch(self):
        return self.latest


class TestAutoResume:
    def test_resumes_from_epoch_after_latest_checkpoint(self):
        ckpt = _FakeCkpt()
        calls = []

        def fit(start_epoch):
            calls.append(start_epoch)
            if len(calls) == 1:
                ckpt.latest = 3  # "a checkpoint landed before the crash"
                raise RuntimeError("simulated crash")
            return "done"

        out = run_with_auto_resume(fit, ckpt, max_restarts=2, restart_delay_s=0.0)
        assert out == "done"
        assert calls == [0, 4]

    def test_restart_budget_exhaustion_raises_training_failure(self):
        def fit(start_epoch):
            raise RuntimeError("always down")

        with pytest.raises(TrainingFailure, match="after 2 restarts"):
            run_with_auto_resume(
                fit, _FakeCkpt(), max_restarts=2, restart_delay_s=0.0
            )

    def test_preemption_never_burns_a_restart(self):
        calls = []

        def fit(start_epoch):
            calls.append(start_epoch)
            raise Preempted(1)

        with pytest.raises(Preempted):
            run_with_auto_resume(
                fit, _FakeCkpt(), max_restarts=5, restart_delay_s=0.0
            )
        assert calls == [0]  # exactly one attempt


class TestGracefulShutdown:
    def test_manual_request_latches(self):
        gs = GracefulShutdown()
        assert not gs.requested()
        gs.request()
        assert gs.requested()

    def test_sigterm_sets_the_flag_and_uninstall_restores(self):
        gs = GracefulShutdown().install()
        if not gs.installed:
            pytest.skip("not on the main thread; install degraded")
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 2.0
            while not gs.requested() and time.monotonic() < deadline:
                time.sleep(0.005)
            assert gs.requested()
        finally:
            gs.uninstall()
        assert signal.getsignal(signal.SIGTERM) is not gs._handler

    def test_preempted_fit_checkpoints_and_raises(self, tmp_path, mesh):
        factory = _lm_factory(mesh)
        ck = Checkpointer(tmp_path / "ck")
        loader = ShardedLoader(SyntheticTokens(16, 16), 8, mesh, shuffle=False)
        shutdown = GracefulShutdown()  # manual: no signal in-process needed
        trainer = Trainer(
            factory(), "lm", mesh, checkpointer=ck, eval_every=1,
            time_steps=False, shutdown=shutdown,
        )
        shutdown.request()
        with pytest.raises(Preempted) as exc:
            trainer.fit(loader, num_epochs=3)
        assert exc.value.epoch == 0
        assert ck.latest_epoch() == 0  # the graceful final checkpoint
        ck.close()


# -- loader watchdog ----------------------------------------------------------

class TestResilientLoader:
    def _loader(self, mesh, n=32, batch=8):
        return ShardedLoader(
            SyntheticTokens(n, 16), batch, mesh, shuffle=True, seed=0
        )

    def test_transparent_without_faults(self, mesh):
        clean = list(self._loader(mesh).epoch(0))
        wrapped = ResilientLoader(self._loader(mesh))
        assert wrapped.steps_per_epoch() == 4  # __getattr__ delegation
        got = list(wrapped.epoch(0))
        assert len(got) == len(clean)
        for a, b in zip(got, clean):
            np.testing.assert_array_equal(
                np.asarray(a["tokens"]), np.asarray(b["tokens"])
            )

    def test_stall_times_out_retries_and_delivers_same_batch(self, mesh):
        chaos = ChaosInjector(
            FaultPlan.parse("loader_stall@batch:1"), stall_s=1.0
        )
        wrapped = ResilientLoader(
            self._loader(mesh), chaos=chaos,
            batch_timeout_s=0.1, max_retries=2, backoff_s=0.01,
        )
        clean = list(self._loader(mesh).epoch(0))
        got = list(wrapped.epoch(0))
        assert wrapped.stalls >= 1  # the watchdog actually tripped
        assert wrapped.retries >= 1
        assert not wrapped.quarantined
        assert len(got) == len(clean)  # nothing dropped
        for a, b in zip(got, clean):  # retried batch is bit-identical
            np.testing.assert_array_equal(
                np.asarray(a["tokens"]), np.asarray(b["tokens"])
            )
        assert chaos.balanced()
        assert chaos.counts()[labeled(RECOVERY, kind="loader_stall")] == 1

    def test_poison_batch_quarantined_not_fatal(self, mesh):
        chaos = ChaosInjector(FaultPlan.parse("loader_die@batch:2"))
        wrapped = ResilientLoader(
            self._loader(mesh), chaos=chaos,
            batch_timeout_s=5.0, max_retries=1, backoff_s=0.0,
        )
        got = list(wrapped.epoch(0))
        assert wrapped.quarantined == [2]
        assert len(got) == 3  # 4 batches, one skipped
        assert chaos.balanced()
        assert chaos.counts()[labeled(RECOVERY, kind="loader_die")] == 1


# -- scheduler shed accounting (labeled counter) ------------------------------

class TestShedCounter:
    def test_every_shed_reason_is_counted_and_labeled(self):
        from deeplearning_mpi_tpu.serving import PagedKVPool, Request, Scheduler

        def req(rid, prompt_len, max_new=2, arrival=0.0, deadline=None):
            return Request(
                rid=rid, prompt=np.arange(1, prompt_len + 1, dtype=np.int32),
                max_new_tokens=max_new, arrival=arrival, deadline=deadline,
            )

        registry = MetricsRegistry()
        pool = PagedKVPool(8, 4)
        sched = Scheduler(
            pool, max_slots=1, max_seq_len=8, max_queue=2, registry=registry,
        )
        assert registry.snapshot()["serve_shed_total"] == 0  # explicit zero

        assert not sched.submit(req(1, 20))               # too_long
        assert sched.submit(req(2, 2, deadline=1.0))
        assert sched.submit(req(3, 2))
        assert not sched.submit(req(4, 2))                # queue_full
        assert sched.shed_expired(now=5.0)                # deadline (rid 2)
        admitted = sched.admit(now=5.0)
        assert [r.rid for r in admitted] == [3]
        sched.evict(admitted[0])                          # evicted

        snap = registry.snapshot()
        assert snap["serve_shed_total"] == 4
        for reason in ("too_long", "queue_full", "deadline", "evicted"):
            assert snap[labeled("serve_shed_total", reason=reason)] == 1
        pool.check()


# -- headline e2e: chaos training run recovers onto the clean trajectory -----

class TestTrainChaosE2E:
    EPOCHS = 3
    BATCH = 8
    SEQS = 48  # 6 steps per epoch -> 18 total

    def _run(self, mesh, tmp_path, chaos_spec=None):
        from deeplearning_mpi_tpu.utils import config

        factory = _lm_factory(mesh)
        loader = ShardedLoader(
            SyntheticTokens(self.SEQS, 32), self.BATCH, mesh,
            shuffle=True, seed=0,
        )
        chaos = (
            ChaosInjector(FaultPlan.parse(chaos_spec), stall_s=0.05)
            if chaos_spec else None
        )
        ck = Checkpointer(tmp_path / "ck", max_to_keep=5, chaos=chaos)
        trainer = Trainer(
            factory(), "lm", mesh, checkpointer=ck, eval_every=1,
            time_steps=False, chaos=chaos,
        )
        trainer.place_state()
        if chaos is not None:
            chaos.bind_registry(trainer.metrics)
            loader = ResilientLoader(
                loader, chaos=chaos, batch_timeout_s=10.0, backoff_s=0.01
            )
        args = SimpleNamespace(
            num_epochs=self.EPOCHS, max_restarts=2, eval_only=False,
            resume=False, restart_delay_s=0.01,
        )
        try:
            history = config.execute_training(
                trainer, ck, args, loader, None, 0, state_factory=factory
            )
        finally:
            ck.close()
        return trainer, chaos, history

    @pytest.fixture(scope="class")
    def chaos_and_clean(self, tmp_path_factory):
        from deeplearning_mpi_tpu.runtime.mesh import create_mesh

        mesh = create_mesh()
        tmp = tmp_path_factory.mktemp("chaos_e2e")
        # kill fires 1 step into epoch 2; the newest checkpoint (epoch 1)
        # was corrupted at commit, so recovery must roll back to epoch 0
        # and RE-TRAIN epochs 1-2, not resume at 2 over a hole.
        faulted = self._run(
            mesh, tmp / "faulted",
            "kill@step:13,corrupt_ckpt@epoch:1,loader_stall@batch:1",
        )
        clean = self._run(mesh, tmp / "clean")
        return faulted, clean

    def test_run_completes_all_planned_steps(self, chaos_and_clean):
        (trainer, _, history), _ = chaos_and_clean
        assert int(trainer.state.step) == self.EPOCHS * (self.SEQS // self.BATCH)
        # Cumulative history: epochs 0,1 pre-kill + retrained 1,2.
        assert [h["epoch"] for h in history] == [0, 1, 1, 2]

    def test_recovered_trajectory_matches_unfaulted_run(self, chaos_and_clean):
        (ft, _, fh), (ct, _, ch) = chaos_and_clean
        # Bit-identical final params: the restore was exact and the replayed
        # epochs saw identical batches (seeded per (seed, epoch) order).
        assert tree_digests({"p": ft.state.params}) == tree_digests(
            {"p": ct.state.params}
        )
        clean_loss = {h["epoch"]: h["loss"] for h in ch}
        for h in fh:
            assert h["loss"] == clean_loss[h["epoch"]], (
                f"epoch {h['epoch']} diverged after recovery"
            )

    def test_fault_accounting_reconciles(self, chaos_and_clean):
        (trainer, chaos, _), _ = chaos_and_clean
        assert chaos.balanced(), chaos.summary()
        assert not chaos.unrecovered()
        snap = trainer.metrics.snapshot()
        assert snap[FAULT_INJECTED] == 3
        assert snap[RECOVERY] == 2          # kill + loader_stall
        assert snap[ROLLBACK] == 1          # corrupt_ckpt
        assert snap[FAULT_INJECTED] == snap[RECOVERY] + snap[ROLLBACK]
        assert snap[labeled(FAULT_INJECTED, kind="kill")] == 1
        assert any(k.startswith("recovery_latency_s") for k in snap)


# -- headline e2e: serving crash recovery stays bit-identical -----------------

class TestServeChaos:
    def test_crash_recovery_keeps_greedy_parity(self):
        from deeplearning_mpi_tpu.models.generate import generate
        from deeplearning_mpi_tpu.serving import (
            EngineConfig,
            RequestState,
            ServingEngine,
        )

        cfg = TransformerConfig.tiny()
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        registry = MetricsRegistry()
        chaos = ChaosInjector(
            FaultPlan.parse("serve_crash@step:3"), registry=registry
        )
        engine = ServingEngine(
            cfg, params,
            EngineConfig(max_slots=3, block_size=4, num_blocks=32,
                         max_blocks_per_seq=8, prefill_chunk=4),
            dtype=jnp.float32, registry=registry, chaos=chaos,
        )
        rng = np.random.default_rng(7)
        prompts = [
            rng.integers(1, 255, size=n).astype(np.int32)
            for n in (5, 9, 3, 12)
        ]
        max_new = 5
        reqs = [engine.submit(p, max_new) for p in prompts]

        engine.run_until_idle()  # recovers the injected crash in place

        snap = registry.snapshot()
        assert snap["serve_requeued_total"] >= 1  # crash hit live sequences
        for req, prompt in zip(reqs, prompts):
            assert req.state is RequestState.FINISHED
            out = generate(
                model, params, jnp.asarray(prompt)[None],
                max_new_tokens=max_new, rng=jax.random.key(0),
                temperature=0.0,
            )
            expect = np.asarray(out)[0, len(prompt):].tolist()
            assert req.generated == expect, f"rid {req.rid} diverged"
        engine.pool.check()
        assert chaos.balanced()
        assert snap[FAULT_INJECTED] == 1
        assert snap[RECOVERY] == 1
        assert snap[labeled(RECOVERY, kind="serve_crash")] == 1


# -- elastic restore: re-shard a checkpoint onto a smaller world --------------

class TestElasticRestore:
    """A dp=4/ZeRO-1 checkpoint must restore onto dp=2 and dp=1 meshes with
    every leaf re-sharded to the NEW mesh's placement and values bit-equal
    to a single-device restore — the pod supervisor's re-form path
    (``Checkpointer.restore_elastic``, docs/RESILIENCE.md "Elastic pods").

    d_model=64 x d_ff=256 makes the MLP kernels exactly 16384 elements —
    the ZeRO MIN_SIZE floor — so the optimizer moments really shard over
    "data" at dp=4 (a replicated-everything state would test nothing).
    """

    @staticmethod
    def _axes(spec):
        names = set()
        for entry in spec or ():
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                names.update(entry)
            else:
                names.add(entry)
        return names

    def _factory(self, mesh, zero):
        cfg = TransformerConfig(
            vocab_size=128, num_layers=1, num_heads=4, head_dim=16,
            d_model=64, d_ff=256,
        )
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        tx = build_optimizer("adam", 1e-2, clip_norm=1.0)
        return create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx,
            mesh=mesh, zero=zero,
        )

    @pytest.fixture(scope="class")
    def saved_dp4(self, tmp_path_factory):
        """Train 2 real ZeRO steps on a dp=4 mesh and checkpoint them."""
        from deeplearning_mpi_tpu.runtime.mesh import (
            MeshSpec, batch_sharding, create_mesh,
        )
        from deeplearning_mpi_tpu.train import make_train_step

        mesh4 = create_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
        state = self._factory(mesh4, zero=True)
        mu_ff = state.opt_state[1][0].mu["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert "data" in self._axes(mu_ff.sharding.spec), (
            "ZeRO must actually shard the moments at dp=4 for this test "
            "to mean anything"
        )
        step = make_train_step("lm", donate=False)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh4, ndim=2))}
        for _ in range(2):
            state, _ = step(state, batch)
        ck_dir = tmp_path_factory.mktemp("elastic") / "ck"
        ck = Checkpointer(ck_dir, max_to_keep=2)
        ck.save(state, epoch=0)
        ck.close()
        yield ck_dir

    @pytest.mark.parametrize("dp", [2, 1])
    def test_restores_onto_smaller_world_tree_equal_to_oracle(
        self, saved_dp4, dp
    ):
        from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

        registry = MetricsRegistry()
        mesh_small = create_mesh(
            MeshSpec(data=dp), devices=jax.devices()[:dp]
        )
        ck = Checkpointer(saved_dp4, max_to_keep=2)
        restored, epoch = ck.restore_elastic(
            self._factory(mesh_small, zero=True), registry=registry
        )
        assert epoch == 0
        assert int(restored.step) == 2
        assert registry.snapshot()["elastic_restore_total"] == 1
        if dp > 1:
            # The re-sharded leaves live on the NEW data axis...
            mu_ff = restored.opt_state[1][0].mu["layer_0"]["mlp"][
                "gate_proj"]["kernel"]
            assert "data" in self._axes(mu_ff.sharding.spec)

        # ...and every value is bit-equal to the single-device oracle.
        mesh1 = create_mesh(MeshSpec(data=1), devices=jax.devices()[:1])
        oracle, _ = ck.restore_verified(self._factory(mesh1, zero=False))
        ck.close()
        got = jax.tree.leaves(
            {"p": restored.params, "o": restored.opt_state}
        )
        want = jax.tree.leaves({"p": oracle.params, "o": oracle.opt_state})
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert np.array_equal(np.asarray(g), np.asarray(w))

    def test_mismatched_placement_fails_loud(self, saved_dp4, monkeypatch):
        """A leaf left on the wrong sharding must raise, not limp along."""
        from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

        mesh1 = create_mesh(MeshSpec(data=1), devices=jax.devices()[:1])
        ck = Checkpointer(saved_dp4, max_to_keep=2)
        template = self._factory(mesh1, zero=True)
        real_restore = Checkpointer.restore_verified

        def sabotage(self_, tmpl):
            # Hand back arrays still on a dp=4 layout: what a broken orbax
            # target would produce. restore_elastic must refuse it.
            mesh4 = create_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
            wrong = self._factory(mesh4, zero=True)
            return wrong, 0

        monkeypatch.setattr(Checkpointer, "restore_verified", sabotage)
        with pytest.raises(RuntimeError, match="elastic restore"):
            ck.restore_elastic(template)
        monkeypatch.setattr(Checkpointer, "restore_verified", real_restore)
        ck.close()
