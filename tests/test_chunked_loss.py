"""Chunked head+loss cross-entropy vs the dense-logits oracle.

The chunked path must be numerically identical to computing the full
``[B, S, V]`` logits and calling ``lm_cross_entropy`` — values AND
gradients — for every chunking (dividing, non-dividing, chunk > sequence)
and with token masks. The memory claim (no full-logits tensor in either
pass) is structural: logits only exist inside the per-chunk
``jax.checkpoint``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.ops import chunked_lm_loss, lm_cross_entropy


def _case(B=2, S=17, D=8, V=31, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(B, S, D)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(D, V)) * 0.3, jnp.float32)
    tokens = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    return x, w, tokens


@pytest.mark.parametrize("chunk", [4, 16, 5, 100], ids=["divides", "exact", "ragged", "oversize"])
def test_matches_dense_loss(chunk):
    x, w, tokens = _case()
    dense = lm_cross_entropy(x @ w, tokens)
    chunked = chunked_lm_loss(x, w, tokens, chunk_size=chunk)
    np.testing.assert_allclose(float(chunked), float(dense), rtol=1e-6)


def test_matches_dense_loss_with_mask():
    x, w, tokens = _case(seed=1)
    mask = jnp.asarray(
        np.random.default_rng(2).integers(0, 2, tokens.shape), jnp.float32
    )
    dense = lm_cross_entropy(x @ w, tokens, mask)
    chunked = chunked_lm_loss(x, w, tokens, chunk_size=5, mask=mask)
    np.testing.assert_allclose(float(chunked), float(dense), rtol=1e-6)


def test_grads_match_dense_loss():
    x, w, tokens = _case(seed=3)

    gx_d, gw_d = jax.grad(
        lambda x, w: lm_cross_entropy(x @ w, tokens), argnums=(0, 1)
    )(x, w)
    gx_c, gw_c = jax.grad(
        lambda x, w: chunked_lm_loss(x, w, tokens, chunk_size=5), argnums=(0, 1)
    )(x, w)
    np.testing.assert_allclose(np.asarray(gx_c), np.asarray(gx_d), atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw_c), np.asarray(gw_d), atol=1e-6)


def test_model_prehead_path_matches_plain_model():
    """TransformerLM(return_prehead=True) + chunked loss == the plain model's
    logits through lm_cross_entropy — same params (the tree is unchanged),
    same loss, same parameter gradients."""
    cfg = TransformerConfig.tiny()
    plain = TransformerLM(config=cfg, dtype=jnp.float32)
    prehead = TransformerLM(config=cfg, dtype=jnp.float32, return_prehead=True)
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
    )
    variables = plain.init(jax.random.key(0), tokens)
    assert (
        jax.tree.structure(variables)
        == jax.tree.structure(prehead.init(jax.random.key(0), tokens))
    )

    def loss_plain(params):
        return lm_cross_entropy(plain.apply({"params": params}, tokens), tokens)

    def loss_chunked(params):
        x, kernel = prehead.apply({"params": params}, tokens)
        return chunked_lm_loss(x, kernel, tokens, chunk_size=4)

    l_p, g_p = jax.value_and_grad(loss_plain)(variables["params"])
    l_c, g_c = jax.value_and_grad(loss_chunked)(variables["params"])
    np.testing.assert_allclose(float(l_c), float(l_p), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_c), jax.tree.leaves(g_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_untied_embeddings_rejected():
    import dataclasses

    cfg = dataclasses.replace(TransformerConfig.tiny(), tied_embeddings=False)
    model = TransformerLM(config=cfg, dtype=jnp.float32, return_prehead=True)
    with pytest.raises(ValueError, match="tied_embeddings"):
        model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))


@pytest.mark.slow
def test_train_step_with_loss_chunk_matches_standard():
    """One SGD step through make_train_step(loss_chunk=...) equals the
    standard step bit-for-near-bit (update linear in grads)."""
    from deeplearning_mpi_tpu.train import create_train_state, make_train_step
    from deeplearning_mpi_tpu.train.trainer import build_optimizer

    cfg = TransformerConfig.tiny()
    tx = build_optimizer("sgd", 1e-2, momentum=0.0)
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, cfg.vocab_size, (4, 16)), jnp.int32
    )
    batch = {"tokens": tokens}

    def run(model, **step_kw):
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
        )
        step = make_train_step("lm", donate=False, **step_kw)
        new_state, metrics = step(state, batch)
        return float(metrics["loss"]), new_state.params

    loss_std, params_std = run(TransformerLM(config=cfg, dtype=jnp.float32))
    loss_chk, params_chk = run(
        TransformerLM(config=cfg, dtype=jnp.float32, return_prehead=True),
        loss_chunk=4,
    )
    np.testing.assert_allclose(loss_chk, loss_std, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(params_chk), jax.tree.leaves(params_std)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_pipelined_prehead_matches_flat():
    """PipelinedLM(return_prehead=True) + chunked loss == the flat prehead
    model (weights remapped), closing the --loss_chunk x --pp composition."""
    from deeplearning_mpi_tpu.models.pipeline_lm import PipelinedLM
    from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

    mesh = create_mesh(MeshSpec(data=4, pipe=2))
    cfg = TransformerConfig.tiny()
    pipelined = PipelinedLM(
        cfg, mesh, num_microbatches=2, dtype=jnp.float32, return_prehead=True
    )
    tokens = jnp.asarray(
        np.random.default_rng(6).integers(0, cfg.vocab_size, (4, 16)), jnp.int32
    )
    variables = pipelined.init(jax.random.key(0), tokens)
    x, kernel = jax.jit(pipelined.apply)(variables, tokens)
    loss_pp = chunked_lm_loss(x, kernel, tokens, chunk_size=4)

    p = variables["params"]
    flat_params = {
        "embed": p["embed_head"]["embed"],
        "final_norm": p["embed_head"]["final_norm"],
        "layer_0": jax.tree.map(lambda leaf: leaf[0], p["stages"]["block_0"]),
        "layer_1": jax.tree.map(lambda leaf: leaf[1], p["stages"]["block_0"]),
    }
    flat = TransformerLM(config=cfg, dtype=jnp.float32, return_prehead=True)
    xf, kf = flat.apply({"params": flat_params}, tokens)
    loss_flat = chunked_lm_loss(xf, kf, tokens, chunk_size=4)
    np.testing.assert_allclose(float(loss_pp), float(loss_flat), rtol=1e-5)


def test_moe_composes_with_chunked_loss():
    """MoE x loss_chunk: the aux collection rides mutable independently of
    the (x, kernel) output tuple."""
    from deeplearning_mpi_tpu.models.moe import AUX_COLLECTION, collect_aux_loss

    cfg = TransformerConfig.tiny_moe()
    model = TransformerLM(config=cfg, dtype=jnp.float32, return_prehead=True)
    tokens = jnp.asarray(
        np.random.default_rng(7).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
    )
    variables = model.init(jax.random.key(0), tokens)
    (x, kernel), mutated = model.apply(
        {"params": variables["params"]}, tokens, mutable=[AUX_COLLECTION]
    )
    loss = chunked_lm_loss(x, kernel, tokens, chunk_size=4)
    assert np.isfinite(float(loss))
    assert float(collect_aux_loss(mutated)) > 0.0

    plain = TransformerLM(config=cfg, dtype=jnp.float32)
    logits = plain.apply({"params": variables["params"]}, tokens)
    np.testing.assert_allclose(
        float(loss), float(lm_cross_entropy(logits, tokens)), rtol=1e-6
    )


def test_pipelined_untied_prehead_rejected_at_construction():
    import dataclasses

    from deeplearning_mpi_tpu.models.pipeline_lm import PipelinedLM
    from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh

    cfg = dataclasses.replace(TransformerConfig.tiny(), tied_embeddings=False)
    with pytest.raises(ValueError, match="tied_embeddings"):
        PipelinedLM(
            cfg, create_mesh(MeshSpec(data=4, pipe=2)), return_prehead=True
        )
