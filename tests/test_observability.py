"""Profiling, step timing, collective latency, and resilience subsystems."""

import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.train.resilience import (
    Heartbeat,
    TrainingFailure,
    preflight,
    run_with_auto_resume,
)
from deeplearning_mpi_tpu.utils.profiling import (
    Profiler,
    StepTimer,
    measure_collective_latency,
)


class TestStepTimer:
    def test_times_steps_and_summarizes(self):
        timer = StepTimer(sync_every=4)
        x = jnp.zeros((8, 8))
        step = jax.jit(lambda a: a @ a + 1.0)
        out = step(x)
        timer.tick(out)  # window start
        for _ in range(8):
            out = step(out)
            timer.tick(out)
        s = timer.summary(items_per_step=32)
        assert s["steps_timed"] == 8
        assert s["step_ms_p50"] > 0
        assert s["items_per_s"] > 0
        assert s["items_per_s_per_device"] == pytest.approx(
            s["items_per_s"] / jax.device_count()
        )

    def test_empty_summary(self):
        assert StepTimer().summary() == {}

    def test_short_run_flushes_partial_window(self):
        """Fewer steps than sync_every must still produce stats (summary
        flushes the pending window)."""
        timer = StepTimer(sync_every=10)
        x = jnp.ones((4, 4))
        step = jax.jit(lambda a: a + 1.0)
        out = step(x)
        timer.tick(out)
        for _ in range(3):
            out = step(out)
            timer.tick(out)
        s = timer.summary()
        assert s["steps_timed"] == 3
        assert s["step_ms_p50"] > 0


class TestProfiler:
    def test_trace_writes_files(self, tmp_path):
        prof = Profiler(tmp_path / "trace")
        step = jax.jit(lambda a: a * 2.0)
        out = prof.trace_steps(step, jnp.ones((4,)), num_steps=2)
        np.testing.assert_allclose(np.asarray(out), 2.0)
        files = list((tmp_path / "trace").rglob("*"))
        assert files, "profiler trace produced no files"

    def test_disabled_profiler_is_noop(self):
        prof = Profiler(None)
        with prof:
            pass  # no trace dir: start/stop must be no-ops


class TestCollectiveLatency:
    def test_measures_allreduce_on_mesh(self, mesh):
        out = measure_collective_latency(mesh, num_floats=1 << 12, trials=3)
        assert out["axis_size"] == 8
        assert out["all_reduce_ms_min"] > 0
        assert out["bus_gbps"] > 0


class TestAutoResume:
    def test_retries_from_checkpoint_then_succeeds(self):
        calls = []

        class FakeCkpt:
            def latest_epoch(self):
                return 3

        def fit(start_epoch):
            calls.append(start_epoch)
            if len(calls) < 3:
                raise RuntimeError("boom")
            return "done"

        out = run_with_auto_resume(
            fit, FakeCkpt(), max_restarts=3, restart_delay_s=0.0,
            logger=type("L", (), {"log": staticmethod(lambda m: None)})(),
        )
        assert out == "done"
        assert calls == [0, 4, 4]  # restarts resume at checkpoint epoch + 1

    def test_exhausted_budget_raises_loudly(self):
        class FakeCkpt:
            def latest_epoch(self):
                return None

        def fit(start_epoch):
            raise RuntimeError("persistent failure")

        with pytest.raises(TrainingFailure):
            run_with_auto_resume(
                fit, FakeCkpt(), max_restarts=1, restart_delay_s=0.0,
                logger=type("L", (), {"log": staticmethod(lambda m: None)})(),
            )


class TestHeartbeat:
    def test_writes_progress_json(self, tmp_path):
        path = tmp_path / "hb.json"
        hb = Heartbeat(path, interval_s=0.05)
        with hb:
            hb.progress = {"epoch": 2, "step": 17}
            deadline = time.time() + 5.0
            while time.time() < deadline:
                if path.exists() and "step" in path.read_text():
                    break
                time.sleep(0.05)
        payload = json.loads(path.read_text())
        assert payload["step"] == 17
        assert payload["process_index"] == 0

    def test_stop_is_idempotent(self, tmp_path):
        hb = Heartbeat(tmp_path / "hb.json", interval_s=0.05).start()
        hb.stop()
        hb.stop()


class TestRunLoggerMetrics:
    def test_jsonl_sidecar(self, tmp_path):
        import json

        from deeplearning_mpi_tpu.utils.logging import RunLogger

        logger = RunLogger(tmp_path, echo=False, run_name="run")
        logger.log_metrics({"kind": "epoch", "epoch": 0, "loss": 1.25})
        logger.log_metrics({"kind": "epoch", "epoch": 1, "loss": 1.0})
        records = [
            json.loads(line)
            for line in (tmp_path / "run.metrics.jsonl").read_text().splitlines()
        ]
        assert [r["epoch"] for r in records] == [0, 1]
        assert records[0]["loss"] == 1.25
        assert all("ts" in r and r["kind"] == "epoch" for r in records)

    def test_disabled_without_log_dir(self):
        from deeplearning_mpi_tpu.utils.logging import RunLogger

        RunLogger(None, echo=False).log_metrics({"loss": 1.0})  # no-op, no crash


class TestPreflight:
    def test_missing_data_dir_fails_with_message(self, tmp_path):
        with pytest.raises(SystemExit, match="data directory"):
            preflight(data_dir=str(tmp_path / "nope"))

    def test_creates_model_and_log_dirs(self, tmp_path):
        preflight(model_dir=str(tmp_path / "m"), log_dir=str(tmp_path / "l"))
        assert (tmp_path / "m").is_dir() and (tmp_path / "l").is_dir()

    def test_batch_divisibility(self, mesh):
        with pytest.raises(SystemExit, match="divisible"):
            preflight(global_batch_size=12, mesh=mesh)
        preflight(global_batch_size=16, mesh=mesh)  # ok

    def test_grad_accum_divisibility(self, mesh):
        # 8-device data axis: batch 32 / grad_accum 5 doesn't divide; 32/8
        # divides the batch but leaves per-chunk 4 < dp 8.
        with pytest.raises(SystemExit, match="grad_accum 5"):
            preflight(global_batch_size=32, mesh=mesh, grad_accum=5)
        with pytest.raises(SystemExit, match="per-chunk batch"):
            preflight(global_batch_size=32, mesh=mesh, grad_accum=8)
        preflight(global_batch_size=32, mesh=mesh, grad_accum=2)  # ok


class TestExecuteTraining:
    """The CLI tail: donated-state rebuild on pre-checkpoint crashes."""

    def _make(self, fail_times, latest=None):
        import argparse

        calls = {"fit": 0, "factory": 0, "restore": 0, "placed": 0}

        class FakeTrainer:
            heartbeat = None
            profiler = None
            shutdown = None
            logger = type("L", (), {"log": staticmethod(lambda m: None)})()
            state = "initial"

            def place_state(self):
                calls["placed"] += 1

            def fit(self, loader, num_epochs, eval_loader=None, start_epoch=0):
                calls["fit"] += 1
                if calls["fit"] <= fail_times:
                    raise RuntimeError("crash")
                return "done"

        class FakeCkpt:
            def latest_epoch(self):
                return latest

            def restore_verified(self, template):
                calls["restore"] += 1
                return "restored", latest

        def state_factory():
            calls["factory"] += 1
            return "fresh"

        args = argparse.Namespace(num_epochs=5, max_restarts=2)
        return FakeTrainer(), FakeCkpt(), args, state_factory, calls

    def test_precheckpoint_crash_rebuilds_fresh_state(self):
        from deeplearning_mpi_tpu.utils.config import execute_training

        trainer, ckpt, args, factory, calls = self._make(fail_times=1, latest=None)
        # Patch out the restart delay to keep the test fast.
        import deeplearning_mpi_tpu.resilience.supervisor as sup
        from unittest import mock

        with mock.patch.object(sup.time, "sleep"):
            out = execute_training(
                trainer, ckpt, args, None, None, 0, state_factory=factory
            )
        assert out == "done"
        # crash before any checkpoint: a FRESH state must be built (the old
        # one's buffers were donated), never the deleted one reused
        assert calls["factory"] == 1
        assert trainer.state == "fresh"
        assert calls["placed"] == 1

    def test_postcheckpoint_crash_restores_latest(self):
        import deeplearning_mpi_tpu.resilience.supervisor as sup
        from unittest import mock

        from deeplearning_mpi_tpu.utils.config import execute_training

        trainer, ckpt, args, factory, calls = self._make(fail_times=1, latest=3)
        with mock.patch.object(sup.time, "sleep"):
            out = execute_training(
                trainer, ckpt, args, None, None, 0, state_factory=factory
            )
        assert out == "done"
        assert calls["restore"] == 1
        assert trainer.state == "restored"


class TestMetricsRegistry:
    def test_counter_gauge_histogram_semantics(self):
        from deeplearning_mpi_tpu.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("tokens")
        c.inc()
        c.inc(4.0)
        assert c.value == 5.0
        assert reg.counter("tokens") is c  # get-or-create
        with pytest.raises(ValueError):
            c.inc(-1.0)
        reg.gauge("mfu").set(0.41)
        assert reg.gauge("mfu").value == 0.41
        h = reg.histogram("step_ms")
        for v in (5.0, 1.0, 3.0, 2.0, 4.0):
            h.observe(v)
        s = h.summary()
        assert s["count"] == 5 and s["mean"] == 3.0
        assert s["p50"] == 3.0 and s["max"] == 5.0
        snap = reg.snapshot()
        assert snap["tokens"] == 5.0 and snap["step_ms_p50"] == 3.0

    def test_emit_canonical_record_shape(self):
        from deeplearning_mpi_tpu.telemetry import InMemorySink, MetricsRegistry

        sink = InMemorySink()
        reg = MetricsRegistry([sink])
        reg.emit("epoch", {"loss": jnp.asarray(1.5), "nan": float("nan"),
                           "note": "x"})
        (rec,) = sink.records
        assert rec["kind"] == "epoch" and isinstance(rec["ts"], float)
        assert rec["loss"] == 1.5 and isinstance(rec["loss"], float)
        assert rec["nan"] is None  # non-finite -> null, JSON-safe
        assert rec["note"] == "x"

    def test_record_step_buffers_without_fetch_then_one_flush(self):
        from deeplearning_mpi_tpu.telemetry import InMemorySink, MetricsRegistry

        sink = InMemorySink()
        reg = MetricsRegistry([sink])
        for step in range(3):
            reg.record_step(step, {"loss": jnp.asarray(float(step))})
        assert sink.records == []  # nothing emitted until the flush
        out = reg.flush_steps(extra={"epoch": 7})
        assert [r["step"] for r in out] == [0, 1, 2]
        assert all(r["kind"] == "step" and r["epoch"] == 7 for r in sink.records)
        assert sink.records[2]["loss"] == 2.0
        assert reg.flush_steps() == []  # buffer drained

    def test_broken_sink_never_raises_into_the_loop(self):
        from deeplearning_mpi_tpu.telemetry import InMemorySink, MetricsRegistry

        class Broken:
            def write(self, record):
                raise RuntimeError("sink died")

            def close(self):
                raise RuntimeError("close died")

        good = InMemorySink()
        reg = MetricsRegistry([Broken(), good])
        reg.emit("step", {"loss": 1.0})  # must not raise
        reg.close()  # must not raise
        assert len(good.records) == 1


class TestJsonlSink:
    def test_round_trip(self, tmp_path):
        from deeplearning_mpi_tpu.telemetry import JsonlSink, MetricsRegistry

        path = tmp_path / "sub" / "metrics.jsonl"  # parent dirs auto-created
        reg = MetricsRegistry([JsonlSink(path)])
        reg.emit("epoch", {"epoch": 0, "loss": 2.5})
        reg.record_step(0, {"loss": jnp.asarray(2.25)})
        reg.close()  # close() drains the pending step buffer
        records = [json.loads(line) for line in path.read_text().splitlines()]
        assert [r["kind"] for r in records] == ["epoch", "step"]
        assert records[0]["loss"] == 2.5 and records[1]["loss"] == 2.25

    def test_report_tool_renders_required_columns(self, tmp_path):
        """tools/metrics_report.py renders a registry-written JSONL with the
        acceptance columns non-null."""
        import pathlib
        import sys

        sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))
        try:
            import metrics_report
        finally:
            sys.path.pop(0)
        from deeplearning_mpi_tpu.telemetry import JsonlSink, MetricsRegistry

        path = tmp_path / "metrics.jsonl"
        reg = MetricsRegistry([JsonlSink(path)])
        reg.record_step(0, {"loss": 2.0, "finite": 1.0})
        reg.flush_steps(extra={"epoch": 0, "comm_bytes": 1e6})
        reg.emit("epoch", {"epoch": 0, "loss": 2.0, "images_per_s": 100.0,
                           "step_ms_p50": 10.0, "step_ms_p95": 12.0,
                           "mfu": 0.3, "comm_bytes_per_step": 1e6})
        reg.close()
        report = metrics_report.summarize(metrics_report.load_records(path))
        for needle in ("images/s", "p50", "p95", "MFU", "collective bytes"):
            assert needle in report


class TestFlopsAndMfu:
    def test_transformer_flops_match_hand_computation(self):
        """Tiny dense config, fwd FLOPs recomputed by hand term by term."""
        from deeplearning_mpi_tpu.models import TransformerConfig
        from deeplearning_mpi_tpu.telemetry.flops import (
            transformer_fwd_flops,
            transformer_train_flops,
        )

        cfg = TransformerConfig(
            vocab_size=256, num_layers=2, num_heads=4, head_dim=8,
            d_model=32, d_ff=64,
        )
        batch, seq = 2, 16
        d, h, dh, ff = 32, 4, 8, 64
        per_token = (
            2 * d * (h * dh) * 2      # q + out projections
            + 2 * d * (h * dh) * 2    # k + v (no GQA: kv heads == heads)
            + 4 * (seq / 2) * h * dh  # scores + values at S/2 visible
            + 6 * d * ff              # SwiGLU gate/up/down
        )
        expected = batch * seq * (2 * per_token + 2 * d * 256)
        assert transformer_fwd_flops(cfg, batch, seq) == pytest.approx(expected)
        assert transformer_train_flops(cfg, batch, seq) == pytest.approx(
            3 * expected
        )

    def test_mfu_arithmetic(self):
        from deeplearning_mpi_tpu.telemetry.flops import mfu

        # 1e9 FLOPs in 0.5 s on 1 device with 200e9 peak -> 1% exactly.
        assert mfu(1e9, 0.5, n_devices=1, peak_flops_per_device=200e9) == (
            pytest.approx(0.01)
        )
        assert mfu(0.0, 0.5, n_devices=1, peak_flops_per_device=1.0) is None
        assert mfu(1e9, 0.0, n_devices=1, peak_flops_per_device=1.0) is None

    def test_peak_flops_env_override(self, monkeypatch):
        from deeplearning_mpi_tpu.telemetry import flops

        monkeypatch.setenv("DMT_PEAK_FLOPS", "123e9")
        assert flops.device_peak_flops() == 123e9

    def test_remat_flops_pinned(self):
        """Pin the remat-aware per-step FLOP accounting to exact literals
        (same tiny config as test_transformer_flops_match_hand_computation,
        batch 2 x seq 16). 'full' re-runs every block forward in the
        backward pass — one extra forward MINUS the head (the loss head is
        outside the remat'd blocks); 'dots' only saves matmul outputs, so
        its recompute is ~free and counted as 0; issued = train + recompute.
        A change to any of these numbers is a change to what mfu_issued and
        mfu_gap report and must be deliberate."""
        from deeplearning_mpi_tpu.models import TransformerConfig
        from deeplearning_mpi_tpu.telemetry.flops import (
            transformer_issued_flops,
            transformer_remat_flops,
            transformer_train_flops,
        )

        cfg = TransformerConfig(
            vocab_size=256, num_layers=2, num_heads=4, head_dim=8,
            d_model=32, d_ff=64,
        )
        batch, seq = 2, 16
        assert transformer_train_flops(cfg, batch, seq) == 5701632.0
        assert transformer_remat_flops(cfg, batch, seq, remat="none") == 0.0
        assert transformer_remat_flops(cfg, batch, seq, remat="dots") == 0.0
        assert transformer_remat_flops(cfg, batch, seq, remat="full") == 1376256.0
        # bool spellings map to the same policies as the model flag.
        assert transformer_remat_flops(cfg, batch, seq, remat=True) == 1376256.0
        assert transformer_remat_flops(cfg, batch, seq, remat=False) == 0.0
        assert transformer_issued_flops(cfg, batch, seq, remat="none") == 5701632.0
        assert transformer_issued_flops(cfg, batch, seq, remat="full") == 7077888.0
        with pytest.raises(ValueError, match="remat"):
            transformer_remat_flops(cfg, batch, seq, remat="sometimes")

    def test_overlap_fraction_roofline(self):
        from deeplearning_mpi_tpu.telemetry.flops import overlap_fraction

        # Compute-bound: compute_s = 2e9/(2*1e12) = 1 ms dwarfs comm_s =
        # (1e6/2)/1e10 = 50 us -> everything hideable, capped at 1.0.
        assert overlap_fraction(
            1e6, 2e9, n_devices=2, peak_flops_per_device=1e12,
            link_bandwidth_per_device=1e10,
        ) == 1.0
        # Comm-bound: comm_s = 50 ms vs compute_s = 1 ms -> 2% hideable.
        assert overlap_fraction(
            1e9, 2e9, n_devices=2, peak_flops_per_device=1e12,
            link_bandwidth_per_device=1e10,
        ) == pytest.approx(0.02)
        # No collective bytes: nothing to hide, trivially 1.0.
        assert overlap_fraction(0.0, 2e9, n_devices=2) == 1.0
        # Degenerate inputs: None, not a fake number.
        assert overlap_fraction(1e6, 0.0) is None
        assert overlap_fraction(None, 2e9) is None
        assert overlap_fraction(-1.0, 2e9) is None

    def test_link_bandwidth_env_override(self, monkeypatch):
        from deeplearning_mpi_tpu.telemetry import flops

        monkeypatch.setenv("DMT_LINK_BANDWIDTH", "42e9")
        assert flops.device_link_bandwidth() == 42e9
        monkeypatch.delenv("DMT_LINK_BANDWIDTH")
        # CPU test devices fall through the TPU table to the nominal figure.
        assert flops.device_link_bandwidth() == (
            flops.CPU_NOMINAL_LINK_BANDWIDTH
        )


class TestCommsAccounting:
    def test_collective_byte_formulas(self):
        from deeplearning_mpi_tpu.telemetry import comms

        B = 1000.0
        assert comms.allreduce_bytes(B, 4) == pytest.approx(2 * 3 / 4 * B)
        assert comms.reduce_scatter_bytes(B, 4) == pytest.approx(3 / 4 * B)
        assert comms.all_gather_bytes(B, 4) == pytest.approx(3 / 4 * B)
        assert comms.all_to_all_bytes(B, 4) == pytest.approx(3 / 4 * B)
        assert comms.ppermute_bytes(B, 4) == B
        # Degenerate single-device axis: everything is free.
        for fn in (comms.allreduce_bytes, comms.reduce_scatter_bytes,
                   comms.all_gather_bytes, comms.all_to_all_bytes,
                   comms.ppermute_bytes):
            assert fn(B, 1) == 0.0

    def test_dp_grad_allreduce_and_zero_equivalence(self):
        from deeplearning_mpi_tpu.telemetry import comms

        n_params, dp = 1_000_000, 8
        plain = comms.dp_grad_allreduce_bytes(n_params, dp)
        zero = comms.dp_grad_allreduce_bytes(n_params, dp, zero=True)
        # ZeRO-1's RS+AG moves the same wire volume as the all-reduce.
        assert plain == pytest.approx(zero)
        assert plain == pytest.approx(2 * 7 / 8 * n_params * 4)

    def test_param_count_never_fetches(self):
        from deeplearning_mpi_tpu.telemetry import comms

        params = {"w": jnp.zeros((4, 8)), "b": jnp.zeros((8,))}
        assert comms.param_count(params) == 40


class TestTraceAnnotations:
    def test_annotations_do_not_change_train_step_outputs(self):
        """Annotated regions are semantics-free: the same train step on the
        same batch yields bit-identical loss and params with tracing
        enabled vs disabled (CPU mesh; exercises trainer/train_step and the
        model-internal scopes)."""
        from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
        from deeplearning_mpi_tpu.telemetry import trace
        from deeplearning_mpi_tpu.train import create_train_state
        from deeplearning_mpi_tpu.train.trainer import (
            build_optimizer,
            make_train_step,
        )

        model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
        tx = build_optimizer("sgd", 1e-2, momentum=0.0)

        def run_one():
            state = create_train_state(
                model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
            )
            batch = {
                "tokens": jnp.asarray(
                    np.random.default_rng(3).integers(0, 256, (4, 16)),
                    jnp.int32,
                )
            }
            new_state, metrics = make_train_step("lm", donate=False)(state, batch)
            return float(metrics["loss"]), jax.tree.leaves(new_state.params)

        old = trace.set_enabled(True)
        try:
            loss_on, params_on = run_one()
            trace.set_enabled(False)
            loss_off, params_off = run_one()
        finally:
            trace.set_enabled(old)
        assert loss_on == loss_off
        for a, b in zip(params_on, params_off):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_annotate_is_noop_when_disabled(self):
        from deeplearning_mpi_tpu.telemetry import trace

        old = trace.set_enabled(False)
        try:
            with trace.annotate("x"):
                out = jnp.ones(()) + 1.0
        finally:
            trace.set_enabled(old)
        assert float(out) == 2.0


class TestTrainerTelemetry:
    def test_trainer_emits_canonical_records_through_registry(self, mesh):
        """Satellite (b): Trainer metric records flow through ONE registry —
        the RunLogger sidecar and any other sink receive identical
        canonical records, per-step scalars included."""
        from deeplearning_mpi_tpu.telemetry import InMemorySink
        from deeplearning_mpi_tpu.train import Trainer, create_train_state
        from deeplearning_mpi_tpu.train.trainer import build_optimizer

        from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM

        model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
        tx = build_optimizer("sgd", 1e-2, momentum=0.0)
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
        )

        class FakeLoader:
            def epoch(self, epoch):
                rng = np.random.default_rng(epoch)
                for _ in range(3):
                    yield {
                        "tokens": jnp.asarray(
                            rng.integers(0, 256, (8, 16)), jnp.int32
                        )
                    }

        class FakeLogger:
            def __init__(self):
                self.records = []

            def log(self, msg):
                pass

            def log_metrics(self, record):
                self.records.append(dict(record))

        logger = FakeLogger()
        sink = InMemorySink()
        trainer = Trainer(
            state, "lm", mesh, logger=logger, flops_per_step=1e6,
            comm_bytes_per_step=2048.0,
        )
        trainer.metrics.add_sink(sink)
        stats = trainer.run_epoch(FakeLoader(), epoch=0)
        trainer._log_metrics("epoch", stats)
        kinds = [r["kind"] for r in sink.records]
        assert kinds.count("step") == 3 and kinds[-1] == "epoch"
        # LoggerSink fans the SAME records to the RunLogger-style consumer.
        assert logger.records == sink.records
        steps = [r for r in sink.records if r["kind"] == "step"]
        assert [r["step"] for r in steps] == [0, 1, 2]
        assert all(r["epoch"] == 0 and r["comm_bytes"] == 2048.0 for r in steps)
        epoch_rec = sink.records[-1]
        assert epoch_rec["mfu"] is not None and epoch_rec["mfu"] > 0
        assert epoch_rec["comm_bytes_per_step"] == 2048.0
        assert "ts" in epoch_rec

    def test_trainer_emits_mfu_gap_and_overlap_fraction(self, mesh):
        """With issued FLOPs configured, the epoch stats must carry the
        remat-aware companions: mfu_issued (recompute priced in), their
        difference mfu_gap, and the roofline overlap_fraction estimate —
        the columns tools/metrics_report.py renders."""
        from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
        from deeplearning_mpi_tpu.train import Trainer, create_train_state
        from deeplearning_mpi_tpu.train.trainer import build_optimizer

        model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
        tx = build_optimizer("sgd", 1e-2, momentum=0.0)
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
        )

        class FakeLoader:
            def epoch(self, epoch):
                rng = np.random.default_rng(epoch)
                for _ in range(2):
                    yield {
                        "tokens": jnp.asarray(
                            rng.integers(0, 256, (8, 16)), jnp.int32
                        )
                    }

        trainer = Trainer(
            state, "lm", mesh, flops_per_step=1e6,
            issued_flops_per_step=1.3e6, comm_bytes_per_step=2048.0,
        )
        stats = trainer.run_epoch(FakeLoader(), epoch=0)
        assert stats["mfu"] > 0
        assert stats["mfu_issued"] == pytest.approx(1.3 * stats["mfu"])
        assert stats["mfu_gap"] == pytest.approx(
            stats["mfu_issued"] - stats["mfu"]
        )
        assert 0.0 < stats["overlap_fraction"] <= 1.0
        # Without issued FLOPs, none of the companions appear — no fake 0s.
        plain = Trainer(state, "lm", mesh, flops_per_step=1e6)
        stats2 = plain.run_epoch(FakeLoader(), epoch=0)
        assert "mfu_issued" not in stats2 and "mfu_gap" not in stats2
        assert "overlap_fraction" not in stats2

    def test_metrics_every_thins_step_records(self, mesh):
        from deeplearning_mpi_tpu.telemetry import InMemorySink
        from deeplearning_mpi_tpu.train import Trainer, create_train_state
        from deeplearning_mpi_tpu.train.trainer import build_optimizer

        from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM

        model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
        tx = build_optimizer("sgd", 1e-2, momentum=0.0)
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
        )

        class FakeLoader:
            def epoch(self, epoch):
                rng = np.random.default_rng(epoch)
                for _ in range(4):
                    yield {
                        "tokens": jnp.asarray(
                            rng.integers(0, 256, (8, 16)), jnp.int32
                        )
                    }

        sink = InMemorySink()
        trainer = Trainer(state, "lm", mesh, metrics_every=2, time_steps=False)
        trainer.metrics.add_sink(sink)
        trainer.run_epoch(FakeLoader(), epoch=0)
        steps = [r["step"] for r in sink.records if r["kind"] == "step"]
        assert steps == [0, 2]


class TestSpanTracing:
    """telemetry/spans.py: the span model, the per-process recorder, the
    flight ring, and the JSONL readers — all under fake clocks."""

    @staticmethod
    def _clock(start=0.0):
        t = [start]

        def advance(dt):
            t[0] += dt

        return (lambda: t[0]), advance

    def test_span_tree_nesting_with_fake_clock(self, tmp_path):
        from deeplearning_mpi_tpu.telemetry.spans import (
            SpanRecorder,
            load_trace_file,
            span_tree,
        )

        clock, advance = self._clock(100.0)
        rec = SpanRecorder(tmp_path / "trace_t.jsonl", proc="t",
                           clock=clock, epoch_clock=lambda: 1e9)
        root = rec.begin("request", trace="r1", rid=1)
        advance(0.25)
        child = rec.begin("prefill", trace="r1", parent=root.sid)
        advance(0.5)
        rec.end(child)
        advance(0.25)
        rec.end(root)
        rec.close()

        meta, records = load_trace_file(rec.path)
        assert meta["proc"] == "t" and meta["pid"] == rec.pid
        spans = [r for r in records if r["kind"] == "span"]
        # end() writes on close, so the CHILD hits disk first — the tree
        # readers must not rely on parents preceding children.
        assert [s["name"] for s in spans] == ["prefill", "request"]
        by_sid, children, orphans = span_tree(spans)
        assert not orphans
        assert [c["name"] for c in children[root.sid]] == ["prefill"]
        assert by_sid[child.sid]["t1"] - by_sid[child.sid]["t0"] == 0.5
        assert by_sid[root.sid]["t1"] - by_sid[root.sid]["t0"] == 1.0
        assert by_sid[root.sid]["labels"] == {"rid": 1}

    def test_orphan_detection(self, tmp_path):
        from deeplearning_mpi_tpu.telemetry.spans import (
            SpanRecorder,
            load_trace_file,
            span_tree,
        )

        rec = SpanRecorder(tmp_path / "trace_t.jsonl", proc="t",
                           clock=lambda: 1.0, epoch_clock=lambda: 2.0)
        rec.record_span("decode", 1.0, 2.0, trace="r7",
                        parent="dead-proc/999:0")
        rec.close()
        _, records = load_trace_file(rec.path)
        _, _, orphans = span_tree(records)
        assert len(orphans) == 1
        assert orphans[0]["parent"] == "dead-proc/999:0"

    def test_flight_ring_evicts_oldest(self, tmp_path):
        from deeplearning_mpi_tpu.telemetry.spans import SpanRecorder

        rec = SpanRecorder(tmp_path / "trace_t.jsonl", proc="t", ring=4,
                           clock=lambda: 0.0, epoch_clock=lambda: 0.0,
                           flight_dir=tmp_path / "flight")
        for i in range(10):
            rec.record_span(f"s{i}", float(i), float(i) + 0.5, trace="r0")
        out = rec.dump_flight("unit test")
        rec.close()
        assert out is not None and out.parent == tmp_path / "flight"
        assert "unit-test" in out.name  # reason sanitized for filenames
        payload = json.loads(out.read_text())
        assert payload["spans_total"] == 10
        # Bounded ring: only the 4 most recent records survive to the dump.
        assert [r["name"] for r in payload["ring"]] == [
            "s6", "s7", "s8", "s9",
        ]

    def test_torn_final_line_dropped_on_read(self, tmp_path):
        from deeplearning_mpi_tpu.telemetry.spans import (
            SpanRecorder,
            load_trace_file,
        )

        rec = SpanRecorder(tmp_path / "trace_t.jsonl", proc="t",
                           clock=lambda: 5.0, epoch_clock=lambda: 5.0)
        rec.record_span("queue", 1.0, 2.0, trace="r0")
        rec.record_span("decode", 2.0, 3.0, trace="r0")
        rec.close()
        # The single-writer contract's only failure mode: a process dies
        # mid-write and the file ends in half a record, no newline.
        with rec.path.open("a") as f:
            f.write('{"kind": "span", "name": "pref')
        meta, records = load_trace_file(rec.path)
        assert meta is not None
        assert [r["name"] for r in records] == ["queue", "decode"]

    def test_meta_line_carries_clock_offset(self, tmp_path):
        from deeplearning_mpi_tpu.telemetry.spans import (
            SpanRecorder,
            load_trace_file,
        )

        # Wall clock 1000, monotonic 400: the offset that places this
        # process's monotonic stamps on the wall-clock timeline is 600.
        rec = SpanRecorder(tmp_path / "trace_t.jsonl", proc="t",
                           clock=lambda: 400.0, epoch_clock=lambda: 1000.0)
        rec.close()
        assert rec.mono_offset == 600.0
        meta, _ = load_trace_file(rec.path)
        assert meta["mono_offset"] == 600.0
        assert meta["ts"] == 1000.0

    def test_skewed_monotonic_clocks_merge_onto_one_timeline(self, tmp_path):
        """Satellite regression: two workers whose monotonic epochs differ
        wildly (different boots) but whose wall clocks agree must merge
        into ONE consistent timeline — each file's own mono_offset does
        the alignment, applied by tools/trace_report.merge_traces."""
        import importlib.util

        from deeplearning_mpi_tpu.telemetry.spans import SpanRecorder

        spec = importlib.util.spec_from_file_location(
            "trace_report",
            Path(__file__).resolve().parent.parent / "tools"
            / "trace_report.py",
        )
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)

        wall = 1.75e9
        a = SpanRecorder(tmp_path / "trace_a.jsonl", proc="a",
                         clock=lambda: 10.0, epoch_clock=lambda: wall)
        b = SpanRecorder(tmp_path / "trace_b.jsonl", proc="b",
                         clock=lambda: 9010.0, epoch_clock=lambda: wall)
        # The same wall instant, expressed in each process's coordinates:
        # a's monotonic reads 10.0 where b's reads 9010.0.
        a.record_span("request", 10.0, 10.5, trace="r0")
        b.record_span("stream", 9010.5, 9010.6, trace="r0")
        a.close()
        b.close()
        _, merged = tr.merge_traces(sorted(tmp_path.glob("trace_*.jsonl")))
        req = next(s for s in merged if s["name"] == "request")
        stream = next(s for s in merged if s["name"] == "stream")
        assert req["t0"] == pytest.approx(wall, abs=1e-6)
        assert stream["t0"] == pytest.approx(req["t1"], abs=1e-6)

    def test_failed_write_degrades_to_dropped_count(self, tmp_path):
        """Recording must never raise into the serving/training hot path:
        a dead file degrades to span_dropped_total, ring still fed."""
        from deeplearning_mpi_tpu.telemetry.spans import SpanRecorder

        rec = SpanRecorder(tmp_path / "trace_t.jsonl", proc="t",
                           clock=lambda: 0.0, epoch_clock=lambda: 0.0)
        rec._f.close()  # simulate the fd dying under the recorder
        span = rec.record_span("decode", 0.0, 1.0, trace="r0")  # no raise
        assert span.duration == 1.0
        assert rec.dropped_total == 1
        assert rec.spans_total == 1
        assert any(r.get("name") == "decode" for r in rec._ring)
        rec.close()

    def test_tracing_off_allocates_nothing(self, tmp_path):
        """Costless-off (the DMT_SANITIZE pattern): with no trace dir the
        hot-path hook is one pointer test — zero allocations, zero files.
        This is the guard exactly as serving/engine.py and
        train/trainer.py write it."""
        import gc
        import sys as _sys

        tracer = None

        def measure(body) -> int:
            gc.collect()
            before = _sys.getallocatedblocks()
            body()
            return _sys.getallocatedblocks() - before

        def baseline():
            for _ in range(10_000):
                pass

        def guarded():
            for _ in range(10_000):
                if tracer is not None:  # the hot-path guard under test
                    tracer.event("engine_step", step=0)

        # The frame machinery itself costs a block or two; the guarded
        # loop must cost no more than the empty loop (min over trials
        # irons out interpreter noise — a REAL per-call allocation would
        # show up ~10k strong in every trial).
        base = min(measure(baseline) for _ in range(5))
        guard = min(measure(guarded) for _ in range(5))
        assert guard <= base, (
            f"tracing-off guard allocated: {guard} blocks vs "
            f"baseline {base}"
        )
        assert list(tmp_path.glob("trace_*.jsonl")) == []

    def test_dump_all_covers_every_live_recorder(self, tmp_path):
        from deeplearning_mpi_tpu.telemetry.spans import (
            SpanRecorder,
            dump_all,
        )

        a = SpanRecorder(tmp_path / "trace_a.jsonl", proc="a",
                         clock=lambda: 0.0, epoch_clock=lambda: 0.0,
                         flight_dir=tmp_path / "flight")
        b = SpanRecorder(tmp_path / "trace_b.jsonl", proc="b",
                         clock=lambda: 0.0, epoch_clock=lambda: 0.0,
                         flight_dir=tmp_path / "flight")
        try:
            a.record_span("x", 0.0, 1.0)
            paths = dump_all("sanitizer-test")
            ours = [p for p in paths
                    if Path(p).parent == tmp_path / "flight"]
            assert len(ours) == 2
            procs = {json.loads(Path(p).read_text())["proc"] for p in ours}
            assert procs == {"a", "b"}
        finally:
            a.close()
            b.close()
        # Closed recorders leave the registry: a later dump skips them.
        assert not [p for p in dump_all("after-close")
                    if Path(p).parent == tmp_path / "flight"]
