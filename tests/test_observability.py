"""Profiling, step timing, collective latency, and resilience subsystems."""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.train.resilience import (
    Heartbeat,
    TrainingFailure,
    preflight,
    run_with_auto_resume,
)
from deeplearning_mpi_tpu.utils.profiling import (
    Profiler,
    StepTimer,
    measure_collective_latency,
)


class TestStepTimer:
    def test_times_steps_and_summarizes(self):
        timer = StepTimer(sync_every=4)
        x = jnp.zeros((8, 8))
        step = jax.jit(lambda a: a @ a + 1.0)
        out = step(x)
        timer.tick(out)  # window start
        for _ in range(8):
            out = step(out)
            timer.tick(out)
        s = timer.summary(items_per_step=32)
        assert s["steps_timed"] == 8
        assert s["step_ms_p50"] > 0
        assert s["items_per_s"] > 0
        assert s["items_per_s_per_device"] == pytest.approx(
            s["items_per_s"] / jax.device_count()
        )

    def test_empty_summary(self):
        assert StepTimer().summary() == {}

    def test_short_run_flushes_partial_window(self):
        """Fewer steps than sync_every must still produce stats (summary
        flushes the pending window)."""
        timer = StepTimer(sync_every=10)
        x = jnp.ones((4, 4))
        step = jax.jit(lambda a: a + 1.0)
        out = step(x)
        timer.tick(out)
        for _ in range(3):
            out = step(out)
            timer.tick(out)
        s = timer.summary()
        assert s["steps_timed"] == 3
        assert s["step_ms_p50"] > 0


class TestProfiler:
    def test_trace_writes_files(self, tmp_path):
        prof = Profiler(tmp_path / "trace")
        step = jax.jit(lambda a: a * 2.0)
        out = prof.trace_steps(step, jnp.ones((4,)), num_steps=2)
        np.testing.assert_allclose(np.asarray(out), 2.0)
        files = list((tmp_path / "trace").rglob("*"))
        assert files, "profiler trace produced no files"

    def test_disabled_profiler_is_noop(self):
        prof = Profiler(None)
        with prof:
            pass  # no trace dir: start/stop must be no-ops


class TestCollectiveLatency:
    def test_measures_allreduce_on_mesh(self, mesh):
        out = measure_collective_latency(mesh, num_floats=1 << 12, trials=3)
        assert out["axis_size"] == 8
        assert out["all_reduce_ms_min"] > 0
        assert out["bus_gbps"] > 0


class TestAutoResume:
    def test_retries_from_checkpoint_then_succeeds(self):
        calls = []

        class FakeCkpt:
            def latest_epoch(self):
                return 3

        def fit(start_epoch):
            calls.append(start_epoch)
            if len(calls) < 3:
                raise RuntimeError("boom")
            return "done"

        out = run_with_auto_resume(
            fit, FakeCkpt(), max_restarts=3, restart_delay_s=0.0,
            logger=type("L", (), {"log": staticmethod(lambda m: None)})(),
        )
        assert out == "done"
        assert calls == [0, 4, 4]  # restarts resume at checkpoint epoch + 1

    def test_exhausted_budget_raises_loudly(self):
        class FakeCkpt:
            def latest_epoch(self):
                return None

        def fit(start_epoch):
            raise RuntimeError("persistent failure")

        with pytest.raises(TrainingFailure):
            run_with_auto_resume(
                fit, FakeCkpt(), max_restarts=1, restart_delay_s=0.0,
                logger=type("L", (), {"log": staticmethod(lambda m: None)})(),
            )


class TestHeartbeat:
    def test_writes_progress_json(self, tmp_path):
        path = tmp_path / "hb.json"
        hb = Heartbeat(path, interval_s=0.05)
        with hb:
            hb.progress = {"epoch": 2, "step": 17}
            deadline = time.time() + 5.0
            while time.time() < deadline:
                if path.exists() and "step" in path.read_text():
                    break
                time.sleep(0.05)
        payload = json.loads(path.read_text())
        assert payload["step"] == 17
        assert payload["process_index"] == 0

    def test_stop_is_idempotent(self, tmp_path):
        hb = Heartbeat(tmp_path / "hb.json", interval_s=0.05).start()
        hb.stop()
        hb.stop()


class TestRunLoggerMetrics:
    def test_jsonl_sidecar(self, tmp_path):
        import json

        from deeplearning_mpi_tpu.utils.logging import RunLogger

        logger = RunLogger(tmp_path, echo=False, run_name="run")
        logger.log_metrics({"kind": "epoch", "epoch": 0, "loss": 1.25})
        logger.log_metrics({"kind": "epoch", "epoch": 1, "loss": 1.0})
        records = [
            json.loads(line)
            for line in (tmp_path / "run.metrics.jsonl").read_text().splitlines()
        ]
        assert [r["epoch"] for r in records] == [0, 1]
        assert records[0]["loss"] == 1.25
        assert all("ts" in r and r["kind"] == "epoch" for r in records)

    def test_disabled_without_log_dir(self):
        from deeplearning_mpi_tpu.utils.logging import RunLogger

        RunLogger(None, echo=False).log_metrics({"loss": 1.0})  # no-op, no crash


class TestPreflight:
    def test_missing_data_dir_fails_with_message(self, tmp_path):
        with pytest.raises(SystemExit, match="data directory"):
            preflight(data_dir=str(tmp_path / "nope"))

    def test_creates_model_and_log_dirs(self, tmp_path):
        preflight(model_dir=str(tmp_path / "m"), log_dir=str(tmp_path / "l"))
        assert (tmp_path / "m").is_dir() and (tmp_path / "l").is_dir()

    def test_batch_divisibility(self, mesh):
        with pytest.raises(SystemExit, match="divisible"):
            preflight(global_batch_size=12, mesh=mesh)
        preflight(global_batch_size=16, mesh=mesh)  # ok

    def test_grad_accum_divisibility(self, mesh):
        # 8-device data axis: batch 32 / grad_accum 5 doesn't divide; 32/8
        # divides the batch but leaves per-chunk 4 < dp 8.
        with pytest.raises(SystemExit, match="grad_accum 5"):
            preflight(global_batch_size=32, mesh=mesh, grad_accum=5)
        with pytest.raises(SystemExit, match="per-chunk batch"):
            preflight(global_batch_size=32, mesh=mesh, grad_accum=8)
        preflight(global_batch_size=32, mesh=mesh, grad_accum=2)  # ok


class TestExecuteTraining:
    """The CLI tail: donated-state rebuild on pre-checkpoint crashes."""

    def _make(self, fail_times, latest=None):
        import argparse

        calls = {"fit": 0, "factory": 0, "restore": 0, "placed": 0}

        class FakeTrainer:
            heartbeat = None
            profiler = None
            logger = type("L", (), {"log": staticmethod(lambda m: None)})()
            state = "initial"

            def place_state(self):
                calls["placed"] += 1

            def fit(self, loader, num_epochs, eval_loader=None, start_epoch=0):
                calls["fit"] += 1
                if calls["fit"] <= fail_times:
                    raise RuntimeError("crash")
                return "done"

        class FakeCkpt:
            def latest_epoch(self):
                return latest

            def restore(self, template):
                calls["restore"] += 1
                return "restored"

        def state_factory():
            calls["factory"] += 1
            return "fresh"

        args = argparse.Namespace(num_epochs=5, max_restarts=2)
        return FakeTrainer(), FakeCkpt(), args, state_factory, calls

    def test_precheckpoint_crash_rebuilds_fresh_state(self):
        from deeplearning_mpi_tpu.utils.config import execute_training

        trainer, ckpt, args, factory, calls = self._make(fail_times=1, latest=None)
        # Patch out the restart delay to keep the test fast.
        import deeplearning_mpi_tpu.train.resilience as res
        from unittest import mock

        with mock.patch.object(res.time, "sleep"):
            out = execute_training(
                trainer, ckpt, args, None, None, 0, state_factory=factory
            )
        assert out == "done"
        # crash before any checkpoint: a FRESH state must be built (the old
        # one's buffers were donated), never the deleted one reused
        assert calls["factory"] == 1
        assert trainer.state == "fresh"
        assert calls["placed"] == 1

    def test_postcheckpoint_crash_restores_latest(self):
        import deeplearning_mpi_tpu.train.resilience as res
        from unittest import mock

        from deeplearning_mpi_tpu.utils.config import execute_training

        trainer, ckpt, args, factory, calls = self._make(fail_times=1, latest=3)
        with mock.patch.object(res.time, "sleep"):
            out = execute_training(
                trainer, ckpt, args, None, None, 0, state_factory=factory
            )
        assert out == "done"
        assert calls["restore"] == 1
        assert trainer.state == "restored"
