"""Transformer LM + attention op tests (tiny config, virtual CPU devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM, get_model
from deeplearning_mpi_tpu.ops import dense_attention, lm_cross_entropy
from deeplearning_mpi_tpu.models.transformer import apply_rope


@pytest.fixture(scope="module")
def tiny_model_and_params():
    model = TransformerLM(config=TransformerConfig.tiny(), dtype=jnp.float32)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), tokens)
    return model, params


class TestDenseAttention:
    def test_matches_manual_softmax(self):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(1, 5, 2, 4)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 5, 2, 4)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 5, 2, 4)), jnp.float32)
        out = dense_attention(q, k, v, causal=False)
        scores = np.einsum("bqhd,bkhd->bhqk", q, k) / 2.0  # scale = 4**-0.5
        w = jax.nn.softmax(jnp.asarray(scores), axis=-1)
        expected = np.einsum("bhqk,bkhd->bqhd", w, v)
        np.testing.assert_allclose(out, expected, atol=1e-5)

    def test_causal_mask_blocks_future(self):
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 6, 1, 4)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 6, 1, 4)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 6, 1, 4)), jnp.float32)
        full = dense_attention(q, k, v, causal=True)
        # Changing future keys/values must not change earlier outputs.
        k2 = k.at[:, 4:].set(123.0)
        v2 = v.at[:, 4:].set(-7.0)
        perturbed = dense_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(full[:, :4], perturbed[:, :4], atol=1e-6)
        assert not np.allclose(full[:, 5], perturbed[:, 5])

    def test_fully_future_block_contributes_zero(self):
        """A kv shard entirely in the queries' future must yield exact zeros
        (not a softmax-renormalized uniform average of V)."""
        rng = np.random.default_rng(4)
        q = jnp.asarray(rng.normal(size=(1, 4, 2, 4)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 4, 2, 4)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 4, 2, 4)), jnp.float32)
        out = dense_attention(q, k, v, causal=True, q_offset=0, kv_offset=8)
        np.testing.assert_array_equal(np.asarray(out), 0.0)

    def test_offsets_match_slicing(self):
        """Blockwise calls with offsets reproduce the full causal result."""
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.normal(size=(1, 8, 2, 4)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 8, 2, 4)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 8, 2, 4)), jnp.float32)
        full = dense_attention(q, k, v, causal=True)
        # Second half queries attending over full kv with global positions.
        part = dense_attention(q[:, 4:], k, v, causal=True, q_offset=4)
        np.testing.assert_allclose(full[:, 4:], part, atol=1e-5)


class TestDecodeAttention:
    """The windowed decode step vs the dense whole-buffer-then-mask oracle."""

    def _oracle(self, q, k_buf, v_buf, i):
        from deeplearning_mpi_tpu.ops.attention import NEG_INF

        scale = q.shape[-1] ** -0.5
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_buf, preferred_element_type=jnp.float32
        ) * scale
        valid = jnp.arange(k_buf.shape[1])[None, None, None, :] <= i
        scores = jnp.where(valid, scores, NEG_INF)
        weights = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v_buf.dtype), v_buf)

    # dense_max=0 forces the blockwise walk on these tiny buffers; the
    # default dispatcher sends them down the one-shot masked path (buffers
    # <= DECODE_DENSE_MAX take it — measured faster, ops/attention.py).
    # Parametrizing both pins the two schedules to the same oracle.
    @pytest.mark.parametrize("dense_max", [0, 4096], ids=["windowed", "dense"])
    @pytest.mark.parametrize("index", [0, 1, 7, 8, 19, 31])
    def test_matches_dense_oracle_at_every_fill(self, index, dense_max):
        from deeplearning_mpi_tpu.ops.attention import decode_attention

        rng = np.random.default_rng(index)
        shape = (2, 32, 3, 8)  # [B, max_len, H, D]
        k_buf = jnp.asarray(rng.normal(size=shape), jnp.float32)
        v_buf = jnp.asarray(rng.normal(size=shape), jnp.float32)
        q = jnp.asarray(rng.normal(size=(2, 1, 3, 8)), jnp.float32)
        out = decode_attention(
            q, k_buf, v_buf, jnp.int32(index), block=8, dense_max=dense_max
        )
        ref = self._oracle(q, k_buf, v_buf, index)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_unfilled_blocks_never_read(self):
        # Poison the buffer past the prefix with NaN: the windowed walk must
        # never touch those blocks at all (0*NaN would still be NaN in the
        # accumulator if a poisoned block were scored). A walk-only
        # invariant — the one-shot path reads (and zero-weights) the whole
        # buffer, which is safe for real caches because unfilled rows are
        # zero-initialized, hence dense_max=0 here.
        from deeplearning_mpi_tpu.ops.attention import decode_attention

        rng = np.random.default_rng(0)
        # Poison from the very first unfilled row (prefix = rows 0..7), so
        # even a single extra block read past the prefix surfaces as NaN.
        k_buf = rng.normal(size=(1, 32, 2, 8)).astype(np.float32)
        v_buf = rng.normal(size=(1, 32, 2, 8)).astype(np.float32)
        k_buf[:, 8:] = np.nan
        v_buf[:, 8:] = np.nan
        q = jnp.asarray(rng.normal(size=(1, 1, 2, 8)), jnp.float32)
        out = decode_attention(
            q, jnp.asarray(k_buf), jnp.asarray(v_buf), jnp.int32(7), block=8,
            dense_max=0,
        )
        assert np.all(np.isfinite(np.asarray(out)))

    @pytest.mark.parametrize("index", [3, 15, 16, 20, 23])
    def test_non_dividing_length_clamps_tail(self, index):
        # 24 % 16 != 0: the last block's start clamps back to 8 and re-reads
        # rows 8..15, which the dedup mask must exclude — blocks stay
        # full-size for ANY buffer length instead of shrinking to a divisor.
        from deeplearning_mpi_tpu.ops.attention import decode_attention

        rng = np.random.default_rng(3)
        shape = (1, 24, 2, 8)
        k_buf = jnp.asarray(rng.normal(size=shape), jnp.float32)
        v_buf = jnp.asarray(rng.normal(size=shape), jnp.float32)
        q = jnp.asarray(rng.normal(size=(1, 1, 2, 8)), jnp.float32)
        out = decode_attention(
            q, k_buf, v_buf, jnp.int32(index), block=16, dense_max=0
        )
        ref = self._oracle(q, k_buf, v_buf, index)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_rejects_multi_token_query(self):
        from deeplearning_mpi_tpu.ops.attention import decode_attention

        q = jnp.zeros((1, 2, 2, 8))
        buf = jnp.zeros((1, 8, 2, 8))
        with pytest.raises(ValueError, match="one query token"):
            decode_attention(q, buf, buf, jnp.int32(0))

    def _window_oracle(self, q, k_buf, v_buf, i, window):
        from deeplearning_mpi_tpu.ops.attention import NEG_INF

        scale = q.shape[-1] ** -0.5
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_buf, preferred_element_type=jnp.float32
        ) * scale
        pos = jnp.arange(k_buf.shape[1])[None, None, None, :]
        valid = (pos <= i) & (pos > i - window)
        scores = jnp.where(valid, scores, NEG_INF)
        weights = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v_buf.dtype), v_buf)

    @pytest.mark.parametrize("dense_max", [0, 4096], ids=["walk", "dense"])
    @pytest.mark.parametrize("index", [0, 3, 7, 8, 15, 23, 31])
    def test_sliding_window_matches_oracle(self, index, dense_max):
        """Windowed decode (window 8, block 8): fills below, at, and past
        the window boundary, on both schedules."""
        from deeplearning_mpi_tpu.ops.attention import decode_attention

        rng = np.random.default_rng(index)
        shape = (2, 32, 3, 8)
        k_buf = jnp.asarray(rng.normal(size=shape), jnp.float32)
        v_buf = jnp.asarray(rng.normal(size=shape), jnp.float32)
        q = jnp.asarray(rng.normal(size=(2, 1, 3, 8)), jnp.float32)
        out = decode_attention(
            q, k_buf, v_buf, jnp.int32(index), block=8, dense_max=dense_max,
            window=8,
        )
        ref = self._window_oracle(q, k_buf, v_buf, index, 8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_sliding_window_skips_stale_blocks(self):
        """The walk must START at the window's first block — poison every
        wholly-stale block with NaN; any read of one surfaces as NaN in the
        flash accumulator. This is the O(window)-reads-per-token claim."""
        from deeplearning_mpi_tpu.ops.attention import decode_attention

        rng = np.random.default_rng(0)
        k_buf = rng.normal(size=(1, 32, 2, 8)).astype(np.float32)
        v_buf = rng.normal(size=(1, 32, 2, 8)).astype(np.float32)
        # index 23, window 8 -> window covers 16..23 -> blocks 0 and 1
        # (rows 0..15) are wholly stale; block 3 (rows 24..31) is unfilled.
        k_buf[:, :16] = np.nan
        v_buf[:, :16] = np.nan
        k_buf[:, 24:] = np.nan
        v_buf[:, 24:] = np.nan
        q = jnp.asarray(rng.normal(size=(1, 1, 2, 8)), jnp.float32)
        out = decode_attention(
            q, jnp.asarray(k_buf), jnp.asarray(v_buf), jnp.int32(23),
            block=8, dense_max=0, window=8,
        )
        assert np.all(np.isfinite(np.asarray(out)))
        ref = self._window_oracle(
            q,
            jnp.nan_to_num(jnp.asarray(k_buf)),
            jnp.nan_to_num(jnp.asarray(v_buf)),
            23, 8,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.parametrize("dense_max", [0, 4096], ids=["windowed", "dense"])
    @pytest.mark.parametrize("index", [0, 5, 19, 31])
    def test_gqa_matches_repeated_kv(self, index, dense_max):
        # Grouped buffers consumed natively must equal plain decode over the
        # same buffers repeated to full head count — the repeat_kv ordering
        # (consecutive query heads share kv head h//G) is part of the
        # contract, so a mismatch here is a head-permutation bug.
        from deeplearning_mpi_tpu.ops.attention import decode_attention, repeat_kv

        rng = np.random.default_rng(index)
        k_buf = jnp.asarray(rng.normal(size=(2, 32, 2, 8)), jnp.float32)
        v_buf = jnp.asarray(rng.normal(size=(2, 32, 2, 8)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(2, 1, 4, 8)), jnp.float32)  # H=4, Hkv=2
        out = decode_attention(
            q, k_buf, v_buf, jnp.int32(index), block=8, dense_max=dense_max
        )
        ref = decode_attention(
            q, repeat_kv(k_buf, 2), repeat_kv(v_buf, 2), jnp.int32(index),
            block=8, dense_max=dense_max,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_gqa_rejects_non_dividing_heads(self):
        from deeplearning_mpi_tpu.ops.attention import decode_attention

        q = jnp.zeros((1, 1, 4, 8))
        buf = jnp.zeros((1, 8, 3, 8))
        with pytest.raises(ValueError, match="multiple of KV heads"):
            decode_attention(q, buf, buf, jnp.int32(0))


class TestRoPE:
    def test_rotation_preserves_norm(self):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 7, 2, 8)), jnp.float32)
        pos = jnp.broadcast_to(jnp.arange(7)[None, :], (1, 7))
        rotated = apply_rope(x, pos)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(rotated), axis=-1),
            rtol=1e-5,
        )

    def test_relative_positions_only(self):
        """RoPE attention scores depend only on relative offset."""
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(1, 1, 1, 8)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 1, 1, 8)), jnp.float32)

        def score(q_pos, k_pos):
            qr = apply_rope(q, jnp.array([[q_pos]]))
            kr = apply_rope(k, jnp.array([[k_pos]]))
            return float(jnp.sum(qr * kr))

        assert score(3, 1) == pytest.approx(score(10, 8), rel=1e-4)


class TestTransformerLM:
    def test_forward_shape_and_finite(self, tiny_model_and_params):
        model, params = tiny_model_and_params
        tokens = jnp.ones((2, 16), jnp.int32)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, 256)
        assert logits.dtype == jnp.float32
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_causality_end_to_end(self, tiny_model_and_params):
        model, params = tiny_model_and_params
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 256, (1, 12)), jnp.int32)
        logits = model.apply(params, tokens)
        tokens2 = tokens.at[0, 8:].set(0)
        logits2 = model.apply(params, tokens2)
        np.testing.assert_allclose(logits[0, :8], logits2[0, :8], atol=1e-4)

    def test_untied_head_and_registry(self):
        cfg = TransformerConfig(
            vocab_size=64, num_layers=1, num_heads=2, head_dim=4,
            d_model=8, d_ff=16, tied_embeddings=False,
        )
        model = get_model("transformer", config=cfg, dtype=jnp.float32)
        params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))
        assert "lm_head" in params["params"]
        logits = model.apply(params, jnp.zeros((1, 4), jnp.int32))
        assert logits.shape == (1, 4, 64)

    def test_remat_matches_plain(self):
        cfg = TransformerConfig.tiny()
        tokens = jnp.ones((1, 8), jnp.int32)
        plain = TransformerLM(config=cfg, dtype=jnp.float32)
        remat = TransformerLM(config=cfg, dtype=jnp.float32, remat=True)
        params = plain.init(jax.random.key(0), tokens)
        np.testing.assert_allclose(
            plain.apply(params, tokens), remat.apply(params, tokens), atol=1e-5
        )

    @pytest.mark.slow
    def test_grads_flow_through_loss(self, tiny_model_and_params):
        model, params = tiny_model_and_params
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 256, (2, 16)), jnp.int32
        )

        def loss_fn(p):
            return lm_cross_entropy(model.apply(p, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
        assert all(np.isfinite(n) for n in norms)
        assert any(n > 0 for n in norms)


class TestLMCrossEntropy:
    def test_uniform_logits_give_log_vocab(self):
        logits = jnp.zeros((2, 5, 16))
        tokens = jnp.ones((2, 5), jnp.int32)
        assert float(lm_cross_entropy(logits, tokens)) == pytest.approx(
            np.log(16.0), rel=1e-5
        )

    def test_mask_excludes_padding(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(1, 6, 8)), jnp.float32)
        tokens = jnp.asarray(rng.integers(0, 8, (1, 6)), jnp.int32)
        mask_all = jnp.ones((1, 6))
        unmasked = lm_cross_entropy(logits, tokens, mask_all)
        # Poison the last target; with it masked out the loss must not change.
        poisoned = tokens.at[0, 5].set((int(tokens[0, 5]) + 1) % 8)
        mask = mask_all.at[0, 5].set(0)
        assert float(lm_cross_entropy(logits, poisoned, mask)) == pytest.approx(
            float(lm_cross_entropy(logits, tokens, mask))
        )
        assert float(lm_cross_entropy(logits, tokens, mask)) != pytest.approx(
            float(unmasked)
        )


class TestBHSDLayoutThreading:
    """Attention keys on attention_fn.layout == 'bhsd' to project q/k/v
    straight into the kernel layout; the param tree must stay identical so
    checkpoints interchange between the two layouts."""

    def _models(self):
        from deeplearning_mpi_tpu.ops.pallas import (
            flash_attention,
            flash_attention_bhsd,
        )

        cfg = TransformerConfig(
            vocab_size=64, num_layers=2, num_heads=2, head_dim=16,
            d_model=32, d_ff=64,
        )
        import functools

        bshd = TransformerLM(
            config=cfg, dtype=jnp.float32,
            attention_fn=lambda q, k, v, causal=True: flash_attention(
                q, k, v, causal=causal, block_q=16, block_k=16
            ),
        )
        # functools.partial on purpose: attention_fn_layout must follow the
        # .layout attribute through partial wrappers (a partial treated as
        # BSHD would swap the S/H axes silently).
        fn_bhsd = functools.partial(flash_attention_bhsd, block_q=16, block_k=16)
        return bshd, TransformerLM(
            config=cfg, dtype=jnp.float32, attention_fn=fn_bhsd
        )

    def test_param_trees_identical_and_forward_matches(self):
        bshd, bhsd = self._models()
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 32)), jnp.int32
        )
        p_bshd = bshd.init(jax.random.key(0), tokens)["params"]
        p_bhsd = bhsd.init(jax.random.key(0), tokens)["params"]
        flat_a = jax.tree_util.tree_flatten_with_path(p_bshd)[0]
        flat_b = jax.tree_util.tree_flatten_with_path(p_bhsd)[0]
        assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
        assert [x.shape for _, x in flat_a] == [x.shape for _, x in flat_b]
        # Same seed -> same params (identical init fns); cross-apply: the
        # BHSD model running the BSHD model's params must agree with the
        # BSHD forward to float tolerance.
        out_a = bshd.apply({"params": p_bshd}, tokens)
        out_b = bhsd.apply({"params": p_bshd}, tokens)
        np.testing.assert_allclose(
            np.asarray(out_a), np.asarray(out_b), atol=1e-5
        )

    def test_grads_flow_both_layouts(self):
        from deeplearning_mpi_tpu.ops import lm_cross_entropy

        _, bhsd = self._models()
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (2, 32)), jnp.int32
        )
        params = bhsd.init(jax.random.key(0), tokens)["params"]

        def loss(p):
            return lm_cross_entropy(bhsd.apply({"params": p}, tokens), tokens)

        grads = jax.grad(loss)(params)
        leaves = jax.tree.leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in leaves)
        assert any(float(jnp.abs(g).max()) > 0 for g in leaves)


class TestGQA:
    """Grouped-query attention: K/V projected and cached at num_kv_heads."""

    def _cfg(self, **kw):
        import dataclasses

        base = TransformerConfig(
            vocab_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=8, d_model=32, d_ff=64,
        )
        return dataclasses.replace(base, **kw) if kw else base

    def test_kv_param_shapes_shrink(self):
        model = TransformerLM(config=self._cfg(), dtype=jnp.float32)
        tokens = jnp.zeros((2, 16), jnp.int32)
        params = model.init(jax.random.key(0), tokens)["params"]
        attn = params["layer_0"]["attn"]
        assert attn["q_proj"]["kernel"].shape == (32, 4 * 8)
        assert attn["k_proj"]["kernel"].shape == (32, 2 * 8)
        assert attn["v_proj"]["kernel"].shape == (32, 2 * 8)

    def test_cache_stores_kv_heads_only(self):
        model = TransformerLM(config=self._cfg(), dtype=jnp.float32, decode=True)
        cache = model.init(jax.random.key(0), jnp.zeros((2, 16), jnp.int32))["cache"]
        k = cache["layer_0"]["attn"]["cached_key"]
        assert k.shape == (2, 16, 2, 8)  # Hkv=2, not H=4

    def test_forward_matches_explicit_repeat(self):
        """A GQA forward must equal an MHA forward whose K/V kernels are the
        GQA kernels head-repeated — the repeat-ordering contract end to end."""
        from deeplearning_mpi_tpu.ops.attention import repeat_kv

        cfg = self._cfg()
        gqa = TransformerLM(config=cfg, dtype=jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32
        )
        params = gqa.init(jax.random.key(0), tokens)["params"]

        import dataclasses

        mha = TransformerLM(
            config=dataclasses.replace(cfg, num_kv_heads=None), dtype=jnp.float32
        )
        import flax.core

        rep = flax.core.unfreeze(params)  # plain nested dicts, safe to rebuild
        for layer in ("layer_0", "layer_1"):
            attn = dict(rep[layer]["attn"])
            for name in ("k_proj", "v_proj"):
                kern = attn[name]["kernel"]  # [d_model, Hkv*D]
                grouped = kern.reshape(kern.shape[0], 2, 8)
                attn[name] = {
                    "kernel": repeat_kv(grouped, 2, axis=1).reshape(
                        kern.shape[0], 4 * 8
                    )
                }
            rep[layer] = dict(rep[layer])
            rep[layer]["attn"] = attn
        out_gqa = gqa.apply({"params": params}, tokens)
        out_mha = mha.apply({"params": rep}, tokens)
        np.testing.assert_allclose(
            np.asarray(out_gqa), np.asarray(out_mha), atol=1e-5
        )

    def test_bhsd_layout_matches_bshd(self):
        import functools

        from deeplearning_mpi_tpu.ops.pallas import flash_attention_bhsd

        cfg = self._cfg(head_dim=16)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (2, 32)), jnp.int32
        )
        bshd = TransformerLM(config=cfg, dtype=jnp.float32)
        bhsd = TransformerLM(
            config=cfg, dtype=jnp.float32,
            attention_fn=functools.partial(
                flash_attention_bhsd, block_q=16, block_k=16
            ),
        )
        params = bshd.init(jax.random.key(0), tokens)["params"]
        p_bhsd = bhsd.init(jax.random.key(0), tokens)["params"]
        shapes = lambda p: [  # noqa: E731
            x.shape for x in jax.tree.leaves(p)
        ]
        assert shapes(params) == shapes(p_bhsd)
        np.testing.assert_allclose(
            np.asarray(bshd.apply({"params": params}, tokens)),
            np.asarray(bhsd.apply({"params": params}, tokens)),
            atol=1e-5,
        )

    def test_non_dividing_kv_heads_raises(self):
        cfg = TransformerConfig(
            vocab_size=64, num_layers=1, num_heads=4, num_kv_heads=3,
            head_dim=8, d_model=32, d_ff=64,
        )
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        with pytest.raises(ValueError, match="must divide"):
            model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
