"""ViT classifier: shapes, bidirectionality, training integration, registry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.models import get_model
from deeplearning_mpi_tpu.models.vit import ViT, vit_tiny


def _tiny_vit(**kw):
    kw.setdefault("num_classes", 10)
    kw.setdefault("patch_size", 8)  # 32x32 -> 4x4 = 16 patches + CLS
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("head_dim", 8)
    kw.setdefault("d_model", 16)
    kw.setdefault("d_ff", 32)
    kw.setdefault("dtype", jnp.float32)
    return ViT(**kw)


class TestViT:
    def test_forward_shape_and_finite(self):
        model = _tiny_vit()
        images = jnp.asarray(
            np.random.default_rng(0).normal(size=(2, 32, 32, 3)), jnp.float32
        )
        params = model.init(jax.random.key(0), images)["params"]
        logits = model.apply({"params": params}, images)
        assert logits.shape == (2, 10) and logits.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(logits)))

    def test_attention_is_bidirectional(self):
        """The CLS token sits at position 0; with causal masking it could
        never see any patch and the logits would be input-independent.
        Perturbing the LAST patch must move the logits."""
        model = _tiny_vit()
        rng = np.random.default_rng(1)
        images = jnp.asarray(rng.normal(size=(1, 32, 32, 3)), jnp.float32)
        params = model.init(jax.random.key(0), images)["params"]
        base = np.asarray(model.apply({"params": params}, images))
        perturbed = images.at[:, 24:, 24:, :].add(3.0)  # last patch only
        moved = np.asarray(model.apply({"params": params}, perturbed))
        assert np.max(np.abs(base - moved)) > 1e-4

    def test_resolution_independent_params(self):
        """RoPE positions instead of a learned table: the same params apply
        at a different image size (more patches) without reinit."""
        model = _tiny_vit()
        params = model.init(
            jax.random.key(0), jnp.zeros((1, 32, 32, 3))
        )["params"]
        out = model.apply({"params": params}, jnp.zeros((1, 64, 64, 3)))
        assert out.shape == (1, 10)

    def test_non_dividing_image_raises(self):
        model = _tiny_vit(patch_size=5)
        with pytest.raises(ValueError, match="not divisible"):
            model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))

    def test_train_step_decreases_loss(self):
        from deeplearning_mpi_tpu.train import create_train_state, make_train_step
        from deeplearning_mpi_tpu.train.trainer import build_optimizer

        model = _tiny_vit()
        tx = build_optimizer("adam", 1e-3, clip_norm=1.0)
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((1, 32, 32, 3)), tx
        )
        rng = np.random.default_rng(2)
        batch = {
            "image": jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32),
            "label": jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32),
        }
        step = make_train_step("classification")
        losses = []
        for _ in range(30):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.7, losses[::10]

    def test_registry_builds_and_drops_stem(self):
        model = get_model("vit_tiny", num_classes=10, stem="imagenet",
                          dtype=jnp.float32)
        assert isinstance(model, ViT)
        assert model.d_model == 192

    def test_factory_defaults(self):
        m = vit_tiny()
        assert (m.num_layers, m.num_heads, m.patch_size) == (6, 3, 4)
