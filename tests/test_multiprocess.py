"""Multi-process rendezvous tests: real OS processes over a real transport.

The reference's distributed path is only honestly exercised by running N
actual processes (torchrun spawns them; gloo is the hardware-free transport
— ``pytorch/hello_world/hello_world.py:33-44``, SURVEY.md §4). The
single-process virtual-device mesh the rest of this suite uses never
executes ``jax.distributed.initialize`` (``runtime/bootstrap.py``), the
loader's ``process_count > 1`` sharding, or a multi-host orbax save. These
tests do: the parent spawns N workers which rendezvous at a coordinator, run
hello_world, train 2 DP steps, checkpoint, and dump digests the parent
cross-checks — in two topologies: 2 processes × 2 virtual devices (the
TPU-native one-process-per-host layout) and 4 processes × 1 device (the
reference's torchrun one-process-per-accelerator layout).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "helpers" / "multiprocess_worker.py"
REPO = Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_workers(n: int, out_dir: Path, local_devices: int = 2,
                   timeout: float = 300.0) -> list[dict]:
    port = _free_port()
    # The workers run a script by path, so Python puts tests/helpers/ (not
    # the cwd) on sys.path — the repo root must ride PYTHONPATH explicitly
    # or the package import only works when the ambient environment happens
    # to provide it.
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (str(REPO), env.get("PYTHONPATH", "")) if p
    )
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(WORKER),
                "--coordinator", f"127.0.0.1:{port}",
                "--num_processes", str(n),
                "--process_id", str(i),
                "--local_devices", str(local_devices),
                "--out_dir", str(out_dir),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
            env=env,
        )
        for i in range(n)
    ]
    outputs = [p.communicate(timeout=timeout)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
    return [
        json.loads((out_dir / f"proc{i}.json").read_text()) for i in range(n)
    ]


@pytest.mark.slow
@pytest.mark.multiprocess
@pytest.mark.parametrize(
    "n_procs,local_devices",
    [(2, 2), (4, 1)],
    ids=["2procs_x_2dev", "4procs_x_1dev"],
)
def test_rendezvous_train_and_checkpoint(tmp_path, n_procs, local_devices):
    """N OS processes: rendezvous, hello_world, 2 DP steps with bit-identical
    replicated params, multi-host orbax save/restore.

    The 4×1 shape is the one-process-per-chip layout the reference's
    torchrun uses (one worker per GPU); 2×2 is the TPU-native
    one-process-per-host layout with multiple local devices.
    """
    results = _spawn_workers(n_procs, tmp_path, local_devices=local_devices)
    n_global = n_procs * local_devices

    for i, r in enumerate(results):
        assert r["topology"] == {
            "process_id": i,
            "num_processes": n_procs,
            "global_devices": n_global,
        }
        assert r["hello_world"]["n_devices"] == n_global
        assert r["hello_world"]["broadcast_ok"]
        assert r["hello_world"]["ring_ok"]
        assert r["hello_world"]["psum_ok"]
        assert r["restore_ok"]

    # DDP-parity invariant: after identical-seed init + all-reduced grads,
    # every process holds bit-identical replicated params (the state DDP
    # reaches via construction broadcast + synchronized updates).
    hashes = {r["params_sha256"] for r in results}
    assert len(hashes) == 1
    # And every process observed the same global loss sequence.
    for r in results[1:]:
        assert r["losses"] == pytest.approx(results[0]["losses"])
    assert len(results[0]["losses"]) == 2
