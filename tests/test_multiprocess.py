"""Multi-process rendezvous tests: real OS processes over a real transport.

The reference's distributed path is only honestly exercised by running N
actual processes (torchrun spawns them; gloo is the hardware-free transport
— ``pytorch/hello_world/hello_world.py:33-44``, SURVEY.md §4). The
single-process virtual-device mesh the rest of this suite uses never
executes ``jax.distributed.initialize`` (``runtime/bootstrap.py``), the
loader's ``process_count > 1`` sharding, or a multi-host orbax save. These
tests do: the parent spawns N workers which rendezvous at a coordinator, run
hello_world, train 2 DP steps, checkpoint, and dump digests the parent
cross-checks — in two topologies: 2 processes × 2 virtual devices (the
TPU-native one-process-per-host layout) and 4 processes × 1 device (the
reference's torchrun one-process-per-accelerator layout).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "helpers" / "multiprocess_worker.py"
REPO = Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_workers(n: int, out_dir: Path, local_devices: int = 2,
                   timeout: float = 600.0, mode: str = "dp") -> list[dict]:
    # 600 s: the workers finish in ~60-120 s alone, but this box has ONE CPU
    # core — a concurrent heavy process (another test lane, a training run)
    # stretches 4-worker topologies past 300 s and flaked the 4x1 lane once.
    port = _free_port()
    # The workers run a script by path, so Python puts tests/helpers/ (not
    # the cwd) on sys.path — the repo root must ride PYTHONPATH explicitly
    # or the package import only works when the ambient environment happens
    # to provide it.
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (str(REPO), env.get("PYTHONPATH", "")) if p
    )
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(WORKER),
                "--coordinator", f"127.0.0.1:{port}",
                "--num_processes", str(n),
                "--process_id", str(i),
                "--local_devices", str(local_devices),
                "--out_dir", str(out_dir),
                "--mode", mode,
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
            env=env,
        )
        for i in range(n)
    ]
    outputs = [p.communicate(timeout=timeout)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
    return [
        json.loads((out_dir / f"proc{i}.json").read_text()) for i in range(n)
    ]


@pytest.mark.slow
@pytest.mark.multiprocess
@pytest.mark.parametrize(
    "n_procs,local_devices",
    [(2, 2), (4, 1)],
    ids=["2procs_x_2dev", "4procs_x_1dev"],
)
def test_rendezvous_train_and_checkpoint(tmp_path, n_procs, local_devices):
    """N OS processes: rendezvous, hello_world, 2 DP steps with bit-identical
    replicated params, multi-host orbax save/restore.

    The 4×1 shape is the one-process-per-chip layout the reference's
    torchrun uses (one worker per GPU); 2×2 is the TPU-native
    one-process-per-host layout with multiple local devices.
    """
    results = _spawn_workers(n_procs, tmp_path, local_devices=local_devices)
    n_global = n_procs * local_devices

    for i, r in enumerate(results):
        assert r["topology"] == {
            "process_id": i,
            "num_processes": n_procs,
            "global_devices": n_global,
        }
        assert r["hello_world"]["n_devices"] == n_global
        assert r["hello_world"]["broadcast_ok"]
        assert r["hello_world"]["ring_ok"]
        assert r["hello_world"]["psum_ok"]
        assert r["restore_ok"]

    # DDP-parity invariant: after identical-seed init + all-reduced grads,
    # every process holds bit-identical replicated params (the state DDP
    # reaches via construction broadcast + synchronized updates).
    hashes = {r["params_sha256"] for r in results}
    assert len(hashes) == 1
    # And every process observed the same global loss sequence.
    for r in results[1:]:
        assert r["losses"] == pytest.approx(results[0]["losses"])
    assert len(results[0]["losses"]) == 2


def _worker_module():
    """Import the worker script by path (tests/helpers is not a package) —
    source of the TP_* workload constants shared with the oracle."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("multiprocess_worker", WORKER)
    w = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(w)  # defs + constants only; main() is __main__-guarded
    return w




def _axis_oracle_losses(mode: str) -> list[float]:
    """The shared LM workload's ground truth per mode: tp/sp run it on ONE
    device (dense attention, unsharded — sharding must not change the
    math), ep likewise with unsharded experts, and pp runs the same pp=2
    program on two single-process virtual devices (num_stages shapes the
    param structure, so pipe=1 would be a different init, not an oracle)."""
    import jax
    import jax.numpy as jnp

    w = _worker_module()

    from deeplearning_mpi_tpu.data import ShardedLoader, SyntheticTokens
    from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
    from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh
    from deeplearning_mpi_tpu.train import create_train_state, make_train_step
    from deeplearning_mpi_tpu.train.trainer import build_optimizer

    aux_weight = 0.0
    if mode == "ep":
        mesh = create_mesh(MeshSpec(data=1), devices=jax.devices()[:1])
        cfg = TransformerConfig(**w.TP_LM, moe_experts=2)
        aux_weight = w.AXIS_AUX_WEIGHT
        model = TransformerLM(config=cfg, dtype=jnp.float32)
    elif mode == "pp":
        # pipe=2 on two SINGLE-PROCESS virtual devices: num_stages is an
        # architecture-shaping knob (stage grouping + per-stage init keys),
        # so a pipe=1 model is a *different init*, not an oracle. The claim
        # under test is exactly "crossing the OS-process boundary does not
        # change the math of the same pp=2 program".
        from deeplearning_mpi_tpu.models.pipeline_lm import PipelinedLM

        mesh = create_mesh(MeshSpec(data=1, pipe=2), devices=jax.devices()[:2])
        cfg = TransformerConfig(**w.TP_LM)
        model = PipelinedLM(
            cfg, mesh, num_microbatches=w.PP_MICROBATCHES, dtype=jnp.float32
        )
    else:
        mesh = create_mesh(MeshSpec(data=1), devices=jax.devices()[:1])
        cfg = TransformerConfig(**w.TP_LM)
        model = TransformerLM(config=cfg, dtype=jnp.float32)
    # SGD for pp, matching the worker (see _train_axis's optimizer note);
    # shared PP_OPT constant so the two sides cannot diverge.
    tx = (
        build_optimizer("sgd", w.PP_OPT["lr"], momentum=w.PP_OPT["momentum"])
        if mode == "pp"
        else build_optimizer(
            "adam", w.TP_OPT["lr"], clip_norm=w.TP_OPT["clip_norm"]
        )
    )
    state = create_train_state(
        model, jax.random.key(w.TP_INIT_SEED),
        jnp.zeros((1, w.TP_SEQ_LEN), jnp.int32), tx,
    )
    step_kwargs = {}
    if mode == "pp":
        from deeplearning_mpi_tpu.parallel import shard_state
        from deeplearning_mpi_tpu.parallel.tensor_parallel import (
            infer_state_sharding,
        )

        state = shard_state(state, mesh)
        # Pin output placement like the worker does — without it GSPMD
        # propagation could drift the oracle's placement (and reduction
        # order) away from the run it anchors.
        step_kwargs["state_shardings"] = infer_state_sharding(state, mesh)
    loader = ShardedLoader(
        SyntheticTokens(
            w.TP_DATASET["n"], w.TP_DATASET["seq_len"], seed=w.TP_DATASET["seed"]
        ),
        w.TP_LOADER["batch"], mesh, shuffle=True,
        seed=w.TP_LOADER["shuffle_seed"], num_workers=2,
    )
    step = make_train_step(
        "lm", donate=False, aux_weight=aux_weight, **step_kwargs
    )
    losses = []
    for _, batch in zip(range(w.TP_STEPS), loader.epoch(0)):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    return losses


@pytest.mark.slow
@pytest.mark.multiprocess
@pytest.mark.parametrize("mode", ["sp", "ep", "pp"])
def test_seq_expert_pipe_axes_across_processes(tmp_path, mode):
    """sp (ring attention's ppermute), ep (MoE dispatch), and pp (the GPipe
    stage-to-stage transfers) each spanning 2 OS processes x 1 device —
    with the TP test above this completes the verdict's 'TP/PP/EP/SP across
    an actual process boundary' list.

    Each must reproduce its oracle's loss sequence (tp/sp/ep: one unsharded
    device; pp: the same pp=2 program single-process — see
    _axis_oracle_losses): crossing the process boundary must not change the
    math.
    """
    batch = _worker_module().TP_LOADER["batch"]
    results = _spawn_workers(2, tmp_path, local_devices=1, mode=mode)
    for r in results:
        assert len(r[mode]["losses"]) == 2
        # data axis size 1 => replicated rows: each process supplies all rows.
        assert r[mode]["local_rows"] == batch
    if mode in ("ep", "pp"):
        assert all(r[f"n_{mode}_sharded"] > 0 for r in results)
    for r in results[1:]:
        assert r[mode]["losses"] == pytest.approx(results[0][mode]["losses"])
    oracle = _axis_oracle_losses(mode)
    assert results[0][mode]["losses"] == pytest.approx(oracle, rel=1e-5)


@pytest.mark.slow
@pytest.mark.multiprocess
@pytest.mark.parametrize(
    "n_procs,local_devices",
    [(2, 2), (2, 1)],
    ids=["dp2_x_tp2", "tp2_across_procs"],
)
def test_tensor_parallel_across_processes(tmp_path, n_procs, local_devices):
    """tp=2 meshes spanning real OS processes (round-3 verdict missing #3).

    ``tp2_across_procs`` (2 procs × 1 device, mesh dp1×tp2) is the sharp
    case: the model axis itself crosses the process boundary, so every
    megatron collective rides the transport, each process holds half of
    every sharded kernel (shard digests must DIFFER), the loader's
    replicated-rows path engages (every process supplies all batch rows),
    and orbax saves/restores cross-host sharded leaves. ``dp2_x_tp2`` is
    the verdict's literal topology: TP sharding alongside cross-process DP
    (model axis intra-process ⇒ both processes hold identical local shards).
    Both must reproduce the single-process oracle's loss sequence exactly
    (to f32 reduction-order tolerance).
    """
    results = _spawn_workers(
        n_procs, tmp_path, local_devices=local_devices, mode="tp"
    )
    for r in results:
        tp = r["tp"]
        assert tp["n_tp_sharded"] > 0
        assert tp["restore_ok"]
        assert len(tp["losses"]) == 2

    # Same global loss sequence on every process...
    for r in results[1:]:
        assert r["tp"]["losses"] == pytest.approx(results[0]["tp"]["losses"])
    # ...and equal to the single-process single-device oracle.
    oracle = _axis_oracle_losses("tp")
    assert results[0]["tp"]["losses"] == pytest.approx(oracle, rel=1e-5)

    digests = {r["tp"]["tp_shard_sha256"] for r in results}
    batch = _worker_module().TP_LOADER["batch"]
    dp = n_procs * local_devices // 2  # worker mesh: data = n_devices // 2
    if local_devices == 1:
        # TP across the boundary: each process owns a different kernel half.
        assert len(digests) == n_procs
        # data axis size 1 ⇒ replicated rows: every process supplies ALL rows.
        assert all(
            r["tp"]["local_rows"] == batch // dp for r in results
        ), [r["tp"]["local_rows"] for r in results]
    else:
        # model axis intra-process: local shard 0 is model-half 0 everywhere.
        assert len(digests) == 1
        assert all(r["tp"]["local_rows"] == batch // dp for r in results)


# -- elastic pod drill --------------------------------------------------------

def _pod_drill_module():
    """Import tools/pod_drill.py by path (it is a script, not a package)."""
    import importlib.util

    drill = REPO / "tools" / "pod_drill.py"
    spec = importlib.util.spec_from_file_location("pod_drill", drill)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- serving fleet drill ------------------------------------------------------

def _fleet_drill_module():
    """Import tools/fleet_drill.py by path (script, not a package)."""
    import importlib.util

    drill = REPO / "tools" / "fleet_drill.py"
    spec = importlib.util.spec_from_file_location("fleet_drill", drill)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
@pytest.mark.multiprocess
def test_fleet_survives_kill_and_hang_with_parity(tmp_path):
    """The fleet acceptance drill (``tools/fleet_drill.py``, also ``make
    fleet-smoke``): a 2-replica fleet under a trace burst loses replica 0
    to a replica_kill and replica 1 to a replica_hang; every in-flight
    request must fail over to a survivor and complete bit-identical to
    offline greedy, a rolling weight swap must land under load with zero
    drops and zero post-warmup compiles, and the chaos books must
    reconcile in ``fleet_metrics.jsonl``."""
    out = _fleet_drill_module().run_drill(tmp_path / "drill", "kill_hang")
    assert out["dropped"] == 0
    assert out["restarts"] == 2
    assert out["failures"] == {"replica_kill": 1, "replica_hang": 1}
    assert out["redispatched"] >= 1
    assert out["swap"]["performed"] and out["swap"]["compile_flat"]
    assert out["chaos_balanced"] is True
    assert out["parity_checked"] == out["completed"] > 0


@pytest.mark.slow
@pytest.mark.multiprocess
def test_fleet_hedges_around_slow_replica(tmp_path):
    """A replica_slow-degraded replica must trigger deadline-budgeted
    hedged retries; first-winner-cancels-loser leaves exactly one stream
    per request, still bit-identical to offline greedy, books balanced."""
    out = _fleet_drill_module().run_drill(tmp_path / "drill", "slow")
    assert out["dropped"] == 0
    assert out["restarts"] == 0
    assert out["hedge_total"] >= 1
    assert out["chaos_balanced"] is True
    assert out["parity_checked"] == out["completed"] > 0


# -- fleet autoscaler drill ---------------------------------------------------

def _autoscale_drill_module():
    """Import tools/autoscale_drill.py by path (script, not a package)."""
    import importlib.util

    drill = REPO / "tools" / "autoscale_drill.py"
    spec = importlib.util.spec_from_file_location("autoscale_drill", drill)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
@pytest.mark.multiprocess
def test_autoscaler_scales_2_to_3_to_1_with_parity(tmp_path):
    """Full closed-loop trajectory without chaos: a 2-replica fleet under
    a saturating burst scales up to the 3-replica ceiling, then the
    trickle tail drain-retires twice back to the 1-replica floor — every
    replica spawned supervised (warmup + ready-ack before the router sees
    it), every retire a zero-drop drain, and every completed stream
    bit-identical to offline greedy across BOTH scale events. The scale
    books must reconcile: events == spawned + retired + vetoed."""
    d = _autoscale_drill_module()
    from deeplearning_mpi_tpu.serving.autoscaler import AutoscalerConfig

    autoscale = AutoscalerConfig(
        min_replicas=1,
        max_replicas=3,
        up_load_per_replica=3.0,
        down_load_per_replica=0.25,
        hysteresis_s=0.2,
        cooldown_s=0.8,
    )
    # Burst deep enough that load/replica clears the up-threshold on TWO
    # warm replicas; the trickle tail gives the down-signal repeated calm
    # windows (hysteresis + cooldown per retire) to step 3 -> 2 -> 1. The
    # tail must outlast the scaled-up replica's warmup on a CONTENDED box
    # (a slow spawn holds the fleet at ready=1, which min_replicas vetoes)
    # plus two full drain-retire cycles — hence ~19 s of arrivals.
    entries = d._trace(48, 24, trickle_dt=0.8, max_new=12)
    result = d._run_fleet(
        tmp_path / "drill",
        num_replicas=2,
        autoscale=autoscale,
        chaos=None,
        entries=entries,
    )

    s = result.scale
    assert s["spawned"] >= 1, f"never scaled up: {s}"
    assert s["retired"] >= 2, f"expected two drain-retires: {s}"
    assert s["replicas_final"] == 1, s
    assert s["events"] == s["spawned"] + s["retired"] + s["vetoed"], s
    assert result.dropped == 0
    assert result.restarts == 0  # no chaos: every exit is commanded
    checked = d._check_parity(result)
    shed = sum(result.shed.values())
    assert checked == result.completed == len(entries) - shed > 0


@pytest.mark.slow
@pytest.mark.multiprocess
@pytest.mark.parametrize("fault", ["rank_kill", "rank_hang"])
def test_pod_survives_rank_failure_bit_identical(tmp_path, fault):
    """The PR-level acceptance drill (``tools/pod_drill.py``, also ``make
    pod-smoke``): a 2-process pod loses rank 1 mid-epoch-1 — killed outright
    or wedged with its heartbeat daemon still beating — and the supervisor
    must detect it (exit code vs. progress-stall culprit analysis), re-form
    a world of 1, and resume from the epoch-0 checkpoint onto a loss
    trajectory BIT-IDENTICAL to a clean single-process from-checkpoint run,
    with the chaos books reconciling in ``pod_metrics.jsonl``."""
    out = _pod_drill_module().run_drill(tmp_path / "drill", fault)
    assert out["world_sizes"] == [2, 1]
    assert out["restarts"] == 1
    assert out["rank_failures"] == 1
    assert out["steps_compared"] >= 12  # epochs 1-3 x 4 steps
    assert out["chaos_balanced"] is True


# -- distributed tracing drill ------------------------------------------------

def _trace_drill_module():
    """Import tools/trace_drill.py by path (script, not a package)."""
    import importlib.util

    drill = REPO / "tools" / "trace_drill.py"
    spec = importlib.util.spec_from_file_location("trace_drill", drill)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
@pytest.mark.multiprocess
def test_trace_stitches_request_across_fleet_processes(tmp_path):
    """Cross-process correlation e2e (``tools/trace_drill.py``, also
    ``make trace-smoke``): a traced 2-replica disaggregated fleet loses
    replica 0 to a chaos kill, and the merged per-process JSONL must
    stitch every completed request end to end — the supervisor's dispatch
    event and stream span joined to the worker's queue / prefill /
    handoff / decode spans by the fleet-wide ``r<rid>`` trace key — with
    the phase spans covering TTLT within 5%, zero orphan spans, and the
    killed replica's flight dump on disk."""
    out = _trace_drill_module().run_fleet_trace(tmp_path / "drill")
    assert out["completed"] > 0
    assert out["worst_coverage"] >= 0.95
    # supervisor + both replicas + the respawned attempt, each its own file
    assert out["trace_files"] >= 4
    assert Path(out["flight_dump"]).is_file()


@pytest.mark.slow
@pytest.mark.multiprocess
def test_traced_training_attributes_step_phases(tmp_path):
    """A traced training run must tile every step into
    data_wait/h2d/compute/collective_tail spans whose epoch totals close
    to the measured wall-clock exactly (the "other" residual), with
    mfu_gap decomposed into named phase shares."""
    out = _trace_drill_module().run_train_trace(tmp_path / "drill")
    assert out["steps"] == 4
    assert out["phase_sum_s"] == pytest.approx(out["duration_s"], rel=1e-6)


# -- control-plane crash drill ------------------------------------------------

def _controlplane_drill_module():
    """Import tools/controlplane_drill.py by path (script, not a package)."""
    import importlib.util

    drill = REPO / "tools" / "controlplane_drill.py"
    spec = importlib.util.spec_from_file_location("controlplane_drill", drill)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
@pytest.mark.multiprocess
def test_supervisor_sigkill_readopt_with_parity(tmp_path):
    """The control-plane crash drill (``tools/controlplane_drill.py``,
    also ``make controlplane-smoke``): the incarnation-1 FleetSupervisor
    SIGKILLs ITSELF mid-surge via its own chaos plan (load_spike
    absorbed, a scale-up replica still warming), the harness kills one
    orphaned worker to prove the probe discriminates, and the restarted
    incarnation-2 supervisor replays the write-ahead journal, re-adopts
    the live replicas without respawning them (serve_compile_total flat
    — zero retraces), respawns the corpse, re-dispatches the victim's
    in-flight requests at their original arrival/deadline, and drains
    with zero drops, every stream bit-identical to offline greedy, chaos
    + scale books reconciling across both incarnations."""
    out = _controlplane_drill_module().run_drill(tmp_path / "drill")
    assert out["incarnation"] >= 2
    assert out["readopted"] >= 1
    assert out["respawned"] >= 1
    assert out["redispatched"] >= 1
    assert out["dropped"] == 0
    assert out["compile_flat"] is True
    assert out["chaos_balanced"] is True
    assert out["parity_checked"] == out["completed"] > 0
