"""Multi-process rendezvous tests: real OS processes over a real transport.

The reference's distributed path is only honestly exercised by running N
actual processes (torchrun spawns them; gloo is the hardware-free transport
— ``pytorch/hello_world/hello_world.py:33-44``, SURVEY.md §4). The
single-process virtual-device mesh the rest of this suite uses never
executes ``jax.distributed.initialize`` (``runtime/bootstrap.py``), the
loader's ``process_count > 1`` sharding, or a multi-host orbax save. These
tests do: the parent spawns 2 workers (each with 2 virtual CPU devices → a
4-device global mesh), which rendezvous at a coordinator, run hello_world,
train 2 DP steps, checkpoint, and dump digests the parent cross-checks.
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "helpers" / "multiprocess_worker.py"
REPO = Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_workers(n: int, out_dir: Path, local_devices: int = 2,
                   timeout: float = 300.0) -> list[dict]:
    port = _free_port()
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(WORKER),
                "--coordinator", f"127.0.0.1:{port}",
                "--num_processes", str(n),
                "--process_id", str(i),
                "--local_devices", str(local_devices),
                "--out_dir", str(out_dir),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
        )
        for i in range(n)
    ]
    outputs = [p.communicate(timeout=timeout)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
    return [
        json.loads((out_dir / f"proc{i}.json").read_text()) for i in range(n)
    ]


@pytest.mark.slow
@pytest.mark.multiprocess
def test_two_process_rendezvous_train_and_checkpoint(tmp_path):
    """2 processes × 2 virtual devices: rendezvous, hello_world, 2 DP steps
    with bit-identical replicated params, multi-host orbax save/restore."""
    results = _spawn_workers(2, tmp_path)

    for i, r in enumerate(results):
        assert r["topology"] == {
            "process_id": i, "num_processes": 2, "global_devices": 4,
        }
        assert r["hello_world"]["n_devices"] == 4
        assert r["hello_world"]["broadcast_ok"]
        assert r["hello_world"]["ring_ok"]
        assert r["hello_world"]["psum_ok"]
        assert r["restore_ok"]

    # DDP-parity invariant: after identical-seed init + all-reduced grads,
    # every process holds bit-identical replicated params (the state DDP
    # reaches via construction broadcast + synchronized updates).
    assert results[0]["params_sha256"] == results[1]["params_sha256"]
    # And both processes observed the same global loss sequence.
    assert results[0]["losses"] == pytest.approx(results[1]["losses"])
    assert len(results[0]["losses"]) == 2
