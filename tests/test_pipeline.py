"""Pipeline parallelism: GPipe schedule + pipelined LM vs the dense oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.models.pipeline_lm import PipelinedLM
from deeplearning_mpi_tpu.parallel import (
    merge_microbatches,
    pipeline_apply,
    shard_state,
    split_microbatches,
)
from deeplearning_mpi_tpu.runtime.mesh import MeshSpec, create_mesh


def pipe_mesh(pipe=4, data=2):
    return create_mesh(MeshSpec(data=data, pipe=pipe))


class TestMicrobatchSplit:
    def test_roundtrip(self):
        x = {"a": jnp.arange(24.0).reshape(8, 3)}
        split = split_microbatches(x, 4)
        assert split["a"].shape == (4, 2, 3)
        np.testing.assert_array_equal(merge_microbatches(split)["a"], x["a"])

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="divisible"):
            split_microbatches({"a": jnp.zeros((6, 2))}, 4)


class TestPipelineApply:
    def test_matches_sequential_stages(self):
        """4 pipelined affine stages == applying them in sequence."""
        mesh = pipe_mesh(pipe=4, data=2)
        rng = np.random.default_rng(0)
        S, d = 4, 8
        w = jnp.asarray(rng.normal(size=(S, d, d)) * 0.3, jnp.float32)
        b = jnp.asarray(rng.normal(size=(S, d)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(16, d)), jnp.float32)

        def stage_fn(p, acts):
            return {"x": jnp.tanh(acts["x"] @ p["w"] + p["b"])}

        xs = split_microbatches({"x": x}, 8)
        out = merge_microbatches(
            pipeline_apply(stage_fn, {"w": w, "b": b}, xs, mesh=mesh)
        )["x"]

        expected = x
        for s in range(S):
            expected = jnp.tanh(expected @ w[s] + b[s])
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)

    @pytest.mark.slow
    def test_grads_match_sequential(self):
        mesh = pipe_mesh(pipe=4, data=2)
        rng = np.random.default_rng(1)
        S, d = 4, 4
        w = jnp.asarray(rng.normal(size=(S, d, d)) * 0.3, jnp.float32)
        x = jnp.asarray(rng.normal(size=(8, d)), jnp.float32)

        def stage_fn(p, acts):
            return {"x": jnp.tanh(acts["x"] @ p["w"])}

        def loss_pipe(w):
            xs = split_microbatches({"x": x}, 4)
            out = pipeline_apply(stage_fn, {"w": w}, xs, mesh=mesh)
            return jnp.sum(merge_microbatches(out)["x"] ** 2)

        def loss_seq(w):
            y = x
            for s in range(S):
                y = jnp.tanh(y @ w[s])
            return jnp.sum(y**2)

        g_pipe = jax.grad(loss_pipe)(w)
        g_seq = jax.grad(loss_seq)(w)
        np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq), atol=1e-4)

    def test_single_stage_mesh_degenerates(self):
        mesh = create_mesh(MeshSpec(data=8))
        w = jnp.full((1, 3, 3), 2.0)
        x = jnp.ones((4, 3))

        def stage_fn(p, acts):
            return {"x": acts["x"] @ p["w"]}

        out = pipeline_apply(
            stage_fn, {"w": w}, split_microbatches({"x": x}, 2), mesh=mesh
        )
        np.testing.assert_allclose(merge_microbatches(out)["x"], x @ w[0])

    def test_multi_stage_stack_on_unpipelined_mesh(self):
        """An S>1 stage stack on a pipe=1 mesh runs the stack sequentially —
        a pipelined model works unchanged on an unpipelined mesh."""
        mesh = create_mesh(MeshSpec(data=8))
        rng = np.random.default_rng(4)
        w = jnp.asarray(rng.normal(size=(3, 4, 4)) * 0.3, jnp.float32)
        x = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)

        def stage_fn(p, acts):
            return {"x": jnp.tanh(acts["x"] @ p["w"])}

        out = merge_microbatches(
            pipeline_apply(stage_fn, {"w": w}, split_microbatches({"x": x}, 4), mesh=mesh)
        )["x"]
        expected = x
        for s in range(3):
            expected = jnp.tanh(expected @ w[s])
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)

    def test_wrong_stack_size_raises(self):
        mesh = pipe_mesh(pipe=4, data=2)
        with pytest.raises(ValueError, match="stacked"):
            pipeline_apply(
                lambda p, a: a, {"w": jnp.zeros((3, 2))},
                split_microbatches({"x": jnp.zeros((4, 2))}, 2), mesh=mesh,
            )


class TestPipelinedLM:
    @pytest.mark.slow
    def test_matches_dense_transformer(self):
        """PipelinedLM(S=2 stages) == TransformerLM with the same weights,
        remapped stages[block_j][s] -> layer_{s*K+j}."""
        mesh = pipe_mesh(pipe=2, data=4)
        cfg = TransformerConfig.tiny()  # 2 layers -> 2 stages of 1 block
        pipelined = PipelinedLM(
            cfg, mesh, num_microbatches=2, dtype=jnp.float32
        )
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16)), jnp.int32
        )
        variables = pipelined.init(jax.random.key(0), tokens)

        # Rebuild the equivalent dense model params from the pipelined tree.
        p = variables["params"]
        blocks_per_stage = cfg.num_layers // 2
        dense_params = {
            "embed": p["embed_head"]["embed"],
            "final_norm": p["embed_head"]["final_norm"],
        }
        for s in range(2):
            for j in range(blocks_per_stage):
                dense_params[f"layer_{s * blocks_per_stage + j}"] = jax.tree.map(
                    lambda leaf: leaf[s], p["stages"][f"block_{j}"]
                )
        dense = TransformerLM(config=cfg, dtype=jnp.float32)
        expected = dense.apply({"params": dense_params}, tokens)

        got = jax.jit(pipelined.apply)(variables, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-4)

    @pytest.mark.slow
    def test_trains_with_trainer(self, mesh=None):
        from deeplearning_mpi_tpu.data import ShardedLoader, SyntheticTokens
        from deeplearning_mpi_tpu.train import Trainer, create_train_state
        from deeplearning_mpi_tpu.train.trainer import build_optimizer

        mesh = pipe_mesh(pipe=2, data=4)
        cfg = TransformerConfig.tiny()
        model = PipelinedLM(cfg, mesh, num_microbatches=2, dtype=jnp.float32)
        tx = build_optimizer("adam", 1e-2, clip_norm=1.0)
        state = create_train_state(
            model, jax.random.key(0), jnp.zeros((8, 32), jnp.int32), tx
        )
        trainer = Trainer(state, "lm", mesh)
        trainer.place_state()
        # stage stacks land on the pipe axis
        stage_leaf = trainer.state.params["stages"]["block_0"]["attn"]["q_proj"]["kernel"]
        assert stage_leaf.sharding.spec[0] == "pipe"
        loader = ShardedLoader(
            SyntheticTokens(32, 32, seed=0), 16, mesh, shuffle=True, seed=0
        )
        stats = [trainer.run_epoch(loader, e) for e in range(3)]
        assert np.isfinite(stats[0]["loss"])
        assert stats[-1]["loss"] < stats[0]["loss"]

    @pytest.mark.slow
    def test_moe_matches_flat_moe(self):
        """PP+MoE: logits equal the flat MoE LM with remapped weights, and the
        pipelined aux loss equals the mean of the flat model's per-microbatch
        aux (routing statistics are per batch row, so microbatching does not
        change them)."""
        from deeplearning_mpi_tpu.models.moe import AUX_COLLECTION, collect_aux_loss

        mesh = pipe_mesh(pipe=2, data=4)
        cfg = TransformerConfig.tiny_moe()
        num_micro = 2
        pipelined = PipelinedLM(
            cfg, mesh, num_microbatches=num_micro, dtype=jnp.float32
        )
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(0, cfg.vocab_size, (4, 16)), jnp.int32
        )
        variables = pipelined.init(jax.random.key(0), tokens)

        p = variables["params"]
        blocks_per_stage = cfg.num_layers // 2
        dense_params = {
            "embed": p["embed_head"]["embed"],
            "final_norm": p["embed_head"]["final_norm"],
        }
        for s in range(2):
            for j in range(blocks_per_stage):
                dense_params[f"layer_{s * blocks_per_stage + j}"] = jax.tree.map(
                    lambda leaf: leaf[s], p["stages"][f"block_{j}"]
                )
        flat = TransformerLM(config=cfg, dtype=jnp.float32)
        expected = flat.apply({"params": dense_params}, tokens)

        got, mutated = jax.jit(
            lambda v, t: pipelined.apply(v, t, mutable=[AUX_COLLECTION])
        )(variables, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-4)

        # Aux oracle: the flat model applied per microbatch, averaged.
        mb = tokens.reshape(num_micro, -1, tokens.shape[1])
        aux_ref = np.mean([
            float(collect_aux_loss(
                flat.apply({"params": dense_params}, mb[i], mutable=[AUX_COLLECTION])[1]
            ))
            for i in range(num_micro)
        ])
        aux_got = float(collect_aux_loss(mutated))
        assert aux_got > 0.0
        np.testing.assert_allclose(aux_got, aux_ref, rtol=1e-5)

    def test_moe_drop_metric_threads_through_pipeline(self):
        """The dropped-token fraction must survive the scan/ppermute schedule
        like the aux loss does (review r5: it was silently discarded), and
        equal the flat model's per-microbatch mean; dense pipelines emit no
        metric."""
        from deeplearning_mpi_tpu.models.moe import (
            METRIC_COLLECTION,
            collect_dropped_fraction,
        )

        mesh = pipe_mesh(pipe=2, data=4)
        cfg = TransformerConfig.tiny_moe()
        num_micro = 2
        pipelined = PipelinedLM(
            cfg, mesh, num_microbatches=num_micro, dtype=jnp.float32
        )
        tokens = jnp.asarray(
            np.random.default_rng(5).integers(0, cfg.vocab_size, (4, 16)),
            jnp.int32,
        )
        variables = pipelined.init(jax.random.key(1), tokens)
        _, mutated = pipelined.apply(
            variables, tokens, mutable=[METRIC_COLLECTION]
        )
        drop = collect_dropped_fraction(mutated)
        assert drop is not None and 0.0 <= float(drop) <= 1.0

        # Oracle: flat model with remapped weights, per-microbatch mean.
        p = variables["params"]
        blocks_per_stage = cfg.num_layers // 2
        dense_params = {
            "embed": p["embed_head"]["embed"],
            "final_norm": p["embed_head"]["final_norm"],
        }
        for s in range(2):
            for j in range(blocks_per_stage):
                dense_params[f"layer_{s * blocks_per_stage + j}"] = jax.tree.map(
                    lambda leaf: leaf[s], p["stages"][f"block_{j}"]
                )
        flat = TransformerLM(config=cfg, dtype=jnp.float32)
        mb = tokens.reshape(num_micro, -1, tokens.shape[1])
        ref = np.mean([
            float(collect_dropped_fraction(
                flat.apply(
                    {"params": dense_params}, mb[i],
                    mutable=[METRIC_COLLECTION],
                )[1]
            ))
            for i in range(num_micro)
        ])
        np.testing.assert_allclose(float(drop), ref, rtol=1e-5)

        # Dense pipeline: no metric collection in the mutated dict.
        dense_cfg = TransformerConfig.tiny()
        dense_pipe = PipelinedLM(
            dense_cfg, mesh, num_microbatches=num_micro, dtype=jnp.float32
        )
        dvars = dense_pipe.init(jax.random.key(2), tokens)
        _, dmut = dense_pipe.apply(dvars, tokens, mutable=[METRIC_COLLECTION])
        assert collect_dropped_fraction(dmut) is None

    @pytest.mark.slow
    def test_moe_router_gets_aux_gradient(self):
        """The aux loss must backpropagate through the pipeline to the router
        weights — the whole point of threading it through the schedule."""
        from deeplearning_mpi_tpu.models.moe import AUX_COLLECTION, collect_aux_loss

        mesh = pipe_mesh(pipe=2, data=4)
        cfg = TransformerConfig.tiny_moe()
        pipelined = PipelinedLM(cfg, mesh, num_microbatches=2, dtype=jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(3).integers(0, cfg.vocab_size, (4, 16)), jnp.int32
        )
        variables = pipelined.init(jax.random.key(1), tokens)

        def aux_only(params):
            _, mutated = pipelined.apply(
                {"params": params}, tokens, mutable=[AUX_COLLECTION]
            )
            return collect_aux_loss(mutated)

        grads = jax.grad(aux_only)(variables["params"])
        router_g = grads["stages"]["block_0"]["mlp"]["router"]["kernel"]
        assert float(jnp.max(jnp.abs(router_g))) > 0.0
