"""Weight-only int8 quantization: conversion bounds, QuantDense math,
quantized-model quality, and decode parity within the quantized model."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_mpi_tpu.models import TransformerConfig, TransformerLM
from deeplearning_mpi_tpu.ops.quant import (
    QuantDense,
    quantize_array,
    quantize_lm_params,
)


def _tiny_lm(**cfg_kw):
    cfg = dataclasses.replace(TransformerConfig.tiny(), **cfg_kw)
    model = TransformerLM(config=cfg, dtype=jnp.float32)
    params = model.init(jax.random.key(0), jnp.zeros((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


class TestQuantizeArray:
    def test_error_bounded_by_half_scale(self):
        w = jnp.asarray(np.random.default_rng(0).normal(size=(64, 32)), jnp.float32)
        q, scale = quantize_array(w)
        assert q.dtype == jnp.int8 and scale.shape == (32,)
        err = np.abs(np.asarray(w) - np.asarray(q, np.float32) * np.asarray(scale))
        assert np.all(err <= np.asarray(scale) / 2 + 1e-7)

    def test_extremes_map_to_127(self):
        w = jnp.asarray([[1.0, -3.0], [-1.0, 3.0]], jnp.float32)
        q, scale = quantize_array(w)
        np.testing.assert_array_equal(np.abs(np.asarray(q)), 127)
        np.testing.assert_allclose(np.asarray(scale), [1 / 127, 3 / 127])

    def test_zero_column_safe(self):
        w = jnp.zeros((8, 4), jnp.float32)
        q, scale = quantize_array(w)
        assert np.all(np.asarray(q) == 0) and np.all(np.asarray(scale) > 0)


class TestQuantDense:
    def test_matches_dequantized_matmul(self):
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
        q, scale = quantize_array(w)
        module = QuantDense(8, jnp.float32)
        out = module.apply({"params": {"kernel": q, "scale": scale}}, x)
        ref = x @ (q.astype(jnp.float32) * scale)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )


class TestQuantizedLM:
    def test_conversion_tree_shape(self):
        _, params = _tiny_lm()
        qparams = quantize_lm_params(params)
        attn = qparams["layer_0"]["attn"]
        for name in ("q_proj", "k_proj", "v_proj", "out_proj"):
            assert attn[name]["kernel"].dtype == jnp.int8
            assert attn[name]["scale"].dtype == jnp.float32
        mlp = qparams["layer_0"]["mlp"]
        for name in ("gate_proj", "up_proj", "down_proj"):
            assert mlp[name]["kernel"].dtype == jnp.int8
        # Embeddings and norms pass through untouched.
        assert qparams["embed"]["embedding"].dtype == params["embed"]["embedding"].dtype
        assert qparams["final_norm"]["scale"].dtype == jnp.float32

    def test_quantized_logits_track_dense(self):
        """int8 weights must stay close to the full-precision model: high
        top-1 agreement and bounded logit drift on random data."""
        model, params = _tiny_lm()
        qmodel = dataclasses.replace(model, quantized=True)
        qparams = quantize_lm_params(params)
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(0, 256, (4, 32)), jnp.int32
        )
        dense = np.asarray(model.apply({"params": params}, tokens))
        quant = np.asarray(qmodel.apply({"params": qparams}, tokens))
        agree = np.mean(dense.argmax(-1) == quant.argmax(-1))
        assert agree >= 0.9, f"top-1 agreement {agree:.3f}"
        # Drift bounded relative to the logit spread, not absolutely.
        spread = dense.max() - dense.min()
        assert np.max(np.abs(dense - quant)) <= 0.1 * spread

    def test_stepwise_decode_matches_quantized_forward(self):
        """The decode-parity invariant holds WITHIN the quantized model —
        cache + windowed decode introduce no error beyond quantization."""
        seq = 12
        model, params = _tiny_lm()
        qmodel = dataclasses.replace(model, quantized=True)
        qparams = quantize_lm_params(params)
        tokens = jnp.asarray(
            np.random.default_rng(3).integers(0, 256, (2, seq)), jnp.int32
        )
        full = qmodel.apply({"params": qparams}, tokens)
        decode_model = dataclasses.replace(qmodel, decode=True)
        cache = decode_model.init(
            jax.random.key(0), jnp.zeros((2, seq), jnp.int32)
        )["cache"]
        for i in range(seq):
            step, mutated = decode_model.apply(
                {"params": qparams, "cache": cache},
                tokens[:, i : i + 1],
                positions=jnp.full((2, 1), i, jnp.int32),
                mutable=["cache"],
            )
            cache = mutated["cache"]
            np.testing.assert_allclose(
                np.asarray(step[:, 0]), np.asarray(full[:, i]), atol=2e-4
            )

    def test_moe_quantized_refused(self):
        cfg = TransformerConfig.tiny_moe()
        model = TransformerLM(config=cfg, dtype=jnp.float32, quantized=True)
        with pytest.raises(ValueError, match="dense SwiGLU"):
            model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))

    def test_bhsd_quantized_refused(self):
        import functools

        from deeplearning_mpi_tpu.ops.pallas import flash_attention_bhsd

        model, _ = _tiny_lm()
        qmodel = dataclasses.replace(
            model, quantized=True,
            attention_fn=functools.partial(flash_attention_bhsd),
        )
        with pytest.raises(ValueError, match="BSHD path only"):
            qmodel.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))

    def test_gqa_composes_with_quantization(self):
        # Both decode levers together: grouped KV cache + int8 weights.
        model, params = _tiny_lm(num_heads=4, num_kv_heads=2)
        qmodel = dataclasses.replace(model, quantized=True)
        qparams = quantize_lm_params(params)
        tokens = jnp.asarray(
            np.random.default_rng(4).integers(0, 256, (2, 16)), jnp.int32
        )
        out = qmodel.apply({"params": qparams}, tokens)
        assert np.all(np.isfinite(np.asarray(out)))
        assert qparams["layer_0"]["attn"]["k_proj"]["kernel"].shape == (32, 2 * 8)


class TestInt8KVCache:
    """Activation (KV) quantization for the paged serving cache: per-
    (token, head) absmax scales over head_dim, dequantized in-gather."""

    def test_roundtrip_error_bounded_by_half_scale(self):
        from deeplearning_mpi_tpu.ops.quant import dequantize_kv, quantize_kv

        x = jnp.asarray(
            np.random.default_rng(5).normal(size=(3, 8, 2, 16)), jnp.float32
        )
        q, scale = quantize_kv(x)
        assert q.dtype == jnp.int8
        assert scale.shape == x.shape[:-1]  # one scale per (token, head) row
        deq = np.asarray(dequantize_kv(q, scale, jnp.float32))
        err = np.abs(np.asarray(x) - deq)
        assert np.all(err <= np.asarray(scale)[..., None] / 2 + 1e-7)

    def test_extreme_values_saturate_at_127(self):
        from deeplearning_mpi_tpu.ops.quant import quantize_kv

        x = jnp.asarray([[4.0, -2.0, 1.0, -4.0]], jnp.float32)
        q, scale = quantize_kv(x)
        assert int(np.abs(np.asarray(q)).max()) == 127
        np.testing.assert_allclose(np.asarray(scale), [4.0 / 127.0])

    def test_zero_rows_safe(self):
        from deeplearning_mpi_tpu.ops.quant import dequantize_kv, quantize_kv

        x = jnp.zeros((4, 2, 8), jnp.float32)
        q, scale = quantize_kv(x)
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.asarray(scale) > 0)  # clamped, never divides by 0
        assert np.all(np.asarray(dequantize_kv(q, scale, jnp.float32)) == 0)

    def test_engine_decode_parity_at_tolerance(self):
        """int8 KV is lossy by design; the contract is MEASURED token-level
        acceptance against the fp engine on the same trace, mirroring the
        serve_lm --kv_dtype int8 selftest gate. The fp run itself stays
        bit-identical to offline greedy (the default path is untouched)."""
        from deeplearning_mpi_tpu.models.generate import generate
        from deeplearning_mpi_tpu.serving import EngineConfig, ServingEngine

        cfg = TransformerConfig.tiny()
        model = TransformerLM(config=cfg, dtype=jnp.float32)
        params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
        rng = np.random.default_rng(9)
        prompts = [
            rng.integers(1, 255, size=n).astype(np.int32) for n in (5, 11, 3)
        ]
        max_new = 5
        ecfg = EngineConfig(
            max_slots=3, block_size=4, num_blocks=32, max_blocks_per_seq=8,
            prefill_chunk=4,
        )

        def run(kv_dtype):
            engine = ServingEngine(
                cfg, params,
                dataclasses.replace(ecfg, kv_dtype=kv_dtype),
                dtype=jnp.float32,
            )
            reqs = [engine.submit(p, max_new) for p in prompts]
            engine.run_until_idle()
            assert engine.pool.quantized == (kv_dtype is not None)
            engine.pool.check()
            assert engine.pool.in_use == 0
            return [r.generated for r in reqs]

        fp_tokens = run(None)
        int8_tokens = run("int8")
        for p, fp in zip(prompts, fp_tokens):
            out = generate(
                model, params, jnp.asarray(p)[None], max_new_tokens=max_new,
                rng=jax.random.key(1), temperature=0.0,
            )
            assert fp == np.asarray(out)[0, len(p):].tolist()
        expected = sum(len(t) for t in fp_tokens)
        accepted = 0
        for fp, q8 in zip(fp_tokens, int8_tokens):
            for a, b in zip(fp, q8):
                if a != b:
                    break
                accepted += 1
        acceptance = accepted / expected
        assert acceptance >= 0.9, (
            f"int8 KV acceptance {acceptance:.1%} "
            f"({accepted}/{expected} tokens) below tolerance; "
            f"fp={fp_tokens} int8={int8_tokens}"
        )
